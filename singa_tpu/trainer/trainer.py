"""The training engine.

Replaces the reference's worker-side stack — Worker::Start/Run/RunOneBatch
(src/worker/worker.cc:14-106,187-213), Executor::TrainOneBatch (:304-316),
and ParamManager's init/update machinery (src/worker/param_manager.cc) —
with one `jit`-compiled, sharded XLA train step driven by a plain Python
cadence loop. The Forward/Backward hot loops (worker.cc:240-302), the
per-param WaitUpdate blocking, the bridge spins, and the PS sync sends all
dissolve into that single program; gradient sync across the data-parallel
mesh axis is the psum GSPMD inserts because the loss is a mean over the
sharded batch dim.

Cadence semantics match the reference's predicates exactly
(include/worker/worker.h:118-158): XNow(step) = freq > 0 and
step >= after and (step - after) % freq == 0; tests/validation run *before*
the train step of the step they trigger on (worker.cc:190-200).
"""

from __future__ import annotations

import os
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..config.schema import ClusterConfig, ConfigError, ModelConfig
from ..data.pipeline import BatchPipeline
from ..graph.builder import Net, active_phases, build_net
from ..optim import make_updater
from ..parallel import (
    batch_shardings,
    mesh_from_cluster,
    param_paddings,
    param_shardings,
    replicated,
    state_shardings,
    zero_update_shardings,
)
from ..params import init_params
from ..resilience.guard import (
    GUARD_BAD,
    GUARD_CONSEC,
    GUARD_LR,
    GuardSpec,
    grad_norm_sq,
    guarded_step,
    init_guard_buffers,
)
from ..utils import Performance, Timers, dump_net_json
from .checkpoint import (
    load_stream_positions,
    restore_into,
    save_checkpoint,
)


def _now(step: int, freq: int, after: int) -> bool:
    """The reference's {Display,Test,Validate}Now predicate (worker.h:118-158)."""
    return freq > 0 and step >= after and (step - after) % freq == 0


class Trainer:
    """Builds nets, owns params/updater state, runs the cadence loop."""

    #: subclasses whose step shape is incompatible with on-device batch
    #: gathering switch this off
    _allow_device_cache = True
    #: subclasses that do not thread buffer state (the CD trainer)
    #: reject nets with stateful layers instead of silently dropping them
    _supports_buffers = True
    #: stream batches consumed per train step (the replica trainer feeds
    #: one batch per replica)
    _batches_per_step = 1
    #: engines whose update layout is their own (the replica protocol's
    #: (R, ...)-stacked slots) reject zero_update instead of silently
    #: running it replicated
    _supports_zero_update = True
    #: engines whose gradient sync is their own protocol (the replica
    #: engine's EASGD rounds) reject an active grad_comm block instead
    #: of silently skipping the quantize/overlap machinery
    _supports_grad_comm = True
    #: engines whose step can run the backward per data shard inside the
    #: quantized-ring shard_map (kernels { grad_allreduce:
    #: quantized_ring }); the CD engine's layer-hooked Gibbs walk stays
    #: on the reference seam and rejects the ring instead of silently
    #: keeping fp32 bytes on the wire
    _supports_ring_collective = True

    def __init__(
        self,
        model_cfg: ModelConfig,
        cluster_cfg: ClusterConfig | None = None,
        *,
        mesh=None,
        seed: int = 0,
        log: Callable[[str], None] = print,
        prefetch: bool | None = None,
        device_cache: bool | None = None,
        stream_chunks: bool | None = None,
    ):
        self.cfg = model_cfg
        self.cluster = cluster_cfg
        self.log = log
        self.perf = Performance()
        self.timers = Timers()

        # --- nets (SetupNeuralNet x3, phase-filtered; worker.cc:16-27) ---
        # active_phases is the single source of truth for which nets a job
        # builds — netlint validates exactly the same set
        phases = active_phases(model_cfg)
        self.train_net = build_net(model_cfg, "kTrain")
        self.test_net: Net | None = (
            build_net(model_cfg, "kTest") if "kTest" in phases else None
        )
        self.val_net: Net | None = (
            build_net(model_cfg, "kValidation")
            if "kValidation" in phases
            else None
        )

        # --- params + updater (ParamManager ctor + InitParams) ---
        self.specs = self.train_net.param_specs()
        if model_cfg.updater is None:
            raise ConfigError("model config has no updater block")
        self.updater = make_updater(model_cfg.updater)

        # --- resilience seams (resilience/context.py): the supervisor
        # (or a test) attaches a ResilienceContext; None = inert ---
        self.resilience = None
        # --- telemetry (singa_tpu/obs/): the flight recorder the
        # supervisor attaches via attach_telemetry; None = inert. The
        # step path never writes or syncs for it — events buffer in
        # memory and flush at display cadence (_post_events) ---
        self.telemetry = None
        # every engine supports the guard through the shared _step_core
        # seam (resilience/guard.py guarded_step): each core reports
        # its own finiteness verdict, the wrapper applies the policy
        self._guard = GuardSpec.from_config(model_cfg.resilience)
        root = jax.random.PRNGKey(seed)
        self._init_key, self._step_key = jax.random.split(root)

        # --- mesh + shardings (replaces Cluster/PS/partitioner) ---
        self.mesh = mesh if mesh is not None else mesh_from_cluster(cluster_cfg)
        npipe = dict(self.mesh.shape).get("pipe", 1)
        for net in (self.train_net, self.test_net, self.val_net):
            if net is None:
                continue
            net.bind_mesh(self.mesh)
            if npipe > 1:
                from ..graph.pipeline_plan import plan_stages

                net.pipeline_plan = plan_stages(
                    net, npipe, model_cfg.pipeline_microbatches
                )
                net.pipeline_mesh = self.mesh
        self.param_sh = param_shardings(self.mesh, self.train_net)
        # --- ZeRO-style update sharding (zero_update: reduce-scatter
        # grads, shard-local optimizer, allgather params — arxiv
        # 2004.13336). The updater slots LIVE in the update layout, so
        # per-device opt-state bytes shrink by the data-parallel degree;
        # the step itself picks the layout up via _constrain_grads /
        # _apply_update. ---
        self._zero_sh = None
        if model_cfg.zero_update:
            if not self._supports_zero_update:
                raise ConfigError(
                    f"{type(self).__name__} does not support zero_update "
                    "(the replica protocol owns its own update layout)"
                )
            self._zero_sh = zero_update_shardings(
                self.mesh, self.train_net, self.param_sh, warn=True
            )
        # --- quantized + overlapped gradient collectives (grad_comm:
        # parallel/collectives.py — EQuARX-style scaled int8/bf16 wire
        # cast with error-feedback residuals in the buffer pytree, and
        # reverse-topo bucket chaining so bucket k's reduction overlaps
        # bucket k+1's backward segment). None = today's exact fp32
        # collective, traced bitwise-identically. ---
        from ..parallel.collectives import GradCommSpec

        self._comm = GradCommSpec.from_config(
            model_cfg.grad_comm, model_cfg.kernels, model_cfg.ring
        )
        if self._comm is not None and not self._supports_grad_comm:
            raise ConfigError(
                f"{type(self).__name__} does not support grad_comm mode "
                f"{self._comm.mode!r} (the replica protocol owns its own "
                "gradient sync math)"
            )
        #: grads-keyset -> reverse-topo bucket partition (cached: the CD
        #: engine's greedy layerwise grads cover a param subset)
        self._comm_bucket_cache: dict[frozenset, tuple] = {}
        #: one-shot comm-cost calibration flag (run() probes once)
        self._comm_probe_done = False
        self.state_sh = state_shardings(
            self.param_sh, self.updater.SLOTS, update_sh=self._zero_sh
        )
        #: pad-to-multiple storage for indivisible kLayerPartition dims
        #: (the reference's uneven-partition contract, neuralnet.cc:160-162
        #: — see parallel/shardings.py). Nets slice back to logical shapes
        #: inside forward.
        self.param_pad = param_paddings(self.mesh, self.train_net)
        if self.param_pad:
            logical = {n: self.specs[n].shape for n in self.param_pad}
            for net in (self.train_net, self.test_net, self.val_net):
                if net is not None:
                    net.param_logical = logical
        self.batch_sh = batch_shardings(self.mesh, self.train_net)
        self._repl = replicated(self.mesh)

        # --- quantized ring collective (kernels { grad_allreduce:
        # quantized_ring } — ops/quantized_collective.py): resolve each
        # param's ring chunk dim (zero_update's data dim when the update
        # is sharded — the ring's scatter output IS the update layout —
        # else dim 0) and reject un-runnable geometry at construction,
        # the same fail-early contract as the fused-attention kernel ---
        self._ring_chunk_dims: dict[str, int] | None = None
        self._ring_gather: dict[str, bool] | None = None
        #: hierarchical two-level geometry (intra_axis, inter_axis, K,
        #: M) from hier_ring_geometry — None for the flat ring
        self._ring_hier: tuple | None = None
        if self._comm is not None and self._comm.ring:
            self._setup_ring_collective()

        # --- buffers (stateful layers, e.g. batch-norm running stats) ---
        self._has_buffers = bool(self.train_net.buffer_specs())
        if self._has_buffers and not self._supports_buffers:
            raise ConfigError(
                f"{type(self).__name__} does not support stateful layers "
                f"(buffers: {sorted(self.train_net.buffer_specs())})"
            )

        # --- params + resume, placed on the mesh ---
        self.start_step = model_cfg.step
        #: stateful-layer state; base _materialize_params replaces it
        #: (subclass overrides without buffer support leave it empty)
        self.buffers: dict = {}
        self._materialize_params()

        # --- input pipelines (the Prefetching protocol's host half;
        # base_layer.h:510-537). Pipeline-level prefetch threads stay
        # OFF: with ``prefetch`` on, the DEVICE feeder / chunk stager
        # (data/device_prefetch.py) own the read-ahead thread — it does
        # the host gather AND starts the transfer, and keeping the
        # pipelines thread-free keeps them seek()-able for rollback ---
        if prefetch is None:
            prefetch = model_cfg.prefetch
        self._prefetch_input = bool(prefetch)
        self._pipelines: dict[int, dict[str, BatchPipeline]] = {}
        for net in (self.train_net, self.test_net, self.val_net):
            if net is None:
                continue
            self._pipelines[id(net)] = {
                l.name: BatchPipeline(
                    l.images,
                    l.labels,
                    l.batchsize,
                    random_skip=l.random_skip if net is self.train_net else 0,
                    seed=seed,
                )
                for l in net.datalayers
            }
        # resume: restore each stream to its checkpointed consumed
        # position (completing the Worker::Resume contract — a resumed
        # run continues the data stream, it doesn't replay from the
        # shard start)
        self._seek_resumed_streams()
        #: last step boundary reached (the supervisor's progress gauge)
        self.completed_steps = self.start_step

        # --- device-resident dataset fast path ---
        # When every data layer's decoded shard fits the budget, upload it
        # once and gather batches *inside* the jitted step (host work per
        # step drops to computing a batchsize-long index vector). The
        # reference's per-step shard read + prefetch copy has no useful
        # counterpart once the data already lives in HBM.
        self._dev_data: dict[int, dict[str, dict]] = {}
        #: (net id, layer) -> decoded dtype for uint8-compacted device
        #: data (cached datasets AND streaming staged blocks)
        self._cache_cast: dict[tuple[int, str], jnp.dtype] = {}
        self._cached = self._maybe_cache_datasets(device_cache)

        # --- zero-stall input (data/device_prefetch.py): with prefetch
        # on and no device cache, train batches arrive double-buffered —
        # per-step via the device feeder, or as staged scan-chunk blocks
        # (feeder_mode: cached / stream / prefetch / sync) ---
        if stream_chunks is None:
            stream_chunks = os.environ.get(
                "SINGA_TPU_STREAM_CHUNK", "1"
            ).lower() not in ("0", "off", "false")
        self._stream_chunks = bool(stream_chunks)
        self._feeder = None
        self._stager = None
        #: train-stream positions of batches the trainer actually
        #: consumed (the device feeder reads ahead; checkpoints must not
        #: skip what the step loop never saw)
        self._feeder_positions: dict[str, int] = {}
        if self.feeder_mode != "stream":
            # only the chunk stager consumes the over-budget compaction
            # stash; don't pin a dataset-sized copy for any other mode
            self.__dict__.pop("_compact_train", None)

        if model_cfg.checkpoint_frequency and self._checkpoint_dir() is None:
            self.log(
                "WARNING: checkpoint_frequency is set but no cluster "
                "workspace is configured — no snapshots will be written "
                "(pass -cluster_conf with a workspace field)"
            )

        # --- mixed precision (singa-tpu extension, ModelProto.compute_dtype)
        self._compute_dtype = None
        if model_cfg.compute_dtype:
            try:
                dt = jnp.dtype(model_cfg.compute_dtype)
            except TypeError:
                raise ConfigError(
                    f"unknown compute_dtype {model_cfg.compute_dtype!r}"
                ) from None
            if dt != jnp.float32:
                self._compute_dtype = dt

        # --- the one compiled program ---
        self._train_step = jax.jit(
            self._train_step_entry, donate_argnums=(0, 1, 2)
        )
        # multi-step chunks: scan over the same step body, one dispatch
        # per cadence window instead of per batch (cache keyed by length)
        self._chunk_fns: dict[int, Callable] = {}
        self._eval_steps: dict[int, Callable] = {}
        self._eval_chunk_fns: dict[tuple[int, int], Callable] = {}
        #: unpad? -> compiled snapshot program (zero-stall checkpointing)
        self._snapshot_fns: dict[bool, Callable] = {}
        self._batch_size = self.train_net.batchsize
        #: tokens consumed per train step (LM configs: kSequenceData
        #: feeds (B, S) token batches) — 0 for non-token workloads.
        #: Drives the display line's tok/s readout, straight from the
        #: existing Timers accumulators, no new host syncs.
        self._tokens_per_step = sum(
            l.batchsize * int(np.prod(l.sample_shape)) * self._batches_per_step
            for l in self.train_net.datalayers
            if getattr(l, "TYPE", "") == "kSequenceData"
        )

    # ------------------------------------------------------------------
    # telemetry (singa_tpu/obs/recorder.py)
    # ------------------------------------------------------------------

    def attach_telemetry(self, rec) -> None:
        """Wire the flight recorder in: lifecycle events from the
        cadence loop, and (span mode) every timed phase occurrence as a
        Chrome-trace span. Purely host-side buffer appends — the step
        path gains no write syscalls and no device syncs."""
        self.telemetry = rec
        if rec is not None:
            self.timers.span_sink = rec.phase_span

    # ------------------------------------------------------------------
    # param materialization (overridden by ReplicaTrainer)
    # ------------------------------------------------------------------

    def _materialize_params(self) -> None:
        """Initialize params + updater slots, overlay the resume
        checkpoint (fills Worker::Resume, worker.cc:65-67), and place
        everything onto the mesh shardings. Sharded checkpoints
        (directories) restore shard-to-device without any host gather."""
        from .sharded_ckpt import is_sharded_checkpoint

        params = init_params(self._init_key, self.specs)
        state = self.updater.init_state(params)
        buffers = self.train_net.init_buffers()
        if self._guard is not None:
            # guard counters ride the buffer pytree (reserved dunder
            # keys) so they thread the jitted step and checkpoint with
            # the rest of training state for free
            buffers.update(init_guard_buffers())
        if self._comm is not None and self._comm.wants_residuals:
            # error-feedback residuals ride the buffer pytree the same
            # way (STORED shapes — grads of padded params are padded):
            # they checkpoint, restore, and roll back with training
            # state, so compression error is never silently dropped
            # across a resume
            from ..parallel.collectives import init_residuals

            buffers.update(
                init_residuals(self._pad_stored(params), self._comm)
            )
        #: stream positions waiting to be applied once pipelines exist
        self._resume_streams: dict[str, int] = {}
        if self.cfg.checkpoint and is_sharded_checkpoint(self.cfg.checkpoint):
            # sharded checkpoints hold STORED (padded) arrays; pad the
            # fresh-init fallbacks so every entry matches its sharding
            self._restore_sharded(
                self._pad_stored(params), self._pad_state(state), buffers
            )
            return
        if self.cfg.checkpoint:
            # npz checkpoints hold LOGICAL arrays (save unpads): overlay
            # first, pad after
            ck_step, params, state, buffers = restore_into(
                self.cfg.checkpoint, params, state, buffers
            )
            self._resume_streams = load_stream_positions(self.cfg.checkpoint)
            self.start_step = max(self.start_step, ck_step)
            self.log(
                f"resumed from {self.cfg.checkpoint} at step {self.start_step}"
            )
        params = self._pad_stored(params)
        state = self._pad_state(state)
        self.params = {
            n: jax.device_put(v, self.param_sh[n]) for n, v in params.items()
        }
        self.state = {
            n: {
                s: jax.device_put(v, self.state_sh[n][s])
                for s, v in slots.items()
            }
            for n, slots in state.items()
        }
        self.buffers = {
            n: jax.device_put(v, self._buffer_sharding(n))
            for n, v in buffers.items()
        }

    def _seek_resumed_streams(self) -> None:
        """Apply ``_resume_streams`` to every pipeline (used at init and
        again after a guard rollback re-restores a checkpoint). Any
        input feeder's read-ahead is discarded FIRST — its thread must
        be parked before the streams it draws from are repositioned."""
        self._reset_feeders()
        for net in (self.train_net, self.test_net, self.val_net):
            if net is None:
                continue
            for name, pipe in self._pipelines.get(id(net), {}).items():
                pos = getattr(self, "_resume_streams", {}).get(
                    f"{net.phase}|{name}"
                )
                if pos is not None:
                    pipe.seek(pos)

    # ------------------------------------------------------------------
    # pad-to-multiple storage (uneven kLayerPartition dims)
    # ------------------------------------------------------------------

    def _pad_one(self, name: str, arr):
        """Logical -> stored array: zero-pad the dims param_paddings
        marked so every shard is even (the zero tail is invisible —
        Net.forward slices it off, its gradients are structurally zero,
        and save() strips it). Pad widths apply to the TRAILING dims, so
        replica-stacked (R, ...) arrays pad correctly too."""
        w = self.param_pad.get(name)
        if not w:
            return arr
        widths = ((0, 0),) * (arr.ndim - len(w)) + tuple(w)
        return jnp.pad(arr, widths)

    def _pad_stored(self, params: dict) -> dict:
        if not self.param_pad:
            return params
        return {n: self._pad_one(n, v) for n, v in params.items()}

    def _pad_state(self, state: dict) -> dict:
        if not self.param_pad:
            return state
        return {
            n: {s: self._pad_one(n, v) for s, v in slots.items()}
            for n, slots in state.items()
        }

    def _unpad_one(self, name: str, arr):
        """Stored -> logical (trailing-dims slice keeps any leading
        replica axis)."""
        if name not in self.param_pad:
            return arr
        logical = self.specs[name].shape
        return arr[(Ellipsis, *(slice(0, s) for s in logical))]

    def _unpad_stored(self, params: dict) -> dict:
        if not self.param_pad:
            return params
        return {n: self._unpad_one(n, v) for n, v in params.items()}

    def _unpad_state(self, state: dict) -> dict:
        if not self.param_pad:
            return state
        return {
            n: {s: self._unpad_one(n, v) for s, v in slots.items()}
            for n, slots in state.items()
        }

    def _restore_sharded(self, params, state, buffers) -> None:
        """Place a sharded checkpoint directly onto the mesh: every
        saved array goes shard-to-device (no host-global assembly when
        the topology matches); a checkpoint written by a DIFFERENT
        process count or mesh reshards — each target shard assembled
        from the intersecting saved boxes (resilience/reshard.py), so
        a drained N-rank job resumes on M ranks. Entries absent from
        the checkpoint keep their fresh init."""
        from ..resilience.reshard import Resharder
        from .sharded_ckpt import (
            ShardedCheckpoint,
            buffer_key,
            param_key,
            state_key,
        )

        with ShardedCheckpoint(self.cfg.checkpoint) as ck:
            # mesh admission first: a target that cannot host the
            # manifest's specs must reject loudly (ReshardError; the
            # static mirror is netlint ELA001), never half-restore
            resharder = Resharder(ck, dict(self.mesh.shape))
            have = set(ck.keys())

            def restore(key, init_val, sharding, pname=None):
                if key not in have:
                    return jax.device_put(init_val, sharding)
                saved = tuple(ck.manifest["arrays"][key]["shape"])
                expect = tuple(init_val.shape)
                if saved != expect:
                    # uneven-partition storage is mesh-dependent: a
                    # checkpoint written on a different model-axis width
                    # padded this param differently. Normalize through
                    # the logical shape (slice the saved tail, re-pad
                    # for THIS mesh) via host assembly.
                    logical = (
                        self.specs[pname].shape
                        if pname is not None and pname in self.specs
                        else None
                    )
                    lead = len(expect) - len(logical) if logical else 0
                    if (
                        logical is not None
                        and len(saved) == len(expect)
                        and saved[:lead] == expect[:lead]
                        and all(
                            s >= l for s, l in zip(saved[lead:], logical)
                        )
                    ):
                        arr = ck.assemble(key)[
                            (Ellipsis, *(slice(0, l) for l in logical))
                        ]
                        arr = self._pad_one(pname, jnp.asarray(arr))
                        return jax.device_put(
                            arr.astype(init_val.dtype), sharding
                        )
                    raise ValueError(
                        f"checkpoint {self.cfg.checkpoint!r}: {key!r} "
                        f"shape {saved} != model shape {init_val.shape}"
                    )
                # cast to the MODEL's dtype: a checkpoint written at a
                # different precision must not leak its dtype into the
                # donating jitted step
                return resharder.place(key, sharding, dtype=init_val.dtype)

            self.params = {
                n: restore(param_key(n), v, self.param_sh[n], pname=n)
                for n, v in params.items()
            }
            self.state = {
                n: {
                    s: restore(
                        state_key(n, s), v, self.state_sh[n][s], pname=n
                    )
                    for s, v in slots.items()
                }
                for n, slots in state.items()
            }
            self.buffers = {
                n: restore(buffer_key(n), v, self._buffer_sharding(n))
                for n, v in buffers.items()
            }
            # stream positions are CONSUMED-batch counts against the
            # GLOBAL stream (each rank advances the same cursor; the
            # batch shardings slice each batch, not the stream), so
            # they are world-size-invariant: restoring them verbatim on
            # M ranks replays and skips nothing
            self._resume_streams = dict(ck.streams)
            self.start_step = max(self.start_step, ck.step)
            from ..resilience.coord import process_count

            if resharder.saved_nprocs != process_count():
                self.log(
                    f"elastic restore: checkpoint written by "
                    f"{resharder.saved_nprocs} process(es), resuming on "
                    f"{process_count()}"
                )
            reshard_note = resharder.summary()
            if reshard_note is not None:
                self.log(f"elastic restore: {reshard_note}")
        self.log(
            f"resumed sharded from {self.cfg.checkpoint} at step "
            f"{self.start_step}"
        )

    # ------------------------------------------------------------------
    # device-resident dataset cache
    # ------------------------------------------------------------------

    @staticmethod
    def _compact_cache_array(images: np.ndarray):
        """-> (storage array, original dtype) for the device cache.

        Raw record pixels are byte-valued floats (uint8 widened at
        decode, data/pipeline.py); storing them as uint8 quarters the
        HBM the per-step gather reads — at ResNet scale the gather of a
        (B, 3, 256, 256) fp32 batch is ~100 MB of pure bandwidth before
        any compute. The round trip is exact: values are integers in
        [0, 255], and _resolve_batch casts back to the original dtype
        inside the jitted step (so every consumer sees identical
        arrays). Non-byte-valued data stays as-is."""
        if images.dtype == np.uint8 or images.size == 0:
            return images, images.dtype
        if (
            np.issubdtype(images.dtype, np.floating)
            or np.issubdtype(images.dtype, np.integer)
        ):
            lo, hi = images.min(), images.max()
            if 0 <= lo and hi <= 255 and np.all(images == np.trunc(images)):
                return images.astype(np.uint8), images.dtype
        return images, images.dtype

    def _maybe_cache_datasets(self, enabled: bool | None) -> bool:
        """Upload every net's dataset to the mesh (replicated) when it
        fits SINGA_TPU_DEVICE_CACHE_MB (default 512). Byte-valued data
        is stored uint8 (see _compact_cache_array). Explicit
        ``device_cache=False`` or a cache-incompatible subclass wins."""
        if not self._allow_device_cache or enabled is False:
            return False
        nets = [n for n in (self.train_net, self.test_net, self.val_net)
                if n is not None]
        compact: dict[tuple[int, str], tuple[np.ndarray, np.dtype]] = {}
        total = 0
        for net in nets:
            for l in net.datalayers:
                arr, orig = self._compact_cache_array(np.asarray(l.images))
                compact[(id(net), l.name)] = (arr, orig)
                total += arr.nbytes + l.labels.nbytes
        if enabled is None:
            limit = float(os.environ.get("SINGA_TPU_DEVICE_CACHE_MB", "512"))
            if total > limit * 1e6:
                # over budget -> the stream stager will want exactly the
                # train net's compacted arrays; hand them over instead of
                # re-scanning (and re-copying) a cache-sized dataset
                self._compact_train = {
                    name: compact[(nid, name)]
                    for nid, name in compact
                    if nid == id(self.train_net)
                }
                return False
        if total == 0:
            return False
        for net in nets:
            self._dev_data[id(net)] = {}
            for l in net.datalayers:
                arr, orig = compact[(id(net), l.name)]
                if arr.dtype != orig:
                    self._cache_cast[(id(net), l.name)] = jnp.dtype(orig)
                self._dev_data[id(net)][l.name] = {
                    "image": jax.device_put(jnp.asarray(arr), self._repl),
                    "label": jax.device_put(
                        jnp.asarray(l.labels), self._repl
                    ),
                }
        return True

    def _resolve_batch(self, net: Net, batch: dict, constrain: bool = True):
        """Turn ``__idx__``-tagged feeds (device-cached mode) into real
        per-batch arrays by gathering on device; host-assembled feeds pass
        through unchanged. Runs inside the jitted step, so the gather and
        everything downstream compile into one program."""
        out = {}
        for name, feed in batch.items():
            if "__idx__" not in feed:
                out[name] = feed
                continue
            idx = feed["__idx__"]
            img = jnp.take(feed["image"], idx, axis=0)
            lbl = jnp.take(feed["label"], idx, axis=0)
            # compact uint8 cache: restore the decoded dtype AFTER the
            # gather, so consumers see exactly the host-path arrays but
            # the HBM read was a quarter the size
            cast = getattr(self, "_cache_cast", {}).get((id(net), name))
            if cast is not None:
                img = img.astype(cast)
            if constrain and net is self.train_net:
                sh = self.batch_sh.get(name)
                if sh is not None:
                    img = jax.lax.with_sharding_constraint(img, sh["image"])
                    lbl = jax.lax.with_sharding_constraint(lbl, sh["label"])
            out[name] = {"image": img, "label": lbl}
        return out

    # ------------------------------------------------------------------
    # compiled step functions
    # ------------------------------------------------------------------

    def _train_step_entry(self, params, state, buffers, step, batch, rng):
        """Jit entry: resolve cached batches, then run the (possibly
        subclass-overridden) step body. Buffers always thread through —
        an empty dict for stateless nets costs nothing."""
        batch = self._resolve_batch(self.train_net, batch)
        return self._train_step_fn(params, state, buffers, step, batch, rng)

    def _cast_compute(self, tree):
        """Cast float leaves to the compute dtype (bf16 matmuls on the
        MXU); params keep fp32 masters — the cast sits inside loss_fn so
        its transpose upcasts the grads back to fp32 automatically."""
        if self._compute_dtype is None:
            return tree
        dt = self._compute_dtype
        return jax.tree.map(
            lambda x: x.astype(dt)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )

    def _train_step_fn(self, params, state, buffers, step, batch, rng):
        """One train step: the engine's ``_step_core`` update, wrapped
        by the shared divergence guard when one is configured
        (resilience/guard.py guarded_step — the verdict folds into the
        step's existing outputs, zero per-step host syncs)."""
        if self._guard is None:
            params, state, buffers, metrics, _ = self._step_core(
                params, state, buffers, step, batch, rng, None
            )
            return params, state, buffers, metrics
        return guarded_step(
            self._step_core, params, state, buffers, step, batch, rng
        )

    def _step_core(self, params, state, buffers, step, batch, rng, lr_scale):
        """One forward+backward+update -> (params, state, buffers,
        metrics, ok). Stateful layers' buffer updates (batch-norm
        running stats) ride the has_aux output — plain forward values,
        outside any gradient path.

        The engine-specific half of the guard seam: ``lr_scale`` is
        None for unguarded runs (``ok`` is then unused); guarded, it is
        the accumulated rollback LR backoff — multiplying the grads
        inside the program (scale 1.0 is a bitwise no-op) means backing
        off needs no recompile and no host sync — and ``ok`` is this
        engine's finiteness verdict: loss + global grad-norm."""
        if self._comm is not None and self._comm.ring:
            # the int8-on-the-wire ring runs the backward per data
            # shard (shard_map) so the reduction sees local partials —
            # a different program shape, same seam contract
            return self._ring_step_core(
                params, state, buffers, step, batch, rng, lr_scale
            )

        def loss_fn(p):
            loss, metrics, new_buffers = self.train_net.forward(
                self._cast_compute(p), self._cast_compute(batch),
                training=True, rng=rng,
                buffers=buffers, return_buffers=True,
            )
            return loss, (metrics, new_buffers)

        (loss, (metrics, new_buffers)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        # grad_comm seam: zero_update pins the grads to the update
        # layout FIRST, so the data-axis grad sync lowers to a
        # reduce-scatter and everything downstream — the guard's norm,
        # the updater math — runs on each rank's shard only; quantized
        # mode additionally casts each bucket to the low-precision wire
        # format around that constraint, banking the compression error
        # in the residual buffers (the guard and the update consume the
        # DEQUANTIZED grads unchanged)
        grads, comm_bufs = self._reduce_grads(grads, buffers)
        new_buffers = {**new_buffers, **comm_bufs}
        ok = None
        if lr_scale is not None:
            ok = jnp.isfinite(loss) & jnp.isfinite(grad_norm_sq(grads))
            grads = jax.tree.map(
                lambda g: g * lr_scale.astype(g.dtype), grads
            )
        params, state = self._apply_update(step, params, grads, state)
        return params, state, new_buffers, metrics, ok

    # ------------------------------------------------------------------
    # update sharding (zero_update — parallel/shardings.py)
    # ------------------------------------------------------------------

    @property
    def update_mode(self) -> str:
        """How the weight update is laid out across the data axis:
        ``replicated`` (every rank applies the full update — the
        reference's ParamSync semantics) or ``zero`` (reduce-scatter
        grads, shard-local optimizer, allgather params)."""
        return "zero" if self._zero_sh is not None else "replicated"

    def opt_state_bytes_per_device(self) -> int:
        """Bytes of updater state resident on EACH device — the
        footprint zero_update shrinks by the data-parallel degree.
        Computed from the shard shapes: no host transfer, no sync."""
        total = 0
        for slots in self.state.values():
            for v in slots.values():
                shape = v.sharding.shard_shape(v.shape)
                total += int(np.prod(shape, dtype=np.int64)) * v.dtype.itemsize
        return total

    def _constrain_grads(self, grads: dict) -> dict:
        """Zero mode: constrain each grad to its update sharding, so
        GSPMD replaces the grad all-reduce with a reduce-scatter (each
        rank receives only its shard's sum) and the guard's grad-norm
        becomes shard-local partials psum'd to one scalar — no gather.
        Identity when the update is replicated. ``grads`` may cover a
        subset of params (the CD engine's greedy layerwise grads)."""
        if self._zero_sh is None:
            return grads
        return {n: self._constrain_one(n, g) for n, g in grads.items()}

    def _constrain_one(self, name: str, arr):
        """Per-tensor half of _constrain_grads — the ``constrain``
        callback the grad_comm reduction applies to each QUANTIZED wire
        tensor, so the data-axis reduce-scatter's operand is the
        low-precision value, not the fp32 gradient."""
        if self._zero_sh is None:
            return arr
        return jax.lax.with_sharding_constraint(arr, self._zero_sh[name])

    # ------------------------------------------------------------------
    # gradient collectives (grad_comm — parallel/collectives.py)
    # ------------------------------------------------------------------

    @property
    def comm_mode(self) -> str:
        """How gradients cross the data axis: ``exact`` (today's fp32
        collective) or ``quantized`` (scaled int8/bf16 wire cast with
        error feedback)."""
        return (
            "quantized"
            if self._comm is not None and self._comm.quantized
            else "exact"
        )

    @property
    def comm_dtype(self) -> str:
        """Wire dtype of the quantized gradient collective ("" when the
        collective is exact fp32)."""
        if self._comm is not None and self._comm.quantized:
            return self._comm.dtype
        return ""

    def _comm_buckets(self, names: frozenset) -> tuple:
        """Reverse-topo bucket partition for this grads keyset, cached
        (the CD engine's layerwise grads cover a param subset)."""
        if names not in self._comm_bucket_cache:
            from ..parallel.collectives import reverse_topo_buckets

            self._comm_bucket_cache[names] = reverse_topo_buckets(
                self.train_net, names, self._comm.buckets, self.specs
            )
        return self._comm_bucket_cache[names]

    def _reduce_grads(self, grads: dict, buffers: dict):
        """The grad_comm seam around _constrain_grads: -> (update-ready
        grads, residual-buffer updates). With no active ``grad_comm``
        block this IS _constrain_grads — the exact path traces
        bitwise-identically to pre-grad_comm main."""
        if self._comm is None:
            return self._constrain_grads(grads), {}
        from ..parallel.collectives import reduce_gradients

        return reduce_gradients(
            grads,
            buffers,
            self._comm,
            self._comm_buckets(frozenset(grads)),
            self._constrain_one,
        )

    # ------------------------------------------------------------------
    # quantized ring collective (kernels { grad_allreduce:
    # quantized_ring } — ops/quantized_collective.py)
    # ------------------------------------------------------------------

    @property
    def grad_wire_impl(self) -> str:
        """Which wire implementation the data-axis gradient reduction
        runs ("" when no grad_comm machinery is active): ``reference``
        (quantize around the GSPMD psum — fp32 bytes on the wire),
        ``quantized_ring`` (int8 bytes in explicit ppermutes), or
        ``q8_hier`` (the hierarchical two-level ring)."""
        if self._comm is None:
            return ""
        return self._comm.wire_impl

    def _ring_ndata(self) -> int:
        """Total reduction width: the data-axis width for the flat
        ring, K*M for the hierarchical form (the named-axes variant
        reduces over the PRODUCT of its two mesh axes)."""
        if self._ring_hier is not None:
            return self._ring_hier[2] * self._ring_hier[3]
        return dict(self.mesh.shape).get("data", 1)

    def _ring_axes(self) -> tuple:
        """Mesh axes the ring's chunk layout shards over, major-first
        (chunk index = g*K + p, so the inter axis is the major one)."""
        if self._ring_hier is not None:
            intra_ax, inter_ax, _, _ = self._ring_hier
            if intra_ax != inter_ax:
                return (inter_ax, intra_ax)
        return ("data",)

    def _setup_ring_collective(self) -> None:
        """Resolve the ring's per-param geometry and reject un-runnable
        configs loudly at construction (netlint KRN002 is the static
        mirror, consulting the SAME ``ring_reducible`` /
        ``hier_ring_geometry`` predicates). The flat ring keeps its
        loud composed-mesh rejection; ``q8_hier`` is the acceptance
        path — any mesh whose reduction the two-level factorization
        covers runs, with the chunkability predicates applied at the
        TOTAL width K*M."""
        from ..ops.quantized_collective import (
            hier_ring_geometry,
            ring_fusable,
            ring_reducible,
        )

        impl = self._comm.wire_impl
        if not self._supports_ring_collective:
            raise ConfigError(
                f"{type(self).__name__} does not support kernels "
                f"{{ grad_allreduce: {impl} }} (the ring wraps the "
                "backward in a data-axis shard_map; this engine's step "
                "does not take that shape)"
            )
        widths = dict(self.mesh.shape)
        if self._comm.hier:
            geom = hier_ring_geometry(widths, self._comm)
            if isinstance(geom, str):
                raise ConfigError(
                    f"kernels {{ grad_allreduce: q8_hier }} cannot "
                    f"run: {geom}"
                )
            if geom[0] != geom[1] and self._zero_sh is not None:
                raise ConfigError(
                    "kernels { grad_allreduce: q8_hier } with named "
                    "intra_axis/inter_axis does not compose with "
                    "zero_update (the update layout shards over the "
                    "data axis only) — use the factored "
                    "ring { intra_degree } form"
                )
            self._ring_hier = geom
        else:
            other = {
                a: w for a, w in widths.items() if a != "data" and w > 1
            }
            if other:
                raise ConfigError(
                    "kernels { grad_allreduce: quantized_ring } runs over "
                    f"the data axis only, but the mesh also shards {other} "
                    "— kernels { grad_allreduce: q8_hier } with a "
                    "ring { intra_axis/inter_axis } block is the "
                    "hierarchical (intra/inter-slice) two-level form "
                    "that covers composed meshes"
                )
        ndata = self._ring_ndata()
        bs = self.train_net.batchsize
        if bs % max(1, ndata):
            raise ConfigError(
                f"{impl} needs the data-reduction width ({ndata}) to "
                f"divide the batch ({bs}): each shard computes its own "
                "local partial gradients"
            )
        if self.train_net.buffer_specs():
            # batch-stat layers (kBatchNorm is the only buffer owner)
            # get their "sync BN over the global batch" semantics from
            # GSPMD's implicit psums (layers/norm.py); inside the
            # ring's shard_map the forward sees only its local shard,
            # so batch moments would silently become per-shard stats —
            # a biased variance, not the documented tolerance caveat
            raise ConfigError(
                f"kernels {{ grad_allreduce: {impl} }} cannot run "
                "a net with batch-statistics buffers (kBatchNorm): the "
                "ring's per-shard backward would turn sync BatchNorm "
                "into local-shard BN — cross-shard batch moments inside "
                "the ring are a ROADMAP carry-over"
            )
        chunk_dims: dict[str, int] = {}
        gather: dict[str, bool] = {}
        for name, spec in self.specs.items():
            d, g = 0, True
            if self._zero_sh is not None:
                d_zero = self._zero_data_dim(name)
                if d_zero is not None:
                    # the ring's scatter output lands each shard's chunk
                    # exactly where the zero update wants it — the
                    # allgather phase is skipped for this param
                    d, g = d_zero, False
            chunk_dims[name] = d
            gather[name] = g
        shapes = {n: s.shape for n, s in self.specs.items()}
        reason = ring_reducible(shapes, ndata, chunk_dims)
        if reason is not None:
            raise ConfigError(
                f"kernels.grad_allreduce {impl} cannot run: "
                f"{reason}"
            )
        if not self._comm.interpret:
            reason = ring_fusable(
                shapes, ndata, chunk_dims, interpret=False
            )
            if reason is not None:
                raise ConfigError(
                    f"kernels.grad_allreduce {impl} with "
                    f"interpret off cannot run: {reason}"
                )
        self._ring_chunk_dims = chunk_dims
        self._ring_gather = gather

    def _zero_data_dim(self, name: str) -> int | None:
        """The dim zero_update lays over the data axis for ``name``
        (None = the replicate fallback: no divisible free dim)."""
        spec = self._zero_sh[name].spec
        for i, entry in enumerate(spec):
            axes = entry if isinstance(entry, tuple) else (entry,)
            if "data" in axes:
                return i
        return None

    def _buffer_sharding(self, name: str):
        """Placement for one buffer: a ring-mode error-feedback
        residual lives in its ring-chunk layout — each data shard owns,
        and banks the owner-side quantization error for, exactly its
        own chunk, which is how the shard_map step emits it — so
        sharded checkpoints save and restore matching shard boxes.
        Everything else (and every buffer off the ring path) is
        replicated."""
        from ..parallel.collectives import RESIDUAL_PREFIX

        if self._ring_chunk_dims is not None and name.startswith(
            RESIDUAL_PREFIX
        ):
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            d = self._ring_chunk_dims.get(name[len(RESIDUAL_PREFIX):])
            if d is not None:
                axes = self._ring_axes()
                entry = axes if len(axes) > 1 else axes[0]
                return NamedSharding(
                    self.mesh, P(*([None] * d + [entry]))
                )
        return self._repl

    def _ring_specs(self):
        """(grad out_specs, residual specs) pytrees for the ring
        shard_map: gathered grads come out replicated (bitwise
        identical on every shard by the allgather-from-identical-bytes
        construction), zero-mode grads in the update layout, residuals
        chunk-sharded over the data axis (each shard owns — and banks
        the quantization error for — exactly its own chunk)."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.collectives import residual_key

        axes = self._ring_axes()
        entry = axes if len(axes) > 1 else axes[0]

        def cspec(name):
            d = self._ring_chunk_dims[name]
            return P(*([None] * d + [entry]))

        gspecs = {
            n: (P() if self._ring_gather[n] else cspec(n))
            for n in self.specs
        }
        rspecs = (
            {residual_key(n): cspec(n) for n in self.specs}
            if self._comm.wants_residuals
            else {}
        )
        return gspecs, rspecs

    def _ring_step_core(
        self, params, state, buffers, step, batch, rng, lr_scale
    ):
        """The quantized-ring twin of ``_step_core``: forward + backward
        run PER DATA SHARD inside a shard_map, so each shard holds its
        own local partial gradients — the thing GSPMD's implicit psum
        never exposes — and the data-axis reduction is the explicit
        int8-on-the-wire ring (ops/quantized_collective.py). Loss and
        metrics are pmean'd across shards (equal shard sizes, so the
        mean of per-shard means is the global mean; reduction-order
        parity with the reference path is tolerance-level, the PR 9
        cross-shape caveat). Nets with batch-stat buffers are rejected
        at construction — inside shard_map their moments would be
        per-shard, not the sync-BN semantics GSPMD gives. Everything
        downstream — the guard verdict, lr backoff, the updater — runs
        on the reduced grads unchanged."""
        from jax.sharding import PartitionSpec as P

        from ..ops.quantized_collective import (
            ring_reduce_gradients,
            shard_map,
        )
        from ..parallel.collectives import is_residual_key, residual_key

        spec = self._comm
        ndata = self._ring_ndata()
        hier = self._ring_hier
        axes = self._ring_axes()
        bentry = axes if len(axes) > 1 else axes[0]
        buckets = self._comm_buckets(frozenset(params))
        res_in = {
            k: v for k, v in buffers.items() if is_residual_key(k)
        }
        passthru = {
            k: v for k, v in buffers.items() if not is_residual_key(k)
        }
        gspecs, rspecs = self._ring_specs()

        def body(params, passthru, res, batch, rng):
            if len(axes) > 1:
                # named-axes hier: linear rank = g*K + p (the batch's
                # composite in_spec slices in the same order)
                me = jax.lax.axis_index(axes[0]) * hier[2] + (
                    jax.lax.axis_index(axes[1])
                )
            else:
                me = jax.lax.axis_index("data")
            lrng = jax.random.fold_in(rng, me)

            def loss_fn(p):
                loss, metrics, new_buffers = self.train_net.forward(
                    self._cast_compute(p), self._cast_compute(batch),
                    training=True, rng=lrng,
                    buffers=passthru, return_buffers=True,
                )
                return loss, (metrics, new_buffers)

            (loss, (metrics, new_buffers)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            # each shard's loss is its LOCAL batch mean; /ndata makes
            # the ring's cross-shard sum the global mean gradient
            grads = {n: g / ndata for n, g in grads.items()}
            grads, new_res = ring_reduce_gradients(
                grads, res, buckets,
                axis_name="data", nshards=ndata,
                chunk_dims=self._ring_chunk_dims,
                gather=self._ring_gather,
                dtype=spec.dtype,
                error_feedback=spec.error_feedback,
                overlapped=spec.overlapped,
                residual_key=residual_key,
                fused_hop=not spec.interpret,
                fused_interpret=False,
                hier=hier,
            )

            def fold(tree):
                # float leaves are per-shard means -> pmean; non-float
                # leaves (e.g. integer counters riding the buffer
                # pytree) pass through untouched — they entered
                # replicated and nothing here wrote them
                return jax.tree.map(
                    lambda x: jax.lax.pmean(x, axes)
                    if jnp.issubdtype(x.dtype, jnp.floating)
                    else x,
                    tree,
                )

            return (
                jax.lax.pmean(loss, axes),
                fold(metrics),
                fold(new_buffers),
                grads,
                new_res,
            )

        fn = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(), P(), rspecs, P(bentry), P()),
            out_specs=(P(), P(), P(), gspecs, rspecs),
            check_rep=False,
        )
        loss, metrics, new_buffers, grads, new_res = fn(
            params, passthru, res_in, batch, rng
        )
        new_buffers = {**new_buffers, **new_res}
        ok = None
        if lr_scale is not None:
            ok = jnp.isfinite(loss) & jnp.isfinite(grad_norm_sq(grads))
            grads = jax.tree.map(
                lambda g: g * lr_scale.astype(g.dtype), grads
            )
        params, state = self._apply_update(step, params, grads, state)
        return params, state, new_buffers, metrics, ok

    def _ring_reduce_probe(self, grads: dict, res: dict):
        """The ring reduction in isolation (no forward) for the stall
        tools' machinery probes: each shard treats the replicated input
        as its local partial, so the program exercises exactly the
        step's quantize/ppermute/accumulate work."""
        from jax.sharding import PartitionSpec as P

        from ..ops.quantized_collective import (
            ring_reduce_gradients,
            shard_map,
        )
        from ..parallel.collectives import residual_key

        spec = self._comm
        ndata = self._ring_ndata()
        buckets = self._comm_buckets(frozenset(grads))
        gspecs, rspecs = self._ring_specs()
        gspecs = {n: gspecs[n] for n in grads}
        rspecs = {
            residual_key(n): rspecs[residual_key(n)]
            for n in grads
            if residual_key(n) in rspecs
        }

        def body(grads, res):
            return ring_reduce_gradients(
                {n: g / ndata for n, g in grads.items()}, res, buckets,
                axis_name="data", nshards=ndata,
                chunk_dims=self._ring_chunk_dims,
                gather=self._ring_gather,
                dtype=spec.dtype,
                error_feedback=spec.error_feedback,
                overlapped=spec.overlapped,
                residual_key=residual_key,
                fused_hop=not spec.interpret,
                fused_interpret=False,
                hier=self._ring_hier,
            )

        fn = shard_map(
            body, mesh=self.mesh,
            in_specs=(P(), rspecs), out_specs=(gspecs, rspecs),
            check_rep=False,
        )
        return fn(grads, res)

    def wire_bytes_model(self, ndata: int | None = None) -> dict | None:
        """Both sides of the wire-bytes comparison for THIS trainer's
        real param set (None with no grad_comm machinery): modeled
        per-device bytes crossing an ``ndata``-wide data axis per step
        as ``{"reference": .., "quantized_ring": .., "ndata": ..}`` —
        the reference fp32 ring all-reduce (reduce-scatter alone under
        zero_update) vs the quantized ring's ppermute payloads. The one
        place the model's trainer plumbing (sizes, buckets, gather map,
        zero sharding) lives: the ``kernel_select`` event,
        tools/collective_stall.py's gated arm, and bench.py's
        ``wire_bytes_ratio`` row all consult it. ``ndata`` defaults to
        the mesh's real data-axis width; bench passes a nominal width
        when the host's own axis is 1-wide (an empty wire) — a nominal
        width the chunking could not actually divide is halved until
        ``ring_reducible`` accepts it (never below the real width), so
        the model's floor divisions stay exact and the priced geometry
        is one the ring could really run. Under ``q8_hier`` the dict
        additionally carries the per-level split — ``intra`` /
        ``inter`` / ``intra_degree`` — with ``quantized_ring`` staying
        the active ring's TOTAL (intra + inter), so every downstream
        consumer of the total keeps working unchanged."""
        from ..ops.quantized_collective import (
            modeled_wire_bytes,
            modeled_wire_bytes_levels,
            reference_wire_bytes,
            ring_reducible,
        )

        if self._comm is None:
            return None
        hier_k = 0
        if self._comm.hier and self._ring_hier is not None:
            hier_k = self._ring_hier[2]
            if ndata is not None and ndata > self._ring_ndata() and (
                self._comm.intra_degree > 0
            ):
                # nominal pricing keeps the CONFIGURED factored degree
                # (the host's real axis may be 1-wide, degenerating the
                # runtime geometry to 1x1)
                hier_k = self._comm.intra_degree
        n = self._ring_ndata() if ndata is None else ndata
        if ndata is not None and n > self._ring_ndata():
            shapes = {nm: s.shape for nm, s in self.specs.items()}
            while n > self._ring_ndata() and (
                ring_reducible(shapes, n, self._ring_chunk_dims)
                is not None
                or (hier_k > 1 and n % hier_k)
            ):
                n //= 2
            n = max(n, self._ring_ndata())
        sizes = {
            nm: int(np.prod(s.shape, dtype=np.int64))
            for nm, s in self.specs.items()
        }
        out = {
            "reference": int(
                reference_wire_bytes(
                    sizes, n, scatter_only=self._zero_sh is not None
                )
            ),
            "quantized_ring": int(
                modeled_wire_bytes(
                    sizes, self._comm_buckets(frozenset(sizes)), n,
                    dtype=self._comm.dtype, gather=self._ring_gather,
                )
            ),
            "ndata": n,
        }
        if hier_k:
            k = hier_k if n % hier_k == 0 else 1
            # the flat single-level ring over the same n — the baseline
            # the hierarchical gate (inter x intra_degree <= flat)
            # divides against
            out["flat_ring"] = out["quantized_ring"]
            levels = modeled_wire_bytes_levels(
                sizes, self._comm_buckets(frozenset(sizes)), n,
                intra_degree=k, dtype=self._comm.dtype,
                gather=self._ring_gather,
            )
            out["quantized_ring"] = levels["total"]
            out["intra"] = levels["intra"]
            out["inter"] = levels["inter"]
            out["intra_degree"] = k
        return out

    def modeled_wire_bytes_per_step(self) -> int:
        """Modeled per-device bytes the ACTIVE gradient collective
        moves across the data axis per step (0 with no machinery or a
        1-wide axis) — ``wire_bytes_model``'s entry for the configured
        wire implementation, what the ``kernel_select`` telemetry event
        reports."""
        model = self.wire_bytes_model()
        if model is None:
            return 0
        return model[
            "quantized_ring" if self._comm.ring else "reference"
        ]

    def _maybe_emit_kernel_select(self) -> None:
        """Run-start ``kernel_select`` event for the grad_allreduce
        site (the scheduler emits the serving-tier sibling): which wire
        implementation this run reduces gradients through, plus the
        modeled per-step wire bytes — what ``trace.py --summarize``
        reports as ``grad_wire_impl`` / ``wire_bytes_per_step``."""
        if self.telemetry is None or self._comm is None:
            return
        self.telemetry.event(
            "kernel_select",
            step=self.start_step,
            site="train.grad_allreduce",
            impl=self.grad_wire_impl,
            wire_bytes_per_step=int(self.modeled_wire_bytes_per_step()),
            wire_dtype=self._comm.dtype if self._comm.quantized else "f32",
        )

    def _maybe_record_comm_probe(self) -> None:
        """One-shot comm-cost calibration (the flight recorder's ``comm``
        span track): when the grad_comm machinery is active and
        telemetry is attached, time a short isolated chained-reduce
        program under the ``comm`` phase — the span's duration over its
        round count is the per-step cost of the gradient-collective
        machinery, which tools/trace.py --summarize reports next to the
        train/data stall shares. Runs ONCE, before the cadence loop —
        never on the step path — and a probe failure is logged and
        dropped (calibration must not sink training)."""
        if (
            self.telemetry is None
            or self._comm is None
            or self._comm_probe_done
        ):
            return
        self._comm_probe_done = True
        try:
            from ..tools.collective_stall import record_comm_probe

            record_comm_probe(self)
        except Exception as e:  # pragma: no cover - defensive
            self.log(f"TELEMETRY: comm probe failed: {e}")

    def _apply_update(self, step, params: dict, grads: dict, state: dict):
        """Updater.apply under the configured ``update_mode``.

        ``replicated``: every rank runs the full elementwise update.
        ``zero``: params are viewed through the update layout (a slice
        of the replicated value — free), the updater math runs on each
        rank's shard against the already-reduce-scattered grads and the
        resident sharded slots, and the fresh params are constrained
        back to their forward shardings, which GSPMD satisfies with one
        allgather. Loss-identical to the replicated update: every op
        between the constraints is elementwise, so shard boundaries
        cannot change any value."""
        if self._zero_sh is None:
            return self.updater.apply(step, params, grads, state, self.specs)
        wsc = jax.lax.with_sharding_constraint
        shard_view = {
            n: wsc(p, self._zero_sh[n]) for n, p in params.items()
        }
        new_p, new_s = self.updater.apply(
            step, shard_view, grads, state, self.specs
        )
        new_p = {n: wsc(v, self.param_sh[n]) for n, v in new_p.items()}
        new_s = {
            n: {s: wsc(v, self._zero_sh[n]) for s, v in slots.items()}
            for n, slots in new_s.items()
        }
        return new_p, new_s

    def _eval_batch_metrics(self, net: Net, params, buffers, batch) -> dict:
        """One eval batch -> {losslayer: metrics}. The single overridable
        seam both eval paths share (per-step _eval_step_for and the
        chunked scan body) — subclasses with custom eval semantics (the
        CD trainer's per-RBM reconstruction error) override THIS, and
        both paths follow."""
        batch = self._resolve_batch(net, batch)
        _, metrics = net.forward(
            self._cast_compute(params), self._cast_compute(batch),
            training=False, buffers=buffers,
        )
        return metrics

    def _eval_step_for(self, net: Net) -> Callable:
        if id(net) not in self._eval_steps:

            def eval_fn(params, buffers, batch):
                return self._eval_batch_metrics(net, params, buffers, batch)

            # eval traces the LIVE training params/buffers; donating them
            # would invalidate the arrays the next train step needs
            self._eval_steps[id(net)] = jax.jit(eval_fn)  # netlint: disable=JAX003
        return self._eval_steps[id(net)]

    # ------------------------------------------------------------------
    # input feeders (data/device_prefetch.py)
    # ------------------------------------------------------------------

    @property
    def feeder_mode(self) -> str:
        """How train batches reach the device:

        ``cached``    whole dataset resident in HBM, on-device index
                      gather inside the jitted step
        ``stream``    staged scan-chunk blocks, double-buffered at chunk
                      granularity (the streaming chunk engine)
        ``prefetch``  per-step double-buffered device feeder (batch k+1
                      transfers while step k runs)
        ``sync``      batch assembly + transfer on the step path (the
                      reference's unprefetched behavior)
        """
        if self._cached:
            return "cached"
        if self._prefetch_input and self._stream_ok():
            return "stream"
        if self._prefetch_input:
            return "prefetch"
        return "sync"

    def _stream_ok(self) -> bool:
        """Streaming chunks share every non-cache opt-out with
        _can_chunk: debug wants per-step batches, a pending fault plan
        wants exact step boundaries, SINGA_TPU_CHUNK=1 is the escape
        hatch; SINGA_TPU_STREAM_CHUNK=0 disables just this mode."""
        if not self._stream_chunks or self.cfg.debug:
            return False
        if self.resilience is not None and self.resilience.per_step:
            return False
        return self._chunk_cap() > 1

    def _reset_feeders(self) -> None:
        """Discard all feeder read-ahead and park the threads (restore /
        rollback paths — the streams are about to be re-seeked)."""
        for f in (getattr(self, "_feeder", None),
                  getattr(self, "_stager", None)):
            if f is not None:
                f.reset()
        self._feeder_positions = {}

    def _device_feeder(self):
        """The per-step double-buffered device feeder, lazily built."""
        if self._feeder is None:
            from ..data.device_prefetch import DeviceFeeder

            # prefetch mode never stages blocks; a stash kept because
            # the mode was "stream" until a fault plan bound is dead
            self.__dict__.pop("_compact_train", None)
            net = self.train_net
            pipes = self._pipelines[id(net)]

            def positions():
                return {
                    f"{net.phase}|{name}": pipe.position
                    for name, pipe in pipes.items()
                }

            def assemble():
                # feeder-thread span (obs/): assembly + device_put of
                # the read-ahead batch becomes its own trace track
                rec = self.telemetry
                if rec is None:
                    return self._assemble_host_batch(net)
                with rec.span("assemble_batch", track="feeder"):
                    return self._assemble_host_batch(net)

            self._feeder = DeviceFeeder(assemble, positions)
        return self._feeder

    def _chunk_stager(self):
        """The streaming-chunk block stager, lazily built. Byte-valued
        datasets stage uint8 (the device-cache compaction, decided ONCE
        over the full array so the staged dtype never flips mid-run);
        _resolve_batch restores the decoded dtype inside the program."""
        if self._stager is None:
            from ..data.device_prefetch import ChunkStager

            net = self.train_net
            pipes = self._pipelines[id(net)]
            # consume the compaction _maybe_cache_datasets already did
            # for the over-budget datasets stream mode targets (POP: the
            # stager owns the arrays from here, no second copy lives on)
            stash = self.__dict__.pop("_compact_train", {})
            sources = {}
            for name, pipe in pipes.items():
                arr, orig = stash.get(name) or self._compact_cache_array(
                    np.asarray(pipe.images)
                )
                if arr.dtype != orig:
                    self._cache_cast[(id(net), name)] = jnp.dtype(orig)
                sources[name] = (arr, pipe.labels, pipe.batchsize)
            def put(a, name, kind):
                # staged blocks land DATA-SHARDED along the stacked
                # batch dim (the same batch shardings the sync path
                # uses): each device receives only its 1/ndata slice of
                # the block instead of a full-block broadcast — on wide
                # meshes the host->device traffic drops by the data
                # width. The scan body's gather + batch constraint
                # reassemble exactly the sync path's per-step batches.
                sh = self.batch_sh.get(name)
                sh = sh[kind] if sh is not None else self._repl
                # stager-thread span (obs/): each staged block's
                # host->device commit becomes its own trace track
                rec = self.telemetry
                if rec is None:
                    return jax.device_put(jnp.asarray(a), sh)
                with rec.span("stage_block", track="stager"):
                    return jax.device_put(jnp.asarray(a), sh)

            self._stager = ChunkStager(
                sources,
                self._batches_per_step,
                schedule=self._stream_schedule,
                cursors=lambda: {
                    name: pipe.position for name, pipe in pipes.items()
                },
                put=put,
            )
        return self._stager

    def _stream_schedule(self, step: int) -> int:
        """The stager's window-length oracle: exactly the run() loop's
        chunk lengths (deterministic in ``step``), 0 past the end."""
        if step >= self.cfg.train_steps:
            return 0
        return self._chunk_len(step)

    def _step_via_chunk(self, step: int) -> bool:
        """Whether a length-1 window in stream mode still runs through
        train_chunk (keeping the stager's schedule unbroken). Subclasses
        with a per-step warmup phase (the replica trainer) defer."""
        del step
        return True

    # ------------------------------------------------------------------
    # host-side loop
    # ------------------------------------------------------------------

    def _next_batch(self, net: Net) -> dict:
        """One batch dict for ``net``'s data layers: index feeds
        (device-cached), a feeder buffer swap (prefetch mode), or
        host assembly + transfer on the calling thread."""
        if self._cached:
            out = {}
            for name, pipe in self._pipelines[id(net)].items():
                d = self._dev_data[id(net)][name]
                out[name] = {
                    "__idx__": jnp.asarray(pipe.next_indices()), **d
                }
            return out
        if net is self.train_net and self.feeder_mode == "prefetch":
            feeder = self._device_feeder()
            batch = feeder.next()
            self._feeder_positions = dict(feeder.consumed_positions)
            return batch
        return self._assemble_host_batch(net)

    def _assemble_host_batch(self, net: Net) -> dict:
        """Host-side batch assembly + device_put (the synchronous path;
        also the body the device feeder runs on its thread)."""
        out = {}
        for name, pipe in self._pipelines[id(net)].items():
            images, labels = pipe.next_batch()
            sh = self.batch_sh.get(name)
            leaf_i = sh["image"] if sh and net is self.train_net else self._repl
            leaf_l = sh["label"] if sh and net is self.train_net else self._repl
            out[name] = {
                "image": jax.device_put(images, leaf_i),
                "label": jax.device_put(labels, leaf_l),
            }
        return out

    def train_one_batch(self, step: int) -> None:
        """TrainOneBatch (worker.cc:304-316): one forward+backward+update."""
        if self.telemetry is not None:
            self.telemetry.step = step  # cheap attribute stamp, no I/O
        with self.timers.phase("data"):
            batch = self._next_batch(self.train_net)
        if self.resilience is not None:
            # nanloss@step fault seam (resilience/faults.py)
            batch = self.resilience.inject_batch_faults(self, step, batch)
        self._last_batch = batch  # debug dumps reuse it (no stream skew)
        rng = jax.random.fold_in(self._step_key, step)
        with self.timers.phase("train"):
            (self.params, self.state, self.buffers, metrics) = (
                self._train_step(
                    self.params, self.state, self.buffers,
                    jnp.int32(step), batch, rng,
                )
            )
        self.perf.update(metrics)

    # ------------------------------------------------------------------
    # multi-step chunks (device-cached datasets only)
    # ------------------------------------------------------------------

    def _can_chunk(self) -> bool:
        """Chunking folds N steps into one lax.scan dispatch. It needs the
        dataset on device (batch = index math inside the program) and no
        per-step host work (debug dumps want _last_batch)."""
        if not self._cached or self.cfg.debug:
            return False
        if self.resilience is not None and self.resilience.per_step:
            # a pending fault plan needs exact per-step boundaries
            return False
        return self._chunk_cap() > 1

    def _chunk_cap(self) -> int:
        return int(os.environ.get("SINGA_TPU_CHUNK", "64"))

    @staticmethod
    def _flat_batch_indices(pos0, i, bs: int, n: int):
        """Sequential-wraparound record indices of batch ``i`` from
        stream position ``pos0`` — the base stream-index math shared by
        the train chunk and the (always-flat) eval chunk."""
        return (pos0 + i * bs + jnp.arange(bs)) % n

    def _chunk_batch_indices(self, pos0, i, bs: int, n: int):
        """Record indices of scan-iteration ``i``'s batch (the replica
        trainer overrides with a (replicas, batch) grid)."""
        return self._flat_batch_indices(pos0, i, bs, n)

    def _chunk_meta(self, nsteps: int) -> dict[str, tuple[int, int]]:
        """{layer: (batchsize, gather length)} for a chunk program over
        ``nsteps`` steps: the device-cached dataset's record count, or —
        streaming — the staged block's length. With pos0 = 0 and n = the
        block length, the SAME wraparound index math that walks the
        cached dataset walks the staged block row-exactly (the real
        stream's wraparound was applied at staging time, on the host)."""
        pipes = self._pipelines[id(self.train_net)]
        if self.feeder_mode == "stream":
            return {
                name: (
                    pipe.batchsize,
                    nsteps * self._batches_per_step * pipe.batchsize,
                )
                for name, pipe in pipes.items()
            }
        return {
            name: (pipes[name].batchsize, pipes[name].n)
            for name in self._dev_data[id(self.train_net)]
        }

    def _chunk_body(self, nsteps: int, meta=None) -> Callable:
        """The UNJITTED nsteps-step scan body: (params, state, buffers,
        step0, pos0s, data) -> (params, state, buffers, summed_metrics).
        _make_chunk_fn jits it; the replica trainer composes it with a
        protocol round in one program (fused sync windows — which pass
        the WHOLE multi-window meta so inner windows index into the
        full staged block)."""
        if meta is None:
            meta = self._chunk_meta(nsteps)

        # the cached dataset enters as an ARGUMENT, not a closure capture:
        # captured arrays lower to embedded constants, which some runtimes
        # re-upload on every execution (catastrophic through a tunneled
        # device); as an argument it stays resident and is passed by ref
        def chunk_fn(params, state, buffers, step0, pos0s, data):
            def body(carry, i):
                params, state, buffers = carry
                step = step0 + i
                batch = {}
                for name, d in data.items():
                    bs, n = meta[name]
                    idx = self._chunk_batch_indices(pos0s[name], i, bs, n)
                    batch[name] = {"__idx__": idx, **d}
                batch = self._resolve_batch(self.train_net, batch)
                rng = jax.random.fold_in(self._step_key, step)
                params, state, buffers, metrics = self._train_step_fn(
                    params, state, buffers, step, batch, rng
                )
                return (params, state, buffers), metrics

            (params, state, buffers), metrics = jax.lax.scan(
                body, (params, state, buffers), jnp.arange(nsteps)
            )
            # sum the per-step metrics inside the program: one dispatch
            # total, no (nsteps,)-stacked metrics round trip
            return params, state, buffers, jax.tree.map(
                lambda a: a.sum(axis=0), metrics
            )

        return chunk_fn

    def _make_chunk_fn(self, nsteps: int) -> Callable:
        return jax.jit(self._chunk_body(nsteps), donate_argnums=(0, 1, 2))

    def train_chunk(self, step0: int, nsteps: int) -> None:
        """Run nsteps consecutive train steps as ONE compiled program.

        Semantically identical to nsteps train_one_batch calls: the same
        sequential-wraparound batch indices (computed on device from the
        stream positions), the same per-step rng folds, the same updater
        schedule (each scan iteration sees its true step number)."""
        if nsteps not in self._chunk_fns:
            self._chunk_fns[nsteps] = self._make_chunk_fn(nsteps)
        self._run_chunk(self._chunk_fns[nsteps], (), step0, nsteps)

    def _run_chunk(self, fn, extra_in: tuple, step0: int, nsteps: int):
        """Shared chunk-dispatch scaffolding (ONE copy — the replica
        trainer's fused sync windows reuse it).

        ``fn(params, state, buffers, *extra_in, step0, pos0s, data) ->
        (params, state, buffers, *extra_out, summed_metrics)``;
        ``extra_out`` (protocol state carried through a fused program)
        is handed to _store_chunk_extras. ``data`` is the device-cached
        dataset, or — streaming — the double-buffered staged block
        (normally already transferred; the data phase then times only
        the buffer swap)."""
        pipes = self._pipelines[id(self.train_net)]
        streaming = self.feeder_mode == "stream"
        if self.telemetry is not None:
            self.telemetry.step = step0  # cheap attribute stamp, no I/O
        with self.timers.phase("data", steps=nsteps):
            if streaming:
                data, after = self._chunk_stager().take(step0, nsteps)
                pos0s = {name: jnp.int32(0) for name in pipes}
            else:
                pos0s = {
                    name: jnp.int32(pipe.position)
                    for name, pipe in pipes.items()
                }
                data = self._dev_data[id(self.train_net)]
        with self.timers.phase("train", steps=nsteps):
            out = fn(
                self.params, self.state, self.buffers, *extra_in,
                jnp.int32(step0), pos0s, data,
            )
        self.params, self.state, self.buffers, *extra_out, summed = out
        if extra_out:
            self._store_chunk_extras(tuple(extra_out))
        if streaming:
            # the stager owns the stream cursor (its thread must not
            # race the pipelines); re-sync the pipelines at the window
            # boundary so checkpoints see the consumed position
            for name, pipe in pipes.items():
                pipe.seek(after[name])
        else:
            for name, pipe in pipes.items():
                pipe.advance(nsteps * self._batches_per_step)
        # metrics arrive pre-summed over the chunk; Performance pulls to
        # host only at display time
        self.perf.update_summed(summed, nsteps)

    def _store_chunk_extras(self, extra: tuple) -> None:
        raise NotImplementedError(
            "chunk fn returned extra outputs but no handler is defined"
        )

    def _next_fire(self, cur: int, freq: int, after: int) -> float:
        """Smallest s >= cur with _now(s, freq, after), or +inf."""
        if freq <= 0:
            return float("inf")
        base = max(cur, after)
        return base + (-(base - after)) % freq

    def _chunk_len(self, step: int) -> int:
        """Steps until the next cadence event bounds the chunk: val/test
        run BEFORE their trigger step (chunk must stop short of it);
        display/checkpoint run AFTER theirs (it may close the chunk)."""
        cfg = self.cfg
        n = min(cfg.train_steps - step, self._chunk_cap())
        if self.val_net is not None:
            fire = self._next_fire(
                step + 1, cfg.validation_frequency, cfg.validation_after_steps
            )
            n = min(n, fire - step)
        if self.test_net is not None:
            fire = self._next_fire(
                step + 1, cfg.test_frequency, cfg.test_after_steps
            )
            n = min(n, fire - step)
        fire = self._next_fire(
            step, cfg.display_frequency, cfg.display_after_steps
        )
        n = min(n, fire - step + 1)
        # checkpoint at step s saves "done = s+1" (see run_one_batch)
        fire = self._next_fire(
            step + 1, cfg.checkpoint_frequency, cfg.checkpoint_after_steps
        )
        n = min(n, fire - step)
        if self._guard is not None and self._guard.policy == "kRollback":
            # the rollback policy reads the consecutive-bad counter at
            # chunk boundaries; cap the chunk so detection lag stays
            # within one rollback window
            n = min(n, self._guard.rollback_after)
        return max(1, int(n))

    def _eval_params(self):
        """Params used by eval steps; replica trainers override this to
        evaluate a single replica's view."""
        return self.params

    def _eval_buffers(self):
        """Buffers used by eval steps (replica trainers evaluate replica
        0's running stats)."""
        return self.buffers

    def _eval_batches(self, net: Net, nsteps: int):
        """Yield ``nsteps`` eval batches. Uncached eval streams ride a
        bounded BurstFeeder (the serving tier's request-batching
        machinery applied to the eval plane — the ROADMAP's eval-stream
        feeder gap): batch k+1 assembles + device_puts on a worker
        thread while eval step k runs, and exactly ``nsteps`` batches
        are drawn, so stream positions advance identically to the
        synchronous path (resume/rollback replay stays exact). Cached
        nets and prefetch-off jobs keep the direct path."""
        if self._cached or not self._prefetch_input:
            for _ in range(nsteps):
                yield self._next_batch(net)
            return
        from ..data.device_prefetch import BurstFeeder

        rec = self.telemetry

        def assemble():
            if rec is None:
                return self._assemble_host_batch(net)
            with rec.span("assemble_batch", track="feeder"):
                return self._assemble_host_batch(net)

        feeder = BurstFeeder(assemble, nsteps)
        try:
            for _ in range(nsteps):
                yield feeder.next()
        finally:
            feeder.reset()

    def _make_eval_chunk_fn(self, net: Net, nsteps: int) -> Callable:
        """One compiled program for a whole eval cadence: scan nsteps
        batches (on-device index math, like _make_chunk_fn) and sum the
        metrics inside the program. The r3 eval path dispatched per
        batch; through the tunnel those round trips dominated the
        flagship 60k-step run's wall clock (BASELINE.md r3 note)."""
        pipes = self._pipelines[id(net)]
        meta = {
            name: (pipes[name].batchsize, pipes[name].n)
            for name in self._dev_data[id(net)]
        }

        def chunk_fn(params, buffers, pos0s, data):
            def body(carry, i):
                batch = {}
                for name, d in data.items():
                    bs, n = meta[name]
                    # eval streams are always flat (no replica grid) —
                    # deliberately the base index math, not
                    # _chunk_batch_indices
                    idx = self._flat_batch_indices(pos0s[name], i, bs, n)
                    batch[name] = {"__idx__": idx, **d}
                metrics = self._eval_batch_metrics(
                    net, params, buffers, batch
                )
                return carry, metrics

            _, metrics = jax.lax.scan(body, 0, jnp.arange(nsteps))
            return jax.tree.map(lambda a: a.sum(axis=0), metrics)

        # like _eval_step_for: params stay live across the eval chunk
        return jax.jit(chunk_fn)  # netlint: disable=JAX003

    def evaluate(self, net: Net, nsteps: int, phase: str, step: int) -> dict:
        """Test/Validate (worker.cc:318-348): nsteps batches, averaged."""
        perf = Performance()
        eval_params = self._eval_params()
        eval_buffers = self._eval_buffers()
        # same opt-outs as the train chunk (_can_chunk: device cache,
        # cfg.debug, SINGA_TPU_CHUNK=1 escape hatch)
        if self._can_chunk() and nsteps > 1 and id(net) in self._dev_data:
            key = (id(net), nsteps)
            if key not in self._eval_chunk_fns:
                self._eval_chunk_fns[key] = self._make_eval_chunk_fn(
                    net, nsteps
                )
            pipes = self._pipelines[id(net)]
            pos0s = {
                name: jnp.int32(pipe.position)
                for name, pipe in pipes.items()
            }
            with self.timers.phase("eval", steps=nsteps):
                summed = self._eval_chunk_fns[key](
                    eval_params, eval_buffers, pos0s,
                    self._dev_data[id(net)],
                )
            for pipe in pipes.values():
                pipe.advance(nsteps)
            perf.update_summed(summed, nsteps)
        else:
            fn = self._eval_step_for(net)
            with self.timers.phase("eval", steps=nsteps):
                for batch in self._eval_batches(net, nsteps):
                    perf.update(fn(eval_params, eval_buffers, batch))
        avg = perf.avg()
        self.log(f"step {step}: {phase} {perf.to_string(avg)}")
        if self.telemetry is not None:
            # avg is already on host (computed for the display line) —
            # the event reuses it, no second device round trip
            self.telemetry.event(
                "eval", step=step, phase=phase, batches=nsteps,
                metrics={l: dict(b) for l, b in avg.items()},
            )
        return avg

    def _pre_events(self, step: int) -> None:
        """Validation/test run BEFORE the train step of their trigger step
        (worker.cc:190-200)."""
        cfg = self.cfg
        if self.val_net is not None and _now(
            step, cfg.validation_frequency, cfg.validation_after_steps
        ):
            self.evaluate(
                self.val_net, cfg.validation_steps, "validation", step
            )
        if self.test_net is not None and _now(
            step, cfg.test_frequency, cfg.test_after_steps
        ):
            self.evaluate(self.test_net, cfg.test_steps, "test", step)

    def _post_events(self, step: int) -> None:
        """Display/checkpoint run AFTER the train step."""
        cfg = self.cfg
        if _now(step, cfg.display_frequency, cfg.display_after_steps):
            sps = steps_s = 0.0
            t = self.timers.total("train") + self.timers.total("data")
            if t > 0:
                sps = self.perf.count * self._batch_size / t
                # steps/s (and tok/s for LM configs) straight from the
                # existing accumulators — perf.count already counts the
                # window's steps, no new host syncs
                steps_s = self.perf.count / t
            rate = f"{sps:.0f} samples/s, {steps_s:.1f} steps/s"
            if self._tokens_per_step and steps_s > 0:
                rate += f", {steps_s * self._tokens_per_step:.0f} tok/s"
            # input-stall readout (the guard-counter pattern): per-window
            # data time and its share of the step path, straight from the
            # timers' existing aggregation — no new per-step host syncs
            stall = ""
            if t > 0:
                stall = (
                    f" data {self.timers.mean_ms('data'):.1f}ms "
                    f"({100.0 * self.timers.share('data', 'train'):.0f}%)"
                )
            # divergence-guard counters ride the display line (ONE host
            # sync, at display cadence — never per step); rollbacks are
            # the context's count
            guard = ""
            g = {}
            if self._guard is not None:
                g = self.guard_counters()
                rb = getattr(self.resilience, "rollbacks", 0)
                guard = (
                    f" guard[bad {g['bad_steps']}, rollbacks {rb}, "
                    f"lr x{g['lr_scale']:g}]"
                )
            # metrics pulled ONCE (the display line's existing sync);
            # the telemetry step record reuses the same host values
            avg = self.perf.avg()
            self.log(
                f"step {step}: train {self.perf.to_string(avg)} "
                f"[{self.timers.to_string()}; {rate}]"
                f"{stall}{guard}"
            )
            if self.telemetry is not None:
                self.telemetry.event(
                    "step",
                    step=step,
                    metrics={l: dict(b) for l, b in avg.items()},
                    phase_ms={
                        p: round(self.timers.mean_ms(p), 3)
                        for p in self.timers.phases()
                    },
                    steps=self.perf.count,
                    samples_per_s=round(sps, 1),
                    steps_per_s=round(steps_s, 3),
                    **(
                        {"tokens_per_s": round(
                            steps_s * self._tokens_per_step, 1
                        )}
                        if self._tokens_per_step
                        else {}
                    ),
                    **({"guard": g} if g else {}),
                )
            if cfg.debug:
                self.log(self.debug_string(step))
            self.perf.reset()
            self.timers.reset()
            if self.telemetry is not None:
                # the cadence boundary is the ONLY step-loop flush point
                self.telemetry.flush()
        # snapshot labels carry the RESUME step (steps completed), matching
        # the end-of-run save and restore_into's start_step contract — so a
        # resumed run never replays the step it saved after
        done = step + 1
        if (
            _now(done, cfg.checkpoint_frequency, cfg.checkpoint_after_steps)
            and done > self.start_step
            and done < cfg.train_steps  # run() writes the final snapshot
        ):
            self.save(done)

    def run_one_batch(self, step: int) -> None:
        """RunOneBatch (worker.cc:187-213): cadences around the train step."""
        self._pre_events(step)
        self.train_one_batch(step)
        self._post_events(step)

    def run(self) -> None:
        """Worker::Run (worker.cc:98-106): the full training loop.

        With a device-cached dataset the loop advances in multi-step
        chunks (one compiled scan per cadence window); otherwise it is the
        reference's step-at-a-time loop."""
        if self.cluster is not None and self.cluster.workspace:
            vis = os.path.join(
                self.cluster.workspace, self.cluster.vis_subfolder
            )
            for net in (self.train_net, self.test_net, self.val_net):
                if net is not None:
                    dump_net_json(net, vis)
        # comm-cost calibration span (grad_comm + telemetry only; a
        # one-shot probe off the step path) + the grad_allreduce
        # kernel_select run-start event
        self._maybe_emit_kernel_select()
        self._maybe_record_comm_probe()
        # streaming scan chunks: a non-cached dataset no longer falls
        # back to one dispatch per step — the stager feeds the same
        # _run_chunk scan path from double-buffered staged blocks
        streaming = self.feeder_mode == "stream"
        chunking = self._can_chunk() or streaming
        ctx = self.resilience
        step = self.start_step
        self.completed_steps = step
        while step < self.cfg.train_steps:
            if ctx is not None:
                # step-boundary seam: watchdog heartbeat, fault
                # injection, preemption drain (may raise)
                ctx.before_step(self, step)
            n = self._chunk_len(step) if chunking else 1
            self._pre_events(step)
            if n > 1 or (streaming and self._step_via_chunk(step)):
                # streaming routes length-1 windows through train_chunk
                # too: the stager's block schedule stays unbroken
                self.train_chunk(step, n)
            else:
                self.train_one_batch(step)
            self._post_events(step + n - 1)
            step += n
            if ctx is not None:
                # guard rollback may rewind to the last checkpoint
                step = ctx.after_step(self, step)
            self.completed_steps = step
        if self._checkpoint_dir() is not None:
            self.save(self.cfg.train_steps)

    # ------------------------------------------------------------------
    # checkpoint + debug
    # ------------------------------------------------------------------

    def _checkpoint_dir(self) -> str | None:
        if self.cluster is not None and self.cluster.workspace:
            return os.path.join(self.cluster.workspace, "checkpoints")
        return None

    def _stream_positions(self) -> dict[str, int]:
        out = {}
        for net in (self.train_net, self.test_net, self.val_net):
            if net is None:
                continue
            for name, pipe in self._pipelines[id(net)].items():
                out[f"{net.phase}|{name}"] = pipe.position
        # device-feeder mode: the pipelines run ahead of the trainer by
        # the feeder's read-ahead — checkpoint the CONSUMED positions
        out.update(self._feeder_positions)
        return out

    def save(self, step: int) -> str | None:
        folder = self._checkpoint_dir()
        if folder is None:
            return None
        ctx = self.resilience
        writer = ctx.async_ckpt if ctx is not None else None
        rec = self.telemetry
        if writer is None:
            # the ckpt phase times the save's step-path cost (sync: the
            # whole serialize; async below: snapshot + submit only) —
            # tools/trace.py's stall shares read it
            with self.timers.phase("ckpt"):
                path, write = self._prepare_save(folder, step, snapshot=False)
                write()
            self.log(f"step {step}: checkpoint -> {path}")
            if rec is not None:
                rec.event("ckpt_save", step=step, path=path, mode="sync")
            if ctx is not None:
                # corrupt_ckpt fault, completeness validation, LATEST
                # marking, keep-last-N retention (resilience/retention.py)
                ctx.checkpoint_written(self, path, step)
            return path
        # --- zero-stall path (resilience/async_ckpt.py): snapshot the
        # state with one non-donating device-copy program, start the
        # device->host DMA, and hand serialization to the writer thread.
        # The step loop continues immediately; validation/LATEST/
        # retention run from the writer via the same checkpoint_written
        # seam, in submit (= step) order. ---
        with self.timers.phase("ckpt"):
            path, write = self._prepare_save(folder, step, snapshot=True)
            writer.submit(
                step, path, write,
                on_written=lambda p, s: ctx.checkpoint_written(self, p, s),
            )
        self.log(f"step {step}: checkpoint (async) -> {path}")
        if rec is not None:
            rec.event("ckpt_save", step=step, path=path, mode="async")
        return path

    def _manifest_extra(self) -> dict:
        """Extra promises for a sharded save's manifest. The replica
        engine overrides to promise its ``.server`` sidecar
        (``{"sidecar": True}``) so retention can refuse a save whose
        sidecar tore or never landed (resilience/coord.py sidecar
        commit markers)."""
        return {}

    def _prepare_save(self, folder: str, step: int, snapshot: bool):
        """-> (final path, zero-arg write closure) for one checkpoint.

        ``snapshot=False`` captures the LIVE arrays (the synchronous
        path — the closure runs before the next step). ``snapshot=True``
        captures fresh device-side COPIES with their host transfers
        already started, so the closure is safe to run from the async
        writer thread while the (donating) train loop advances: it only
        materializes host buffers and writes files, never dispatches new
        device programs."""
        # a model axis spanning process boundaries (cross-process
        # kLayerPartition) leaves params PARTITIONED with shards this
        # host cannot see: the host-gathering npz writer cannot
        # materialize them. The per-process sharded format exists for
        # exactly this topology — auto-upgrade rather than crash at the
        # end of a training run. Fully-replicated multi-process arrays
        # are fine for npz (every host holds the whole value), so they
        # keep the configured format.
        def _spanning(arrs):
            return any(
                not v.is_fully_addressable
                and not v.sharding.is_fully_replicated
                for v in arrs
            )

        # check params AND state AND buffers: they can disagree — e.g.
        # the replica engine's protocol round returns params replicated
        # (the scan re-lays them out) while updater slots keep the
        # process-spanning replica sharding
        spans_procs = (
            _spanning(self.params.values())
            or _spanning(
                v for slots in self.state.values() for v in slots.values()
            )
            or _spanning(self.buffers.values())
        )
        sharded = self.cfg.checkpoint_format == "sharded" or spans_procs
        streams = self._stream_positions()
        if snapshot:
            # the sharded format stores STORED (padded) shapes; npz
            # stores LOGICAL ones, so its snapshot program unpads inside
            # the same dispatch
            params, state, buffers = self._snapshot_trees(unpad=not sharded)
            for leaf in jax.tree.leaves((params, state, buffers)):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
        elif sharded:
            params, state, buffers = self.params, self.state, self.buffers
        else:
            # npz checkpoints are host-gathered and mesh-portable: store
            # LOGICAL shapes (a resume onto a different model-axis width
            # re-pads for its own mesh)
            params = self._unpad_stored(self.params)
            state = self._unpad_state(self.state)
            buffers = self.buffers
        if sharded:
            from .sharded_ckpt import save_sharded

            path = os.path.join(folder, f"step_{step}.ckpt")
            extra = self._manifest_extra()

            def write() -> None:
                save_sharded(
                    path, step, params, state, buffers, streams=streams,
                    manifest_extra=extra,
                )

        else:
            path = os.path.join(folder, f"step_{step}.npz")
            if jax.process_index() != 0:
                # npz checkpoints are host-gathered and identical on
                # every rank (the spanning check above upgraded any
                # partitioned state to the sharded format): one writer
                # suffices, and N ranks racing os.replace on the same
                # shared-FS file is N-1 wasted writes plus a window for
                # a half-renamed observation. Rank 0 writes.
                def write() -> None:
                    return None

            else:

                def write() -> None:
                    save_checkpoint(
                        path, step, params, state, buffers, streams=streams
                    )

        return path, write

    def _snapshot_trees(self, unpad: bool):
        """Donation-safe device copies of (params, state, buffers) in
        ONE compiled program (npz variant also unpads inside it). The
        copies are fresh buffers the async writer owns outright — the
        live training arrays stay valid for the next, donating, train
        step, and the writer thread never has to dispatch device work."""
        if unpad not in self._snapshot_fns:

            def snap(params, state, buffers):
                params, state, buffers = jax.tree.map(
                    jnp.copy, (params, state, buffers)
                )
                if unpad:
                    params = self._unpad_stored(params)
                    state = self._unpad_state(state)
                return params, state, buffers

            # snapshots must NOT donate: the inputs are the live params
            self._snapshot_fns[unpad] = jax.jit(snap)  # netlint: disable=JAX003
        return self._snapshot_fns[unpad](self.params, self.state, self.buffers)

    # ------------------------------------------------------------------
    # resilience: rollback + guard state (resilience/context.py calls)
    # ------------------------------------------------------------------

    def rollback_to(self, path: str) -> int:
        """Mid-run restore of params/state/buffers/stream-positions from
        checkpoint ``path`` (the divergence guard's rollback). Returns
        the checkpoint's step — where the cadence loop continues."""
        self.cfg.checkpoint = path
        # take the checkpoint's own step: the pre-rollback resume step
        # is ahead of where training is being rewound to
        self.start_step = 0
        self._materialize_params()
        self._seek_resumed_streams()
        self.completed_steps = self.start_step
        return self.start_step

    def set_guard_state(
        self, consec: int | None = None, lr_scale: float | None = None
    ) -> None:
        """Host-side overwrite of the guard counters (rollback resets
        the consecutive count and compounds the LR backoff)."""
        if consec is not None:
            self.buffers[GUARD_CONSEC] = jax.device_put(
                jnp.int32(consec), self._repl
            )
        if lr_scale is not None:
            self.buffers[GUARD_LR] = jax.device_put(
                jnp.float32(lr_scale), self._repl
            )

    def guard_counters(self) -> dict[str, float]:
        """Pull the guard counters to host — ONE device sync, so call at
        cadence boundaries (display, end of run), never per step."""
        if self._guard is None:
            return {}
        return {
            "consecutive_bad": int(self.buffers[GUARD_CONSEC]),
            "bad_steps": int(self.buffers[GUARD_BAD]),
            "lr_scale": float(self.buffers[GUARD_LR]),
        }

    def debug_string(self, step: int) -> str:
        """Per-layer mean-|activation| + per-param mean-|value| lines, the
        reference's debug dump (worker.cc:262-265, neuralnet.cc:350-378).
        Reuses the step's own batch — debug mode must not consume extra
        training data or shift the stream position."""
        batch = self._resolve_batch(
            self.train_net, self._last_batch, constrain=False
        )
        rng = jax.random.fold_in(self._step_key, step)
        _, _, acts = self.train_net.forward(
            self.params, batch, training=True, rng=rng,
            buffers=self.buffers, return_acts=True,
        )
        lines = [
            "debug: "
            + ", ".join(
                f"{name} {float(jnp.mean(jnp.abs(a))):.4g}"
                for name, a in acts.items()
                if hasattr(a, "dtype")
            )
        ]
        lines.append(
            "params: "
            + ", ".join(
                f"{n} {float(jnp.mean(jnp.abs(v))):.4g}"
                for n, v in sorted(self.params.items())
            )
        )
        return "\n".join(lines)
