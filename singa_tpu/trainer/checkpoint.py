"""Checkpoint/resume.

Fills the reference's declared-but-unimplemented resume path: Worker::Resume
is a TODO (src/worker/worker.cc:65-67), Layer::ToProto is empty
(src/worker/base_layer.cc:37-38), and ModelProto.step ("last snapshot step",
src/proto/model.proto:35) plus ParamProto.kPretrained (model.proto:79) are
parsed but never honored. Here they are:

  - save_checkpoint writes step + params + updater slots as one .npz,
    atomically (tmp file + rename) so a crash mid-write never corrupts the
    latest checkpoint — the same torn-write discipline as
    Shard::PrepareForAppend (src/utils/shard.cc:175-206).
  - data-stream positions ride along ("d|<phase>|<layer>" keys): each
    pipeline's CONSUMED position, so a resumed run continues the stream
    exactly where training stopped instead of silently replaying from
    the shard start. The one-time random_skip draw is baked into the
    position, so no RNG state needs separate persistence.
  - restore ModelConfig.checkpoint -> params/state/step before training;
    kPretrained params take their value from it. Checkpoints written
    before the stream section simply restore with no positions (stream
    starts over — the old behavior).
"""

from __future__ import annotations

import contextlib
import os
import tempfile

import jax.numpy as jnp
import numpy as np

class CheckpointError(RuntimeError):
    """A checkpoint file is missing, unreadable, or corrupt.

    The atomic-write discipline means a checkpoint singa-tpu wrote is
    either complete or absent — so corruption implies external damage,
    and the operator deserves one clear error instead of whatever
    np.load's zip layer leaks (BadZipFile/KeyError/OSError/...;
    corruption-probe-pinned in tests)."""


_STEP_KEY = "__step__"
_P = "p|"  # param arrays
_S = "s|"  # updater slot arrays, "s|<param>|<slot>"
_B = "b|"  # buffer arrays (stateful-layer state, e.g. BN running stats)
_D = "d|"  # data-stream positions, "d|<phase>|<layer>"


def save_checkpoint(
    path: str,
    step: int,
    params: dict[str, jnp.ndarray],
    state: dict[str, dict[str, jnp.ndarray]] | None = None,
    buffers: dict[str, jnp.ndarray] | None = None,
    streams: dict[str, int] | None = None,
) -> str:
    """Atomic .npz snapshot; returns the final path."""
    arrays: dict[str, np.ndarray] = {_STEP_KEY: np.int64(step)}
    for name, arr in params.items():
        arrays[_P + name] = np.asarray(arr)
    for name, slots in (state or {}).items():
        for slot, arr in slots.items():
            arrays[f"{_S}{name}|{slot}"] = np.asarray(arr)
    for name, arr in (buffers or {}).items():
        arrays[_B + name] = np.asarray(arr)
    for name, pos in (streams or {}).items():
        arrays[_D + name] = np.int64(pos)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_checkpoint(
    path: str,
) -> tuple[
    int,
    dict[str, np.ndarray],
    dict[str, dict[str, np.ndarray]],
    dict[str, np.ndarray],
]:
    """-> (step, params, state, buffers). Stream positions via
    load_stream_positions (kept out of this signature for the callers
    that only want arrays). Raises CheckpointError on a missing or
    corrupt file."""
    with _open_checkpoint(path) as z:
        step = int(z[_STEP_KEY])
        params: dict[str, np.ndarray] = {}
        state: dict[str, dict[str, np.ndarray]] = {}
        buffers: dict[str, np.ndarray] = {}
        for key in z.files:
            if key.startswith(_P):
                params[key[len(_P):]] = z[key]
            elif key.startswith(_S):
                name, slot = key[len(_S):].rsplit("|", 1)
                state.setdefault(name, {})[slot] = z[key]
            elif key.startswith(_B):
                buffers[key[len(_B):]] = z[key]
    return step, params, state, buffers


@contextlib.contextmanager
def _open_checkpoint(path: str):
    """np.load with the CheckpointError policy: one place owns the
    missing-vs-corrupt distinction for every load path."""
    try:
        with np.load(path) as z:
            yield z
    except CheckpointError:
        raise
    except FileNotFoundError:
        raise CheckpointError(f"checkpoint not found: {path!r}") from None
    except Exception as e:
        raise CheckpointError(
            f"corrupt or unreadable checkpoint {path!r}: "
            f"{type(e).__name__}: {e}"
        ) from e


def load_stream_positions(path: str) -> dict[str, int]:
    """-> {"<phase>|<layer>": consumed position} from the checkpoint
    (empty for checkpoints written before the stream section existed).
    Raises CheckpointError like load_checkpoint."""
    with _open_checkpoint(path) as z:
        return {
            key[len(_D):]: int(z[key])
            for key in z.files
            if key.startswith(_D)
        }


def restore_into(
    path: str,
    params: dict[str, jnp.ndarray],
    state: dict[str, dict[str, jnp.ndarray]],
    buffers: dict[str, jnp.ndarray] | None = None,
) -> tuple[int, dict, dict, dict]:
    """Overlay a checkpoint onto freshly-initialized pytrees.

    Params present in the checkpoint replace their initialized values
    (this is what makes kPretrained's zeros-then-fill contract work);
    params absent from it keep their init. Shape mismatches are an error —
    better loud than silently truncated.
    """
    step, ck_params, ck_state, ck_buffers = load_checkpoint(path)
    out_p = dict(params)
    for name, arr in ck_params.items():
        if name in out_p:
            if tuple(arr.shape) != tuple(out_p[name].shape):
                raise ValueError(
                    f"checkpoint {path!r}: param {name!r} shape "
                    f"{arr.shape} != model shape {out_p[name].shape}"
                )
            out_p[name] = jnp.asarray(arr)
    out_s = {n: dict(slots) for n, slots in state.items()}
    for name, slots in ck_state.items():
        if name in out_s:
            for slot, arr in slots.items():
                if slot in out_s[name]:
                    out_s[name][slot] = jnp.asarray(arr)
    out_b = dict(buffers or {})
    for name, arr in ck_buffers.items():
        if name in out_b:
            if tuple(arr.shape) != tuple(out_b[name].shape):
                raise ValueError(
                    f"checkpoint {path!r}: buffer {name!r} shape "
                    f"{arr.shape} != model shape {out_b[name].shape}"
                )
            out_b[name] = jnp.asarray(arr)
    return step, out_p, out_s, out_b
