"""Contrastive-divergence trainer (the reference's missing "CD worker").

ModelProto.alg == kContrastiveDivergence is declared in the reference
(src/proto/model.proto:40-44) with a TrainOneBatch comment splitting the
worker into BP and CD variants (include/worker/base_layer.h:96-97), but no
CD worker exists in that snapshot. This trainer fills the hole: the net is
a chain data -> parsers -> kRBM+ (stacked RBMs), and one jitted step runs
greedy layerwise CD — each RBM gets a CD-k update on the mean-field hidden
activations of the (simultaneously training) RBM below it, the
whole stack in a single XLA program. Stacked pretraining feeds a deep
autoencoder: snapshot the pretrained stack, then kPretrained-init the
unrolled MLP (kEuclideanLoss) and fine-tune with the default BP trainer.

Reuses the whole Trainer cadence loop, updaters (momentum/weight-decay/LR
schedules apply to CD grads exactly as they would to BP grads), mesh
shardings, checkpointing, and observability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config.schema import ConfigError
from ..layers.rbm import RBMLayer
from ..resilience.guard import grad_norm_sq
from .trainer import Trainer


def unroll_autoencoder(
    ckpt_in: str, ckpt_out: str, pairs: list[tuple[str, str]]
) -> str:
    """Unroll a pretrained RBM stack into autoencoder decoder weights.

    For each (rbm_layer, decoder_layer) pair, the decoder InnerProduct
    layer gets weight = rbm_weight^T and bias = rbm_vbias (the classic
    Hinton unrolling); encoder layers pick their weights up by name, so
    name the encoder's kInnerProduct layers after the RBMs. The result is
    a checkpoint for ModelConfig.checkpoint / kPretrained init.
    """
    from .checkpoint import load_checkpoint, save_checkpoint

    step, params, state, _ = load_checkpoint(ckpt_in)
    out = dict(params)
    for rbm, dec in pairs:
        w = params.get(f"{rbm}/weight")
        vb = params.get(f"{rbm}/vbias")
        hb = params.get(f"{rbm}/hbias")
        if w is None or vb is None or hb is None:
            raise ConfigError(
                f"checkpoint {ckpt_in!r} has no RBM params for {rbm!r}"
            )
        out[f"{dec}/weight"] = w.T
        out[f"{dec}/bias"] = vb
        # the encoder InnerProduct's bias is the RBM's hidden bias
        out[f"{rbm}/bias"] = hb
    # step 0: fine-tuning starts a fresh step counter, not the CD one
    return save_checkpoint(ckpt_out, 0, out)


class CDTrainer(Trainer):
    """Trainer whose compiled step does CD-k instead of backprop."""

    _supports_buffers = False  # the CD step rewires forward via layer_hook
    #: the CD step's layer-hooked Gibbs walk is not shard_map-wrapped:
    #: quantized grad_comm rides the reference seam (fp32 on the wire);
    #: kernels { grad_allreduce: quantized_ring } is rejected loudly
    _supports_ring_collective = False

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._rbms = [
            l for l in self.train_net.layers if isinstance(l, RBMLayer)
        ]
        if not self._rbms:
            raise ConfigError(
                "alg kContrastiveDivergence requires at least one kRBM layer"
            )
        if self.train_net.losslayers:
            raise ConfigError(
                "kContrastiveDivergence is unsupervised: remove loss layers "
                "(fine-tune the unrolled net with alg kBackPropagation)"
            )

    # ------------------------------------------------------------------

    def _step_core(self, params, state, buffers, step, batch, rng, lr_scale):
        """One jitted CD step: walk the net through Net.forward (keeping
        its shared-param and connector invariants), swapping each RBM's
        compute for a Gibbs-chain update; then push the collected CD grads
        through the regular updater. Grads never flow *between* RBMs —
        greedy layerwise training by construction.

        Guard seam (resilience/guard.py): the verdict is the finiteness
        of the CD grads' global norm AND every RBM's metrics (there is
        no backprop loss to watch — a NaN batch surfaces in both), and
        ``lr_scale`` folds into the CD grads exactly as it would into
        backprop grads."""
        grads: dict = {}
        metrics: dict = {}

        def hook(layer, resolved, inputs, lrng):
            if isinstance(layer, RBMLayer):
                g, m = layer.cd_grads(resolved, inputs[0], lrng)
                grads.update(g)
                metrics[layer.name] = m
                return layer.prop_up(resolved, inputs[0])
            return None

        self.train_net.forward(
            params, batch, training=True, rng=rng, layer_hook=hook
        )
        # the zero_update/grad_comm seams are engine-independent: CD
        # grads reduce-scatter, quantize, and update shard-local exactly
        # like backprop grads (their error-feedback residuals ride the
        # same buffer pytree)
        grads, comm_bufs = self._reduce_grads(grads, buffers)
        buffers = {**buffers, **comm_bufs}
        ok = None
        if lr_scale is not None:
            ok = jnp.isfinite(grad_norm_sq(grads))
            for leaf in jax.tree.leaves(metrics):
                ok = ok & jnp.all(jnp.isfinite(leaf))
            grads = jax.tree.map(
                lambda g: g * lr_scale.astype(g.dtype), grads
            )
        rbm_params = {n: params[n] for n in grads}
        rbm_state = {n: state[n] for n in grads}
        new_p, new_s = self._apply_update(step, rbm_params, grads, rbm_state)
        params = {**params, **new_p}
        state = {**state, **new_s}
        return params, state, buffers, metrics, ok

    def _eval_batch_metrics(self, net, params, buffers, batch) -> dict:
        """Eval metric per RBM: mean-field reconstruction error.

        Overrides the base seam, so both the per-step eval loop and the
        chunked eval scan compute CD metrics."""
        del buffers  # CD nets carry no stateful layers
        batch = self._resolve_batch(net, batch)
        metrics: dict = {}

        def hook(layer, resolved, inputs, lrng):
            if isinstance(layer, RBMLayer):
                metrics[layer.name] = {
                    "loss": layer.recon_error(resolved, inputs[0])
                }
                return layer.prop_up(resolved, inputs[0])
            return None

        net.forward(params, batch, training=False, layer_hook=hook)
        return metrics
