"""Serving tier: continuous-batching inference with a paged KV cache.

The training stack (trainer/, resilience/, obs/) answers the north
star's "fast and fault-tolerant" half; this package is the "millions of
users" half — the capability analog of the reference's Server tier, one
process answering every worker's kGet/kPut concurrently
(src/server/server.cc; PAPERS.md arxiv 1801.09805 studies exactly this
request-serving-plane bottleneck).

Three layers, each importable alone:

  ``kv_pool``      block-pool KV allocation: fixed-size per-layer pools
                   + per-sequence block tables, so thousands of
                   concurrent streams share device memory instead of
                   each reserving max_len (vLLM's PagedAttention idea,
                   sized for this repo's engines). With
                   ``serving { prefix_cache { enabled } }`` the
                   allocator is a content-addressed, refcounted block
                   cache: full prompt-prefilled blocks are hashed by
                   (prefix-so-far, block tokens), admissions share the
                   longest cached block-prefix instead of re-prefilling
                   it (copy-on-write where a shared block must be
                   written, LRU-parked refcount-0 blocks reclaimed
                   lazily), and streams + the paged cache stay bitwise
                   identical to cold admission.
  ``engine``       the compute plane: ONE donated, jitted fixed-shape
                   decode step over a slot-batched state, plus
                   fixed-shape chunked prefill — admitting/retiring
                   streams never recompiles. Shares the
                   ``cache_attend``/``_block_step`` body with
                   models/transformer.generate, so paged == dense is
                   bitwise by construction.
  ``scheduler``    continuous batching: a request queue admitted into
                   free slots at each decode tick, chunked prefill that
                   never stalls decode, retirement on EOS/budget, and
                   admission backpressure when the block pool is
                   exhausted. Lifecycle events + per-request spans flow
                   into the PR 6 flight recorder; SIGTERM drains via
                   the resilience plane (hand back in-flight sequences,
                   resumable exit 75).

``speculate`` adds model-free multi-token decode on top: an n-gram
prompt-lookup drafter proposes k tokens per live slot per tick, the
engine's fixed-shape VERIFY program scores all (slots, k+1) positions
in one forward (one weight stream for up to k+1 emitted tokens — the
throughput answer to decode being weight-streaming-bound), and a
masked KV rewind keeps the paged cache bitwise what sequential
one-token decode would have written. Token streams are identical to
non-speculative greedy by construction.

``conf_decode`` extends the same KV-cache serving path to conf-surface
nets (tools/generate.py); ``tools/serve_bench.py`` is the load harness
and CI gate.
"""

from .engine import Admission, Engine, EngineConfig  # noqa: F401
from .kv_pool import BlockAllocator, KVPool, PrefixCache  # noqa: F401
from .scheduler import Request, Scheduler  # noqa: F401
from .speculate import NGramDrafter, NullDrafter, make_drafter  # noqa: F401
