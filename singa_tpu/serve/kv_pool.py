"""Paged KV cache: fixed-size block pools + per-sequence block tables.

A dense serving cache reserves ``slots * max_len`` K/V positions per
layer no matter how long each stream actually is; at thousands of
concurrent streams that reservation — not compute — caps concurrency.
Here device memory holds ONE pool of fixed-size blocks per layer,
shaped ``(n_blocks, heads, block_len, head_dim)``, and each sequence
owns an ordered list of block ids (its block table). Admission
allocates exactly the blocks a request's ``prompt + budget`` needs;
retirement returns them; a stream's cache view is a gather of its
table. Blocks are uniform, so the allocator is a free list with zero
external fragmentation — "fragmentation" can only mean internal slack
inside a sequence's last block, bounded by ``block_len - 1`` positions.

Block id 0 is reserved as the TRASH block: it is never allocated, table
rows are initialized to it, and fixed-shape prefill chunks route their
padding-position writes at it. Gathers may therefore read it freely —
``models.transformer.cache_attend`` masks every cache entry beyond a
query's position to -1e30 before the softmax, so trash contents never
move an output bit (the parity tests pin this).

The speculative verify tick (serve/engine.py ``Engine._verify``)
extends the same contract to MULTI-POSITION writes: a slot's chunk of
k+1 candidate positions maps through its table to (block, offset)
pairs exactly as single-token decode does, and the post-acceptance
scatter routes every REJECTED position's write to the trash block —
the KV rewind. Rejected positions' pool bytes are therefore never
touched, which is what makes "un-advance the cache" an exact no-op
rather than a restore. Allocation is untouched by speculation: blocks
for ``prompt + budget`` are claimed all-or-nothing at admission (and
freed only at retirement/drain), so an accept/reject pattern can never
strand or leak a block — the accepted-length lane only gates which
allocated positions hold real entries.

The allocator is host-side bookkeeping (admission-path work, like the
reference Server's per-param shard map, src/server/server.cc); the
pools themselves live in the engine's donated device state.
"""

from __future__ import annotations

import dataclasses


class PoolExhausted(Exception):
    """No free blocks for an allocation — the scheduler's admission
    backpressure signal (queued requests wait for a retirement)."""


@dataclasses.dataclass(frozen=True)
class KVPool:
    """Static geometry of the paged cache (the device arrays themselves
    ride the engine's state pytree)."""

    n_blocks: int          # total blocks INCLUDING the reserved trash block
    block_len: int         # positions per block
    max_blocks_per_seq: int  # table width = ceil(max_len / block_len)

    @property
    def cache_len(self) -> int:
        """Gathered per-sequence cache length (= padded max_len)."""
        return self.max_blocks_per_seq * self.block_len

    @classmethod
    def for_model(cls, max_len: int, block_len: int, n_blocks: int = 0,
                  slots: int = 1) -> "KVPool":
        """Geometry for a model with ``max_len`` positions. ``n_blocks``
        0 sizes the pool so every slot can hold a full-length sequence
        (+ the trash block) — the dense-equivalent upper bound; smaller
        explicit pools oversubscribe and rely on backpressure."""
        if block_len < 1:
            raise ValueError(f"kv_block_len must be >= 1, got {block_len}")
        if max_len % block_len:
            raise ValueError(
                f"kv_block_len {block_len} must divide max_len {max_len} "
                "(keeps the gathered cache length equal to the dense "
                "cache, so paged == dense stays bitwise)"
            )
        per_seq = max_len // block_len
        if not n_blocks:
            n_blocks = slots * per_seq + 1
        if n_blocks < per_seq + 1:
            raise ValueError(
                f"kv_blocks {n_blocks} cannot hold even one full "
                f"sequence ({per_seq} blocks) plus the trash block"
            )
        return cls(n_blocks, block_len, per_seq)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks a sequence of ``n_tokens`` total positions needs."""
        return -(-max(1, n_tokens) // self.block_len)

    def block_offset(self, position: int) -> tuple[int, int]:
        """Absolute sequence position -> (table row, in-block offset) —
        the host-side mirror of the device-side index math every write
        path (decode, prefill, the speculative verify's multi-position
        scatter) runs; tests pin the two against each other."""
        return position // self.block_len, position % self.block_len


class BlockAllocator:
    """Free-list allocator over a pool's block ids (block 0 reserved)."""

    def __init__(self, pool: KVPool):
        self.pool = pool
        self._free = list(range(pool.n_blocks - 1, 0, -1))  # pop() -> 1,2,..
        self._owned: set[int] = set()
        #: high-water mark of blocks in use (serve_bench's occupancy row)
        self.peak_used = 0

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._owned)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> list[int]:
        """-> ``n`` block ids; raises PoolExhausted leaving the free
        list untouched (the all-or-nothing contract admission needs)."""
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"({len(self._owned)} in use of {self.pool.n_blocks - 1})"
            )
        out = [self._free.pop() for _ in range(n)]
        self._owned.update(out)
        self.peak_used = max(self.peak_used, len(self._owned))
        return out

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if b not in self._owned:
                raise ValueError(
                    f"free of block {b} not handed out by this allocator"
                )
            self._owned.discard(b)
            self._free.append(b)
