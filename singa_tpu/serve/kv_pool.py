"""Paged KV cache: fixed-size block pools, block tables, prefix cache.

A dense serving cache reserves ``slots * max_len`` K/V positions per
layer no matter how long each stream actually is; at thousands of
concurrent streams that reservation — not compute — caps concurrency.
Here device memory holds ONE pool of fixed-size blocks per layer,
shaped ``(n_blocks, heads, block_len, head_dim)``, and each sequence
owns an ordered list of block ids (its block table). Admission
allocates exactly the blocks a request's ``prompt + budget`` needs;
retirement returns them; a stream's cache view is a gather of its
table. Blocks are uniform, so the allocator is a free list with zero
external fragmentation — "fragmentation" can only mean internal slack
inside a sequence's last block, bounded by ``block_len - 1`` positions.

Block id 0 is reserved as the TRASH block: it is never allocated, table
rows are initialized to it, and fixed-shape prefill chunks route their
padding-position writes at it. Gathers may therefore read it freely —
``models.transformer.cache_attend`` masks every cache entry beyond a
query's position to -1e30 before the softmax, so trash contents never
move an output bit (the parity tests pin this).

The speculative verify tick (serve/engine.py ``Engine._verify``)
extends the same contract to MULTI-POSITION writes: a slot's chunk of
k+1 candidate positions maps through its table to (block, offset)
pairs exactly as single-token decode does, and the post-acceptance
scatter routes every REJECTED position's write to the trash block —
the KV rewind. Rejected positions' pool bytes are therefore never
touched, which is what makes "un-advance the cache" an exact no-op
rather than a restore.

PREFIX CACHING turns the allocator into a content-addressed,
refcounted block cache (``serving { prefix_cache { enabled } }``).
Every block carries a refcount. A FULL block — all ``block_len``
positions prefill-written from prompt tokens — is hashed by
``(hash-of-prefix-so-far, block token ids)``, so a block's identity
includes its ENTIRE left context and (via the chain length) its
absolute positions: two requests sharing a system prompt map to the
same digests block for block. At admission the scheduler matches the
incoming prompt's longest cached block-prefix and points the new
sequence's table at the SHARED blocks (refcount bumped); prefill drops
to the uncached tail. Sharing is sound because a fully-prompt-covered
block is immutable — decode and verify only ever write at positions
``>= prompt_len``, which live in later, privately-owned blocks — and
because prefill chunking is bitwise split-invariant (PR 9's pinned
property), a warm sequence's pool bytes are bit-for-bit what its own
cold prefill would have written. The one place a sequence must write
into a shared block — re-deriving the last-token logits when the hit
covers the WHOLE prompt — is COPY-ON-WRITE: the engine copies the
block to a fresh one and repoints only its own table, so sharing stays
invisible to the fixed-shape decode/prefill/verify programs (they
just read through block tables; admit/retire/COW never recompiles).

Retirement decrements refcounts. A refcount-0 block that is REGISTERED
in the prefix index moves to an LRU list instead of the free list —
reclaimed lazily, oldest first, only when an allocation would
otherwise raise PoolExhausted — so backpressure semantics are
unchanged while a warm pool keeps serving hits across request
lifetimes (multi-turn traffic hits its own history).

The allocator is host-side bookkeeping (admission-path work, like the
reference Server's per-param shard map, src/server/server.cc); the
pools themselves live in the engine's donated device state.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib

import numpy as np


class PoolExhausted(Exception):
    """No free (or LRU-reclaimable) blocks for an allocation — the
    scheduler's admission backpressure signal (queued requests wait
    for a retirement)."""


@dataclasses.dataclass(frozen=True)
class KVPool:
    """Static geometry of the paged cache (the device arrays themselves
    ride the engine's state pytree)."""

    n_blocks: int          # total blocks INCLUDING the reserved trash block
    block_len: int         # positions per block
    max_blocks_per_seq: int  # table width = ceil(max_len / block_len)

    @property
    def cache_len(self) -> int:
        """Gathered per-sequence cache length (= padded max_len)."""
        return self.max_blocks_per_seq * self.block_len

    @classmethod
    def for_model(cls, max_len: int, block_len: int, n_blocks: int = 0,
                  slots: int = 1) -> "KVPool":
        """Geometry for a model with ``max_len`` positions. ``n_blocks``
        0 sizes the pool so every slot can hold a full-length sequence
        (+ the trash block) — the dense-equivalent upper bound; smaller
        explicit pools oversubscribe and rely on backpressure."""
        if block_len < 1:
            raise ValueError(f"kv_block_len must be >= 1, got {block_len}")
        if max_len % block_len:
            raise ValueError(
                f"kv_block_len {block_len} must divide max_len {max_len} "
                "(keeps the gathered cache length equal to the dense "
                "cache, so paged == dense stays bitwise)"
            )
        per_seq = max_len // block_len
        if not n_blocks:
            n_blocks = slots * per_seq + 1
        if n_blocks < per_seq + 1:
            raise ValueError(
                f"kv_blocks {n_blocks} cannot hold even one full "
                f"sequence ({per_seq} blocks) plus the trash block"
            )
        return cls(n_blocks, block_len, per_seq)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks a sequence of ``n_tokens`` total positions needs."""
        return -(-max(1, n_tokens) // self.block_len)

    def block_offset(self, position: int) -> tuple[int, int]:
        """Absolute sequence position -> (table row, in-block offset) —
        the host-side mirror of the device-side index math every write
        path (decode, prefill, the speculative verify's multi-position
        scatter) runs; tests pin the two against each other."""
        return position // self.block_len, position % self.block_len


class PrefixCache:
    """Content-addressed index over FULL, prompt-prefilled blocks.

    A block's identity is the chained digest
    ``d_i = H(d_{i-1}, tokens[i*BL : (i+1)*BL])`` (``d_{-1}`` empty):
    the hash covers the block's own token ids AND, through the chain,
    every token to its left — so two blocks are interchangeable iff
    their whole left context matches, which (with prefill's bitwise
    split-invariance) makes their pool bytes interchangeable too. Only
    blocks every position of which was prefill-written from PROMPT
    tokens are registered in the FULL-block index by default:
    decode/verify-written entries ride different compiled shapes (the
    PR 9 cross-shape caveat), so caching them trades the
    bitwise-identical-to-cold guarantee for a token-level one — the
    engine only does so behind ``prefix_cache { decode_blocks }``. The
    index maps digest -> block id; membership is what the allocator's
    release path consults to route a refcount-0 block to the LRU list
    instead of the free list.

    PARTIAL TAILS (``tail_stride`` > 0): a prompt's LAST, partial block
    additionally registers sub-block digests at every ``tail_stride``
    tokens — ``t_j = H_tail(parent_full_digest, tokens[h*BL : h*BL +
    j*S])`` with a domain-separated hash, mapping to ``(block,
    tokens_covered)``. A later prompt whose shared prefix ends
    mid-block matches the DEEPEST registered tail and COW-extends it
    (the engine copies the tail block to a private fresh block and
    prefills only past the covered tokens). Soundness: the covered
    positions were prompt-prefill-written under the identical left
    context, so — by prefill's bitwise split-invariance — the copied
    bytes are bit-for-bit what the new sequence's own cold prefill
    would have written; bytes BEYOND the covered tokens in the copy are
    either re-prefilled by the new sequence or causally masked, so they
    never move an output bit. The stride must divide ``block_len``
    (netlint SRV001 mirrors this check statically)."""

    def __init__(self, block_len: int, tail_stride: int = 0):
        if tail_stride < 0 or (tail_stride and block_len % tail_stride):
            raise ValueError(
                f"prefix_cache.tail_stride {tail_stride} must divide "
                f"kv_block_len {block_len} (sub-block digests index "
                "whole stride multiples)"
            )
        self.block_len = block_len
        self.tail_stride = tail_stride
        #: bumped on every index mutation (register/forget) — cheap
        #: change detection for consumers that derive state from the
        #: index (the fleet host's published digest feedback)
        self.version = 0
        self._by_digest: dict[bytes, int] = {}
        self._digest_of: dict[int, bytes] = {}
        #: digest -> parent digest (None for a chain head) and the
        #: reverse — the chain linkage eviction needs: a child is only
        #: MATCHABLE through its parent's digest, so dropping a parent
        #: must cascade or descendants sit indexed-but-unreachable
        self._parent: dict[bytes, bytes | None] = {}
        self._children: dict[bytes, set[bytes]] = {}
        #: partial-tail index: tail digest -> (block, tokens covered);
        #: one block registers a tail at EVERY stride multiple its
        #: prompt coverage reaches, so the deepest match wins
        self._tail_block: dict[bytes, tuple[int, int]] = {}
        self._tails_of: dict[int, set[bytes]] = {}
        #: tail digests are only matchable under their parent FULL
        #: digest's chain (parent b"" = chain head), so evicting the
        #: parent must cascade them out exactly like full children
        self._tail_parent: dict[bytes, bytes] = {}
        self._tail_children: dict[bytes, set[bytes]] = {}

    def __len__(self) -> int:
        return len(self._by_digest)

    @staticmethod
    def _digest(prev: bytes, token_bytes: bytes) -> bytes:
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(token_bytes)
        return h.digest()

    @staticmethod
    def _tail_digest(parent: bytes, token_bytes: bytes) -> bytes:
        # blake2b personalization domain-separates sub-block tail
        # digests from the full-block chain, so a tail can never
        # collide into (or be matched as) a full block
        h = hashlib.blake2b(parent, digest_size=16, person=b"tail")
        h.update(token_bytes)
        return h.digest()

    def chain(self, tokens) -> list[bytes]:
        """Digests of every FULL block of ``tokens``, left to right.
        One vectorized int32 serialization for the whole prompt — this
        runs on the admission path for every request."""
        buf = np.ascontiguousarray(tokens, dtype="<i4").tobytes()
        out, prev, width = [], b"", 4 * self.block_len
        for i in range(len(tokens) // self.block_len):
            prev = self._digest(prev, buf[i * width:(i + 1) * width])
            out.append(prev)
        return out

    def match_chain(self, chain: list[bytes]) -> list[int]:
        """Block ids of the longest cached prefix of a digest chain (a
        missing link stops the walk — a block is only reusable under
        the exact left context it was written in)."""
        out: list[int] = []
        for d in chain:
            b = self._by_digest.get(d)
            if b is None:
                break
            out.append(b)
        return out

    def match(self, tokens) -> list[int]:
        """Block ids of the longest cached block-prefix of ``tokens``
        (full blocks only)."""
        return self.match_chain(self.chain(tokens))

    def has(self, digest: bytes) -> bool:
        return digest in self._by_digest

    def digests(self, limit: int | None = None) -> list[bytes]:
        """Up to ``limit`` indexed digests (insertion order — chain
        parents precede children, so a truncated list still matches
        prefixes). The fleet router's prefix-affinity feedback
        publishes these (serve/fleet/router.py)."""
        out = list(self._by_digest)
        return out if limit is None else out[:limit]

    def is_cached(self, block: int) -> bool:
        return block in self._digest_of or block in self._tails_of

    def match_tail(self, tokens, matched_blocks: int,
                   chain: list[bytes]) -> tuple[int, int]:
        """Deepest registered partial-tail extension of an
        ``matched_blocks``-deep full-block match of ``tokens`` ->
        ``(block, tokens_covered)``, or ``(0, 0)`` (block 0 is the
        reserved trash block, never a tail). Probes every stride
        multiple the prompt still covers past the matched blocks."""
        if not self.tail_stride or not self._tail_block:
            return 0, 0
        h, bl = matched_blocks, self.block_len
        rem = min(len(tokens) - h * bl, bl)
        if rem < self.tail_stride:
            return 0, 0
        parent = chain[h - 1] if h else b""
        buf = np.ascontiguousarray(tokens, dtype="<i4").tobytes()
        base = 4 * h * bl
        best = (0, 0)
        for j in range(self.tail_stride, min(rem, bl - 1) + 1,
                       self.tail_stride):
            entry = self._tail_block.get(
                self._tail_digest(parent, buf[base:base + 4 * j])
            )
            if entry is not None:
                best = entry
        return best

    def register_tail(self, tokens, block: int) -> int:
        """Index ``block`` — the prompt's LAST, partial block — under
        sub-block digests at every stride multiple its prompt coverage
        reaches (``tokens`` = the WHOLE prompt; the tail starts at the
        last full-block boundary). First writer wins per depth. -> how
        many depths were newly registered."""
        if not self.tail_stride:
            return 0
        bl = self.block_len
        nb = len(tokens) // bl
        rem = len(tokens) - nb * bl
        if rem < self.tail_stride:
            return 0
        parent = self.chain(tokens)[nb - 1] if nb else b""
        buf = np.ascontiguousarray(tokens, dtype="<i4").tobytes()
        base = 4 * nb * bl
        new = 0
        for j in range(self.tail_stride, rem + 1, self.tail_stride):
            d = self._tail_digest(parent, buf[base:base + 4 * j])
            if d in self._tail_block:
                continue
            self._tail_block[d] = (block, j)
            self._tails_of.setdefault(block, set()).add(d)
            self._tail_parent[d] = parent
            self._tail_children.setdefault(parent, set()).add(d)
            new += 1
        if new:
            self.version += 1
        return new

    def _drop_tail(self, d: bytes) -> int:
        """Remove one tail entry -> its block id."""
        block, _ = self._tail_block.pop(d)
        parent = self._tail_parent.pop(d)
        kids = self._tail_children.get(parent)
        if kids is not None:
            kids.discard(d)
            if not kids:
                del self._tail_children[parent]
        tails = self._tails_of.get(block)
        if tails is not None:
            tails.discard(d)
            if not tails:
                del self._tails_of[block]
        return block

    def clear(self) -> int:
        """Drop EVERY index entry, full-block and partial-tail alike ->
        how many full-block entries were dropped. Cached KV bytes are a
        function of the weights that wrote them, so a weight hot-swap
        (serve/rollout.py) must invalidate the whole index: a block
        prefilled under the old version matching a new-version admission
        would poison the pool."""
        n = len(self._by_digest)
        if n or self._tail_block:
            self.version += 1
        self._by_digest.clear()
        self._digest_of.clear()
        self._parent.clear()
        self._children.clear()
        self._tail_block.clear()
        self._tails_of.clear()
        self._tail_parent.clear()
        self._tail_children.clear()
        return n

    def register(self, digest: bytes, block: int,
                 parent: bytes | None = None) -> bool:
        """Bind ``digest`` -> ``block`` (``parent`` = the previous
        block's digest in the chain, None for a head). First writer
        wins: a digest already present (two identical prompts prefilled
        concurrently) keeps the existing block and the newcomer stays
        private."""
        if digest in self._by_digest or block in self._digest_of:
            return False
        self.version += 1
        self._by_digest[digest] = block
        self._digest_of[block] = digest
        self._parent[digest] = parent
        if parent is not None:
            self._children.setdefault(parent, set()).add(digest)
        return True

    def forget(self, block: int) -> list[int]:
        """Drop a block's index entry AND its descendant subtree — a
        descendant's digest is only reachable through this block's, so
        leaving it indexed would strand it unmatchable forever while
        still counting as cached. -> every block whose entry was
        removed (the allocator returns the LRU-parked ones to the free
        list); empty for an unregistered block. Partial-tail entries
        cascade with it: tails OF this block (and of any removed
        descendant), and tails PARENTED on any removed digest — a tail
        is only matchable through its parent's chain position."""
        d = self._digest_of.get(block)
        had_tails = block in self._tails_of
        if d is None and not had_tails:
            return []
        self.version += 1
        if had_tails:
            for td in list(self._tails_of[block]):
                self._drop_tail(td)
        if d is None:
            return [block]
        removed: list[int] = []
        tail_orphans: set[int] = set()
        stack = [d]
        while stack:
            dig = stack.pop()
            b = self._by_digest.pop(dig, None)
            if b is None:
                continue
            del self._digest_of[b]
            removed.append(b)
            for td in list(self._tails_of.get(b, ())):
                self._drop_tail(td)
            for td in list(self._tail_children.get(dig, ())):
                tail_orphans.add(self._drop_tail(td))
            parent = self._parent.pop(dig, None)
            if parent is not None and parent in self._children:
                self._children[parent].discard(dig)
                if not self._children[parent]:
                    del self._children[parent]
            stack.extend(self._children.pop(dig, ()))
        for b in tail_orphans:
            if b != block and not self.is_cached(b) and b not in removed:
                removed.append(b)
        return removed


class BlockAllocator:
    """Refcounted free-list allocator over a pool's block ids (block 0
    reserved). With ``prefix_cache`` on it doubles as the block cache's
    lifetime manager: ``retain`` bumps shared blocks at a prefix hit
    (reviving LRU blocks), ``release`` decrements at retirement and
    parks refcount-0 REGISTERED blocks on the LRU list, and ``alloc``
    reclaims from the LRU only when the free list alone cannot satisfy
    it (lazy eviction — a warm pool keeps serving hits). ``free`` is
    the strict exclusive-owner API: it refuses already-free AND shared
    blocks loudly, all-or-nothing, so a double release can never
    corrupt the free list (the latent pre-refcount hazard)."""

    def __init__(self, pool: KVPool, *, prefix_cache: bool = False,
                 lru: bool = True, tail_stride: int = 0):
        self.pool = pool
        self._free = list(range(pool.n_blocks - 1, 0, -1))  # pop() -> 1,2,..
        self._ref: dict[int, int] = {}
        #: refcount-0 registered blocks, oldest-released first
        self._lru: collections.OrderedDict[int, None] = (
            collections.OrderedDict()
        )
        self.cache: PrefixCache | None = (
            PrefixCache(pool.block_len, tail_stride) if prefix_cache
            else None
        )
        self.lru_enabled = lru
        #: optional lifecycle sink: callable(kind, **payload) — the
        #: scheduler points this at its recorder event path so
        #: lru_evict / lru_reclaim ride the flight recorder
        self.on_event = None
        #: high-water mark of blocks in use (serve_bench's occupancy row)
        self.peak_used = 0
        self.lru_evictions = 0
        self.lru_reclaims = 0

    def _event(self, kind: str, **payload) -> None:
        if self.on_event is not None:
            self.on_event(kind, **payload)

    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: truly free + lazily-reclaimable LRU."""
        return len(self._free) + len(self._lru)

    @property
    def used_blocks(self) -> int:
        """Blocks referenced by at least one live sequence."""
        return len(self._ref)

    @property
    def cached_blocks(self) -> int:
        """Refcount-0 blocks held warm on the LRU list."""
        return len(self._lru)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def reset_stats(self) -> None:
        self.lru_evictions = 0
        self.lru_reclaims = 0

    def can_alloc(self, n: int) -> bool:
        return n <= self.free_blocks

    def purge_cache(self) -> int:
        """Invalidate the whole prefix cache: drop every index entry and
        return every LRU-parked refcount-0 block to the free list -> how
        many full-block index entries were dropped. The weight-rollout
        flip (serve/rollout.py) calls this at the tick boundary: cached
        K/V bytes were written under the OLD weights, so under the new
        version every warm block is garbage. Blocks still referenced by
        live sequences merely lose their index entries — their in-flight
        owners keep decoding over them, and release() returns them to
        the free list (no longer cached) at retirement."""
        if self.cache is None:
            return 0
        dropped = self.cache.clear()
        while self._lru:
            block, _ = self._lru.popitem(last=False)
            self._free.append(block)
        return dropped

    def headroom_excluding(self, blocks: list[int]) -> int:
        """Allocatable count once ``blocks`` are retained: their LRU
        entries stop being reclaimable. Lets admission decide
        hit-plus-tail feasibility BEFORE touching any state, so
        backpressure retries are true no-ops (no phantom reclaim
        events, no LRU reordering)."""
        return self.free_blocks - sum(1 for b in blocks if b in self._lru)

    def alloc(self, n: int) -> list[int]:
        """-> ``n`` fresh (refcount-1, unshared) block ids; raises
        PoolExhausted leaving free list, LRU, and index untouched (the
        all-or-nothing contract admission needs). Reclaims LRU blocks
        lazily — oldest first, index entry dropped — only when the
        free list alone cannot cover ``n``."""
        if n > self.free_blocks:
            raise PoolExhausted(
                f"need {n} blocks, {len(self._free)} free + "
                f"{len(self._lru)} cached ({len(self._ref)} in use of "
                f"{self.pool.n_blocks - 1})"
            )
        while len(self._free) < n:
            block, _ = self._lru.popitem(last=False)
            self._free.append(block)
            self.lru_evictions += 1
            self._event("lru_evict", block=block)
            if self.cache is not None:
                # dropping a chain block orphans its descendants (they
                # are only matchable through it): their index entries
                # cascade out with it, and any parked on the LRU become
                # plain free blocks instead of dead warm weight
                for orphan in self.cache.forget(block):
                    if orphan != block and orphan in self._lru:
                        del self._lru[orphan]
                        self._free.append(orphan)
                        self.lru_evictions += 1
                        self._event("lru_evict", block=orphan)
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        self.peak_used = max(self.peak_used, len(self._ref))
        return out

    def retain(self, blocks: list[int]) -> int:
        """Bump each block's refcount (a prefix hit sharing them with a
        new sequence). Refcount-0 blocks are revived OFF the LRU list
        (-> the ``lru_reclaim`` lifecycle event). -> how many were
        revived."""
        revived = 0
        for b in blocks:
            if b in self._ref:
                self._ref[b] += 1
            elif b in self._lru:
                del self._lru[b]
                self._ref[b] = 1
                revived += 1
            else:
                raise ValueError(
                    f"retain of block {b} neither live nor cached"
                )
        if revived:
            self.lru_reclaims += revived
            self._event("lru_reclaim", blocks=revived)
        self.peak_used = max(self.peak_used, len(self._ref))
        return revived

    def release(self, blocks: list[int]) -> None:
        """Drop one reference per block (retirement/drain). A block
        reaching refcount 0 parks on the LRU list if it is registered
        in the prefix index (and LRU is on), else returns to the free
        list. A sequence's blocks park TAIL-first (deepest chain block
        oldest), so eviction pressure shaves chains from the tail and
        preserves the shorter — more widely shared — prefixes.
        Releasing an already-free block raises — refcounts make the
        double-release hazard checkable."""
        for b in reversed(list(blocks)):
            rc = self._ref.get(b)
            if rc is None:
                raise ValueError(
                    f"release of block {b} not handed out by this "
                    "allocator (double release?)"
                )
            if rc > 1:
                self._ref[b] = rc - 1
                continue
            del self._ref[b]
            if (
                self.cache is not None
                and self.cache.is_cached(b)
                and self.lru_enabled
            ):
                self._lru[b] = None
            else:
                if self.cache is not None:
                    for orphan in self.cache.forget(b):
                        if orphan != b and orphan in self._lru:
                            del self._lru[orphan]
                            self._free.append(orphan)
                self._free.append(b)

    def free(self, blocks: list[int]) -> None:
        """Strict EXCLUSIVE free: every block must be live with
        refcount exactly 1. Raises loudly — checking ALL blocks before
        mutating anything — on an already-free block (double free), a
        duplicate within ``blocks`` (double free in one call: the old
        free list took it twice and handed it to two owners), or a
        SHARED block (refcount > 1: returning it would corrupt another
        sequence's cache mid-read). Shared lifetimes go through
        ``release``."""
        seen: set[int] = set()
        for b in blocks:
            rc = self._ref.get(b)
            if rc is None or b in seen:
                raise ValueError(
                    f"free of block {b} not handed out by this allocator "
                    "(double free?)"
                )
            if rc > 1:
                raise ValueError(
                    f"free of SHARED block {b} (refcount {rc}): freeing "
                    "would corrupt the other owners' cache; use release()"
                )
            seen.add(b)
        self.release(blocks)
