"""Speculative multi-token decode: model-free drafting for the engine.

The serving engine's one-token tick is weight-streaming-bound: every
tick streams the full weights to emit one token per live slot
(tools/serve_bench.py measured it; the lm_d128_serve bench row notes
it). Speculative decoding amortizes that stream: draft ``k`` candidate
tokens per slot cheaply, score all ``(slots, k+1)`` positions in ONE
batched verify forward (serve/engine.py ``Engine.verify``), and emit
every accepted token — up to k+1 tokens for the cost of one weight
stream.

The drafters here are MODEL-FREE (no draft network, no extra weights to
stream — a draft model would re-pay the bandwidth the speculation is
trying to save at serving-tier batch sizes):

  ``NGramDrafter``   prompt-lookup / longest-suffix-match drafting
                     (arXiv 2304.04487, 2311.08252's observation that
                     LLM output heavily repeats its own context): find
                     the longest n-gram suffix of the sequence's own
                     prompt+emitted tokens that occurred earlier, and
                     propose the tokens that followed that occurrence.
                     Deterministic, O(context) per call, strong on the
                     repetitive/greedy workloads serving actually sees
                     (code, extraction, templated text — and the cyclic
                     continuations tiny greedy LMs emit in CI).
  ``NullDrafter``    never proposes: the machinery probe. A speculative
                     tick with zero drafts isolates the speculation
                     plumbing (verify program, acceptance lanes, KV
                     rewind) from the amortization win — serve_bench's
                     or-gate arm and the zero-acceptance parity tests
                     ride it.

Correctness is the verify step's job, not the drafter's: a drafter may
propose ANY tokens (garbage drafts cost acceptance rate, never
correctness). Greedy acceptance takes the longest prefix of the draft
matching the model's own argmax continuations plus one bonus token, so
the emitted stream is IDENTICAL to non-speculative greedy decode by
construction — speculation changes *when* tokens appear, never
*which*.
"""

from __future__ import annotations


class NGramDrafter:
    """Longest-suffix prompt-lookup drafting over the sequence's own
    context (prompt + emitted tokens).

    For n from ``ngram_max`` down to ``ngram_min``: take the context's
    trailing n-gram, scan for its most recent earlier occurrence, and
    propose (up to ``k``) tokens that followed it. The first n with a
    match wins — longer matches are better evidence the continuation
    repeats. Most-recent occurrence wins among matches (locality: the
    continuation nearest the cursor is likeliest to repeat next).
    Deterministic by construction, so speculative runs are replayable.
    """

    name = "ngram"

    def __init__(self, ngram_max: int = 4, ngram_min: int = 1):
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"[{ngram_min}, {ngram_max}]"
            )
        self.ngram_max = ngram_max
        self.ngram_min = ngram_min

    def draft(self, ctx, k: int) -> list[int]:
        """``ctx`` (sequence of ints, prompt + emitted so far) -> up to
        ``k`` proposed continuation tokens ([] = nothing to propose)."""
        if k <= 0 or len(ctx) < 2:
            return []
        ctx = list(ctx)
        n_hi = min(self.ngram_max, len(ctx) - 1)
        for n in range(n_hi, self.ngram_min - 1, -1):
            tail = ctx[-n:]
            # most recent earlier occurrence: i is the match START, and
            # i + n <= len(ctx) - 1 keeps at least one follower token
            for i in range(len(ctx) - n - 1, -1, -1):
                if ctx[i:i + n] == tail:
                    return ctx[i + n:i + n + k]
        return []


class NullDrafter:
    """Proposes nothing, ever: every speculative tick degrades to the
    one-token tick (acceptance forced to zero by having nothing to
    accept). The machinery probe — serve_bench times this against the
    plain decode tick to isolate the speculation plumbing's cost — and
    the parity oracle for zero-acceptance tests."""

    name = "null"

    def draft(self, ctx, k: int) -> list[int]:
        return []


DRAFTERS = {"ngram": NGramDrafter, "null": NullDrafter}


def make_drafter(name: str):
    """Drafter registry lookup (the ``serving { speculate { drafter } }``
    vocabulary; config/schema.py SPEC_DRAFTERS mirrors DRAFTERS)."""
    try:
        return DRAFTERS[name]()
    except KeyError:
        raise ValueError(
            f"unknown drafter {name!r}; have {sorted(DRAFTERS)}"
        ) from None
