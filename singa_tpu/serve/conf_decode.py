"""KV-cache decode for conf-surface nets (the tools/generate.py path).

The conf-driven LM jobs (examples/lm/tinylm*.conf) train through a
fixed-(B, S) compiled forward; sampling from them used to re-run that
whole forward for EVERY emitted token — O(S) recompute per token.
``NetDecoder`` gives a built ``Net`` the serving tier's incremental
path instead: chunked prefill writes the prompt's K/V into per-
attention-layer caches, then each new token is one (1, 1) step against
them — the same ``cache_attend`` body as models/transformer.generate
and serve/engine.py, reached through each layer's ``decode_step``.

Supported graphs: kSequenceData -> any DAG of position-wise layers
(``decode_positionwise`` — kLayerNorm/kDense/kAdd today) plus
kEmbedding/kAttention, into kLMLoss. Anything else (convs, pooling,
kMoE, pipeline-staged nets) raises ``UnsupportedNet`` and the caller
falls back to the rolling-buffer recompute decode — a performance
downgrade, never a behavior change.

Prefill chunks are FIXED (1, C) shapes with a valid count: padding
tokens write garbage K/V only at positions beyond every live query's
mask (overwritten by later real writes before anything attends there),
so one compiled chunk program serves every prompt length and chunking
is split-invariant.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class UnsupportedNet(ValueError):
    """The graph has a layer the incremental decode cannot serve."""


class NetDecoder:
    """Incremental (KV-cache) token decoder over a built conf Net."""

    def __init__(self, net, *, max_prefill_chunk: int = 32):
        self.net = net
        self.chunk = int(max_prefill_chunk)
        (self.datalayer,) = net.datalayers
        if len(net.losslayers) != 1:
            raise UnsupportedNet("decode needs exactly one loss layer")
        (loss,) = net.losslayers
        self.head = next(
            s for s in loss.srclayers if s != self.datalayer.name
        )
        self.attn_layers = []
        for layer in net.layers:
            if layer.is_datalayer or layer.is_losslayer:
                continue
            if hasattr(layer, "decode_step"):
                if layer.TYPE == "kAttention":
                    self.attn_layers.append(layer)
                continue
            if not layer.decode_positionwise:
                raise UnsupportedNet(
                    f"layer {layer.name!r} ({layer.TYPE}) has no "
                    "incremental decode"
                )
        if net.pipeline_plan is not None:
            raise UnsupportedNet("pipeline-staged nets decode full-window")
        # cache capacity: the embedding's positional table bounds how far
        # absolute positions can run; fall back past it
        embeds = [l for l in net.layers if l.TYPE == "kEmbedding"]
        if len(embeds) != 1:
            raise UnsupportedNet("decode needs exactly one kEmbedding")
        self.embed = embeds[0]
        self.max_positions = int(
            net.param_specs()[self.embed.pos].shape[0]
        )
        # cache capacity rounds UP to a chunk multiple: a final prefill
        # chunk's write window [c0, c0+chunk) must always fit, or
        # dynamic_update_slice would clamp the start and corrupt earlier
        # positions; the over-allocation tail is permanently masked
        self.cache_len = -(-self.max_positions // self.chunk) * self.chunk
        # two compiled programs total: one (1, chunk) prefill shape, one
        # (1, 1) decode shape — prompt/generation lengths never retrace
        self._step = jax.jit(self._step_impl, donate_argnums=(2,))

    def init_caches(self, dtype=jnp.float32) -> dict:
        """{attention layer name: (k, v)} zero caches, (1, H, C, D)."""
        out = {}
        for layer in self.attn_layers:
            shape = layer.out_shape  # (B, S, d) at train shapes
            d = shape[-1]
            h = layer.heads
            out[layer.name] = tuple(
                jnp.zeros((1, h, self.cache_len, d // h), dtype)
                for _ in range(2)
            )
        return out

    def _step_impl(self, params, tokens, caches, pos, n_valid):
        """tokens (1, Q) at absolute positions [pos, pos+Q) -> (logits
        at the last VALID position, new caches). Walks the graph in the
        same topo order as Net.forward; attention layers thread their
        cache, everything else applies position-wise."""
        net = self.net
        resolved = net.resolve_params(params)
        acts: dict = {}
        new_caches = dict(caches)
        for layer in net.layers:
            if layer.is_datalayer:
                acts[layer.name] = tokens
                continue
            if layer.is_losslayer:
                continue
            inputs = [acts[s] for s in layer.srclayers]
            if layer.TYPE == "kEmbedding":
                acts[layer.name] = layer.decode_step(
                    resolved, inputs[0], pos
                )
            elif layer.TYPE == "kAttention":
                out, new_caches[layer.name] = layer.decode_step(
                    resolved, inputs[0], caches[layer.name], pos
                )
                acts[layer.name] = out
            else:
                acts[layer.name] = layer.apply(
                    resolved, inputs, training=False, rng=None
                )
        logits = acts[self.head][0]  # (Q, vocab)
        last = jnp.take(logits, jnp.maximum(n_valid - 1, 0), axis=0)
        return last, new_caches

    def generate(self, params, prompt_tokens, n: int, temperature: float,
                 seed: int) -> list[int]:
        """prompt ids -> prompt + n generated ids, via chunked prefill +
        per-token KV-cache decode. Raises UnsupportedNet when the total
        length exceeds the positional table (the rolling-buffer path
        slides its window; a KV cache cannot)."""
        toks = [int(t) for t in prompt_tokens] or [0]
        if len(toks) + n > self.max_positions:
            raise UnsupportedNet(
                f"prompt {len(toks)} + n {n} exceeds the positional "
                f"table ({self.max_positions}); use the rolling decode"
            )
        caches = self.init_caches()
        rng = jax.random.PRNGKey(seed)
        out = list(toks)
        last = None
        for c0 in range(0, len(toks), self.chunk):
            chunk = toks[c0:c0 + self.chunk]
            buf = np.zeros((1, self.chunk), np.int32)
            buf[0, : len(chunk)] = chunk
            last, caches = self._step(
                params, jnp.asarray(buf), caches, jnp.int32(c0),
                jnp.int32(len(chunk)),
            )
        for i in range(n):
            if temperature <= 0.0:
                nxt = int(jnp.argmax(last))
            else:
                rng, k = jax.random.split(rng)
                nxt = int(jax.random.categorical(k, last / temperature))
            out.append(nxt)
            if i + 1 < n:
                last, caches = self._step(
                    params, jnp.full((1, 1), nxt, jnp.int32), caches,
                    jnp.int32(len(out) - 1), jnp.int32(1),
                )
        return out
