"""Live weight rollout: versioned hot-swap into a RUNNING fleet with
canary, parity-gated promotion, and automatic rollback.

The reference cluster could only change weights by restarting every
process; a serving fleet cannot afford that — streams are in flight.
This controller ships a next-version param tree into hosts that keep
serving the CURRENT version the whole time:

  stage     one ``weight_ship`` bulk frame per host (fleet/migrate.py
            weights codec: CRC-guarded npz). The host stages the tree
            ALONGSIDE its live params (engine.stage_params — dual-
            resident, which is why netlint ROL001 budgets 2x param
            HBM); serving is untouched. A torn frame is rejected by
            the CRC and nacked — the controller retries, then
            QUARANTINES the version. The live weights never stop
            answering.
  canary    ONE host (``rollout { canary }``; default the first
            decode-capable peer) flips first. The flip is applied in
            the host's message handler, BETWEEN scheduler ticks — the
            atomic tick boundary: no stream decodes under two versions
            within a tick. Flipping purges the prefix cache (cached KV
            is a function of the weights that wrote it) and pins the
            previous version for rollback.
  parity    the controller replays deterministic probe traffic through
            the canary's REAL serving path and compares the finished
            streams against a reference engine running the SAME staged
            weights. Any mismatch -> automatic fleet-wide ROLLBACK to
            the pinned current version and a loud ``rollout_abort``
            event. Zero streams drop or hang either way.
  promote   parity passed: the remaining hosts roll one by one (stage,
            flip — prefill hosts included). The fleet is legitimately
            MIXED-VERSION during this window; version tags on every
            migrate / cache_fetch / cache_ship frame make skew safe —
            a cross-version frame degrades to cold prefill, it never
            poisons a pool (fleet/host.py skew guards).

Every run terminates in one documented verdict:

  promoted     all hosts on the new version
  rollback     canary parity mismatch; every flipped host restored
  quarantined  a host's weight_ship tore ``ship_retries + 1`` times;
               the version is abandoned, flipped hosts rolled back,
               serving uninterrupted on current
  paused       a host died mid-stage (stage-ack timeout — the
               swap_die@K drill): the rollout stops where it is.
               Already-flipped hosts STAY flipped — the skew guards
               are exactly what makes the frozen mixed fleet safe —
               and the dead host's streams fail over on the existing
               tombstone path.

``run_rollout_from_conf`` drives all of it from the ``fleet {
rollout {} }`` conf block against a fleet of OS processes (the CI
drill); the class API drives in-process drills (tests/test_rollout.py)
and serve_bench's ``--rollout`` gate.
"""

from __future__ import annotations

import json
import time

import numpy as np

from ..comm.wire import WireError
from .engine import Engine
from .fleet import migrate
from .fleet.host import PROBE_RID_BASE
from .fleet.router import DECODE_CAPABLE
from .scheduler import Request, Scheduler

#: deterministic probe-prompt seed — reserved so a drill's probes are
#: reproducible across runs and processes
PROBE_SEED = 0x5EED


def probe_prompts(cfg, n: int, probe_tokens: int) -> list[np.ndarray]:
    """``n`` deterministic probe prompts that fit the serving window
    with ``probe_tokens`` of decode budget to spare."""
    length = max(1, min(6, cfg.max_len - probe_tokens - 1))
    rng = np.random.default_rng(PROBE_SEED)
    return [
        rng.integers(1, cfg.vocab, size=length).astype(np.int32)
        for _ in range(n)
    ]


class RolloutController:
    """One rollout attempt of one version over one fleet.

    ``tick`` is the pump the controller calls while awaiting acks:
    in-process drills pass a callable that ticks every live host (the
    controller and fleet share a thread); OS-process fleets pass None
    and the default sleep lets the peers' serve loops run.
    """

    def __init__(self, transport, peers: dict[str, str], *, params,
                 version: int, cfg, serving, canary: str = "",
                 probes: int = 4, probe_tokens: int = 8,
                 stage_timeout_s: float = 30.0, ship_retries: int = 2,
                 name: str = "rollout", recorder=None,
                 force_parity_fail: bool = False, tick=None,
                 log=lambda s: None):
        if not peers:
            raise ValueError("rollout needs at least one fleet host")
        self.transport = transport
        self.peers = dict(peers)
        self.params = params
        self.version = int(version)
        self.cfg = cfg
        self.serving = serving
        self.canary = canary or next(
            (n for n, r in self.peers.items() if r in DECODE_CAPABLE),
            next(iter(self.peers)),
        )
        if self.canary not in self.peers:
            raise ValueError(
                f"rollout canary {self.canary!r} is not a fleet host "
                f"(peers: {sorted(self.peers)})"
            )
        self.n_probes = max(1, int(probes))
        self.probe_tokens = max(1, int(probe_tokens))
        self.stage_timeout_s = float(stage_timeout_s)
        self.ship_retries = max(0, int(ship_retries))
        self.name = name
        self.recorder = recorder
        #: test hook: perturb ONE expected probe token so the parity
        #: gate trips and the automatic-rollback path runs end to end
        self.force_parity_fail = force_parity_fail
        self._tick = tick if tick is not None else (
            lambda: time.sleep(0.005)
        )
        self.log = log
        #: hosts currently serving the new version (rollback set)
        self.flipped: list[str] = []
        self.rollbacks = 0
        self.torn_ships = 0
        self._inbox: list[dict] = []
        transport.register(name)

    # -- plumbing -------------------------------------------------------

    def _event(self, kind: str, **payload) -> None:
        if self.recorder is not None:
            self.recorder.event(kind, **payload)

    def _send(self, host: str, kind: str, payload: bytes) -> bool:
        try:
            self.transport.send(host, kind, payload, src=self.name)
            return True
        except WireError as e:
            self.log(f"rollout: send to {host!r} failed: {e}")
            return False

    def _await(self, cmd: str, host: str, timeout_s: float | None = None
               ) -> dict | None:
        """Pump the fleet until ``host`` acks ``cmd`` (or the deadline
        passes -> None). Unrelated frames buffer for later awaits."""
        deadline = time.monotonic() + (
            self.stage_timeout_s if timeout_s is None else timeout_s
        )
        while True:
            for i, body in enumerate(self._inbox):
                if body.get("cmd") == cmd and body.get("host") == host:
                    return self._inbox.pop(i)
            for msg in self.transport.recv(self.name):
                if msg.kind != "rollout":
                    continue
                try:
                    self._inbox.append(
                        json.loads(msg.payload.decode("utf-8"))
                    )
                except ValueError:
                    continue
            for i, body in enumerate(self._inbox):
                if body.get("cmd") == cmd and body.get("host") == host:
                    return self._inbox.pop(i)
            if time.monotonic() >= deadline:
                return None
            self._tick()

    # -- the reference streams ------------------------------------------

    def _probe_plan(self) -> list[tuple[int, np.ndarray, int]]:
        prompts = probe_prompts(self.cfg, self.n_probes,
                                self.probe_tokens)
        return [
            (PROBE_RID_BASE - i, p, PROBE_SEED + i)
            for i, p in enumerate(prompts)
        ]

    def _expected_streams(self) -> dict[int, list[int]]:
        """What the staged weights SHOULD say: the controller runs the
        identical probes through its own reference engine on the new
        params. Greedy decode, same geometry, same seeds — the canary's
        post-flip streams must match bitwise."""
        eng = Engine(self.params, self.cfg, self.serving)
        sched = Scheduler(eng)
        for rid, prompt, seed in self._probe_plan():
            sched.submit(Request(
                rid=rid, prompt=prompt,
                max_new_tokens=self.probe_tokens, temperature=0.0,
                seed=seed,
            ))
        while sched.busy:
            sched.tick()
        out = {
            req.rid: [int(t) for t in req.tokens]
            for req in sched.finished
        }
        if self.force_parity_fail and out:
            rid = min(out)
            out[rid] = list(out[rid])
            out[rid][0] = (out[rid][0] + 1) % self.cfg.vocab
        return out

    # -- the lifecycle --------------------------------------------------

    def _stage(self, host: str) -> str:
        """Ship + stage onto one host. -> "staged" | "torn" | "paused"."""
        frame = migrate.serialize_weights(self.version, self.params)
        for attempt in range(1 + self.ship_retries):
            self._event(
                "weight_ship", dir="out", host=host,
                version=self.version, bytes=len(frame),
                attempt=attempt + 1,
            )
            if not self._send(host, "weight_ship", frame):
                return "paused"
            ack = self._await("stage_ack", host)
            if ack is None:
                # no ack inside the window: the host died mid-stage
                # (the swap_die drill) or the wire is gone — either
                # way the rollout PAUSES; the fleet keeps serving
                return "paused"
            if ack.get("ok"):
                return "staged"
            self.torn_ships += 1
            self.log(f"rollout: {host!r} rejected weight_ship "
                     f"v{self.version} (attempt {attempt + 1}/"
                     f"{1 + self.ship_retries}): "
                     f"{ack.get('error', '?')}")
        return "torn"

    def _flip(self, host: str) -> bool:
        if not self._send(
            host, "rollout",
            json.dumps({"cmd": "flip"}).encode("utf-8"),
        ):
            return False
        ack = self._await("flip_ack", host)
        if ack is None or not ack.get("ok"):
            return False
        self.flipped.append(host)
        return True

    def _rollback_all(self) -> None:
        """Restore every flipped host to the pinned current version."""
        for host in list(self.flipped):
            if self._send(
                host, "rollout",
                json.dumps({"cmd": "rollback"}).encode("utf-8"),
            ):
                self._await("rollback_ack", host)
            self.rollbacks += 1
        self.flipped = []

    def _probe_canary(self) -> tuple[bool, str]:
        """Replay probe traffic through the canary's real serving path
        and compare against the reference. -> (parity_ok, detail)."""
        plan = self._probe_plan()
        body = {
            "cmd": "probe",
            "prompts": [[int(t) for t in p] for _, p, _ in plan],
            "max_new": self.probe_tokens,
            "temperature": 0.0,
            "seeds": [s for _, _, s in plan],
        }
        if not self._send(
            self.canary, "rollout", json.dumps(body).encode("utf-8"),
        ):
            return False, "canary unreachable"
        done = self._await(
            "probe_done", self.canary,
            timeout_s=max(self.stage_timeout_s, 60.0),
        )
        if done is None or not done.get("ok"):
            return False, "probe_failed" if done else "probe_timeout"
        got = {
            int(r): [int(t) for t in toks]
            for r, toks in (done.get("streams") or {}).items()
        }
        expected = self._expected_streams()
        for rid, want in expected.items():
            if got.get(rid) != want:
                return False, (
                    f"stream {rid}: got {got.get(rid)} want {want}"
                )
        return True, f"{len(expected)} probe streams bitwise-identical"

    def run(self) -> dict:
        """The whole lifecycle. -> {"verdict", "version", "canary",
        "flipped", "rollbacks", "torn_ships", "detail"}."""
        order = [self.canary] + [
            n for n in self.peers if n != self.canary
        ]
        self.log(f"rollout v{self.version}: canary {self.canary!r}, "
                 f"order {order}")
        detail = ""
        verdict = "promoted"
        for k, host in enumerate(order):
            staged = self._stage(host)
            if staged == "paused":
                detail = f"no stage_ack from {host!r}"
                self._event(
                    "rollout_abort", reason="paused", host=host,
                    version=self.version, flipped=len(self.flipped),
                )
                verdict = "paused"
                break
            if staged == "torn":
                # retries exhausted: quarantine the version — flipped
                # hosts roll back, the fleet serves current throughout
                detail = (f"weight_ship to {host!r} torn "
                          f"{1 + self.ship_retries}x")
                self._rollback_all()
                self._event(
                    "rollout_abort", reason="torn", host=host,
                    version=self.version, rollbacks=self.rollbacks,
                )
                verdict = "quarantined"
                break
            if not self._flip(host):
                detail = f"no flip_ack from {host!r}"
                self._event(
                    "rollout_abort", reason="paused", host=host,
                    version=self.version, flipped=len(self.flipped),
                )
                verdict = "paused"
                break
            if k == 0:
                ok, detail = self._probe_canary()
                self._event(
                    "rollout_canary", host=host, version=self.version,
                    parity=ok, probes=self.n_probes,
                )
                if not ok:
                    self.log(f"rollout v{self.version}: CANARY PARITY "
                             f"MISMATCH on {host!r} — rolling back: "
                             f"{detail}")
                    self._rollback_all()
                    self._event(
                        "rollout_abort", reason="parity", host=host,
                        version=self.version, rollbacks=self.rollbacks,
                        detail=detail[:200],
                    )
                    verdict = "rollback"
                    break
                self.log(f"rollout v{self.version}: canary parity OK "
                         f"({detail})")
        result = {
            "verdict": verdict,
            "version": self.version,
            "canary": self.canary,
            "flipped": list(self.flipped),
            "rollbacks": self.rollbacks,
            "torn_ships": self.torn_ships,
            "detail": detail,
        }
        self._event(
            "rollout_done", verdict=verdict, version=self.version,
            canary=self.canary, flipped=len(self.flipped),
            rollbacks=self.rollbacks, torn_ships=self.torn_ships,
        )
        self.log(f"rollout v{self.version}: verdict {verdict}"
                 + (f" ({detail})" if detail else ""))
        return result


def run_rollout_from_conf(model_cfg, cluster_cfg, *,
                          force_parity_fail: bool = False,
                          log=print) -> dict:
    """Drive one rollout against a RUNNING conf-launched fleet (the CI
    drill's controller process): load the next-version weights named
    by ``fleet { rollout { checkpoint } }`` through the reshard-on-load
    path, then canary / parity / promote over the conf's transport."""
    import jax

    from ..config.schema import RolloutConfig
    from ..models.transformer import init_lm
    from ..obs.recorder import FlightRecorder
    from ..resilience.reshard import load_serving_params
    from .engine import EngineConfig
    from .fleet.host import (
        _build_transport,
        fleet_topology,
        lm_config_from_conf,
    )

    fleet = model_cfg.fleet
    ro = fleet.rollout if fleet.rollout is not None else RolloutConfig()
    if not ro.checkpoint:
        raise ValueError(
            "fleet rollout needs a checkpoint (the next-version "
            "weights); netlint ROL001 flags this statically"
        )
    cfg = lm_config_from_conf(model_cfg)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    params, info = load_serving_params(ro.checkpoint, params, log=log)
    version = int(ro.version) or int(info["step"]) + 1 or 1
    serving = EngineConfig.from_conf(
        model_cfg.serving, getattr(model_cfg, "kernels", None)
    )
    n_hosts = len(fleet.peers) or (
        cluster_cfg.nworkers if cluster_cfg is not None
        and cluster_cfg.nworkers else 1
    )
    topo = fleet_topology(fleet, n_hosts)
    workspace = (
        cluster_cfg.workspace if cluster_cfg is not None else "."
    )
    root = fleet.mailbox or f"{workspace}/fleet"
    recorder = FlightRecorder(
        f"{workspace}/events", rank=len(topo), run_id="fleet",
    )
    transport = _build_transport(fleet, root, recorder, None, log=log)
    ctl = RolloutController(
        transport, dict(topo),
        params=params, version=version, cfg=cfg, serving=serving,
        canary=ro.canary, probes=ro.parity_probes,
        probe_tokens=ro.probe_tokens,
        stage_timeout_s=ro.stage_timeout_s,
        ship_retries=ro.ship_retries, recorder=recorder,
        force_parity_fail=force_parity_fail, log=log,
    )
    log(f"rollout v{version}: weights from {info['path']!r} "
        f"(step {info['step']}, {info['format']}) over "
        f"{len(topo)}-host fleet at {root}")
    try:
        return ctl.run()
    finally:
        close = getattr(transport, "close", None)
        if close is not None:
            close()
        recorder.close()
