"""Serving engine: fixed-shape prefill/decode over a slot-batched state.

The compute plane of the serving tier. Device state is ONE pytree —
per-slot token/position/liveness/RNG lanes, per-slot block tables, and
the per-layer paged K/V pools — and exactly two compiled programs touch
it:

  decode   ONE donated, jitted step advancing EVERY live slot one
           token: embed the slots' last tokens, run each transformer
           block against the pool (scatter the new K/V into each slot's
           current block, gather each slot's table back to a dense
           (S, H, cache_len, D) view, ``cache_attend`` masked by
           position), sample per-slot. Dead slots ride along masked —
           admitting or retiring a stream flips ``live`` and never
           changes a shape, so the step NEVER recompiles.
  prefill  a fixed (1, max_prefill_chunk) chunk of one slot's prompt
           through the same block body; long prompts take several
           chunks, so a decode tick is never blocked behind an
           unbounded prompt. Padding positions write to the trash block
           and are masked out of every softmax, which makes chunking
           bitwise split-invariant.

Both programs run the SAME ``_block_apply``/``cache_attend`` body as
models/transformer.generate — paged-vs-dense parity is shared code, not
a tolerance. Admission-path work (table updates, first-token sampling)
is small host-driven device ops, off the decode hot path.

Sharding: pass a mesh and the pools lay their heads dim out over the
``model`` axis (parallel/shardings.serving_kv_shardings) — the serving
analog of kLayerPartition; everything else replicates.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import (
    TransformerConfig,
    _block_apply,
    _layernorm,
    cache_attend,
)
from .kv_pool import BlockAllocator, KVPool


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Serving-plane knobs (mirrors the ``serving`` model-conf block)."""

    slots: int = 8
    kv_block_len: int = 16
    kv_blocks: int = 0          # 0 = dense-equivalent sizing (see KVPool)
    max_prefill_chunk: int = 64

    @classmethod
    def from_conf(cls, serving) -> "EngineConfig":
        """From a parsed ``serving { ... }`` config block (None = defaults)."""
        if serving is None:
            return cls()
        return cls(
            slots=serving.slots,
            kv_block_len=serving.kv_block_len,
            kv_blocks=serving.kv_blocks,
            max_prefill_chunk=serving.max_prefill_chunk,
        )


class Engine:
    """Slot-batched continuous-decode engine for the code-API LM."""

    def __init__(
        self,
        params: dict,
        cfg: TransformerConfig,
        serving: EngineConfig | None = None,
        *,
        mesh=None,
        temperature: float = 0.0,
    ):
        self.cfg = cfg
        self.serving = serving or EngineConfig()
        self.temperature = float(temperature)
        self.pool = KVPool.for_model(
            cfg.max_len, self.serving.kv_block_len,
            self.serving.kv_blocks, self.serving.slots,
        )
        self.allocator = BlockAllocator(self.pool)
        self.params = params
        s, mb = self.serving.slots, self.pool.max_blocks_per_seq
        shape = (
            self.pool.n_blocks, cfg.n_heads,
            self.pool.block_len, cfg.head_dim,
        )
        pool_sh = state_sh = None
        if mesh is not None:
            from ..parallel.shardings import serving_kv_shardings

            pool_sh, state_sh = serving_kv_shardings(mesh, cfg.n_heads)
        def put(a, sh):
            return a if sh is None else jax.device_put(a, sh)
        self.state = {
            "tokens": put(jnp.zeros((s,), jnp.int32), state_sh),
            "pos": put(jnp.zeros((s,), jnp.int32), state_sh),
            "live": put(jnp.zeros((s,), bool), state_sh),
            "rng": put(
                jnp.zeros((s, 2), jnp.uint32), state_sh
            ),
            "tables": put(jnp.zeros((s, mb), jnp.int32), state_sh),
            "k": tuple(
                put(jnp.zeros(shape), pool_sh) for _ in range(cfg.n_layers)
            ),
            "v": tuple(
                put(jnp.zeros(shape), pool_sh) for _ in range(cfg.n_layers)
            ),
        }
        #: blocks owned per slot, freed at retire
        self._slot_blocks: dict[int, list[int]] = {}
        self._decode_jit = jax.jit(self._decode, donate_argnums=(1,))
        self._prefill_jit = jax.jit(self._prefill, donate_argnums=(1,))
        # admission-path lane updates fused into one dispatch each —
        # a request admission must not stall live slots' ticks behind a
        # storm of single-element device ops
        self._admit_jit = jax.jit(self._admit_prog, donate_argnums=(0,))
        self._activate_jit = jax.jit(
            self._activate_prog, donate_argnums=(0,)
        )
        self._retire_jit = jax.jit(self._retire_prog, donate_argnums=(0,))

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------

    def _gather(self, pool_arr, tables):
        """(NB, H, BL, D) pool + (S', MB) tables -> (S', H, CL, D) dense
        per-sequence cache views (CL = MB * BL = the dense cache_len)."""
        g = pool_arr[tables]                      # (S', MB, H, BL, D)
        g = jnp.moveaxis(g, 2, 1)                 # (S', H, MB, BL, D)
        s, h = g.shape[0], g.shape[1]
        return g.reshape(s, h, self.pool.cache_len, g.shape[-1])

    def _sample(self, logits, keys, live, prev):
        """Per-slot sampling: greedy at temperature 0 (bit-for-bit the
        generate() decision rule), else per-slot categorical with each
        slot's own key stream (slot-independent by construction — a
        stream's text can never depend on what shares the batch)."""
        if self.temperature <= 0.0:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            nxt = jax.vmap(
                lambda k, l: jax.random.categorical(k, l / self.temperature)
            )(keys, logits).astype(jnp.int32)
        return jnp.where(live, nxt, prev)

    def _decode(self, params, state):
        cfg = self.pool
        tokens, pos, live = state["tokens"], state["pos"], state["live"]
        mcfg = self.cfg
        x = (
            params["embed/tok"][tokens][:, None, :]
            + params["embed/pos"][pos][:, None, :]
        )
        # each slot's write target: its current block, current offset.
        # Dead lanes route to the trash block explicitly — a slot that
        # is admitted-but-still-prefilling has a REAL table whose first
        # block must not be clobbered by its stale decode lane.
        bid = jnp.take_along_axis(
            state["tables"], (pos // cfg.block_len)[:, None], axis=1
        )[:, 0]
        bid = jnp.where(live, bid, 0)
        off = pos % cfg.block_len
        new_k, new_v = [], []

        def mk_attend(i):
            def attend(q, k, v):
                kp = state["k"][i].at[bid, :, off].set(k[:, :, 0, :])
                vp = state["v"][i].at[bid, :, off].set(v[:, :, 0, :])
                o = cache_attend(
                    q,
                    self._gather(kp, state["tables"]),
                    self._gather(vp, state["tables"]),
                    pos[:, None],
                )
                return o, (kp, vp)
            return attend

        for i in range(mcfg.n_layers):
            x, _, (kp, vp) = _block_apply(
                params, f"blk{i}", x, mk_attend(i), mcfg,
                moe_capacity_factor=float(max(mcfg.moe_experts, 1)),
            )
            new_k.append(kp)
            new_v.append(vp)
        xf = _layernorm(x, params["ln_f/scale"], params["ln_f/bias"])
        logits = (xf @ params["embed/tok"].T)[:, 0]
        keys = new_rng = state["rng"]
        if self.temperature > 0.0:
            split = jax.vmap(jax.random.split)(state["rng"])
            new_rng, keys = split[:, 0], split[:, 1]
        nxt = self._sample(logits, keys, live, tokens)
        new_state = {
            **state,
            "tokens": nxt,
            "pos": pos + live.astype(jnp.int32),
            "rng": new_rng,
            "k": tuple(new_k),
            "v": tuple(new_v),
        }
        return new_state, jnp.where(live, nxt, jnp.int32(-1))

    def _prefill(self, params, state, slot, chunk, pos0, n_valid):
        """One (1, C) prompt chunk of ``slot`` at absolute positions
        [pos0, pos0 + C): writes the chunk's K/V into the slot's blocks
        (padding positions to the trash block) and returns the logits
        at the last VALID position — garbage only where the mask
        already guarantees it cannot matter."""
        cfg, mcfg = self.pool, self.cfg
        c = chunk.shape[0]
        p = pos0 + jnp.arange(c)
        valid = jnp.arange(c) < n_valid
        # clip the embedding/table lookups for padding positions; their
        # values are masked, only their indices must stay in range
        p_safe = jnp.minimum(p, mcfg.max_len - 1)
        x = (
            params["embed/tok"][chunk]
            + params["embed/pos"][p_safe]
        )[None]
        row = state["tables"][slot]
        bid = jnp.where(
            valid,
            row[jnp.minimum(p_safe // cfg.block_len, row.shape[0] - 1)],
            0,
        )
        off = p_safe % cfg.block_len
        new_k, new_v = [], []

        def mk_attend(i):
            def attend(q, k, v):
                kp = state["k"][i].at[bid, :, off].set(
                    jnp.moveaxis(k[0], 1, 0)
                )
                vp = state["v"][i].at[bid, :, off].set(
                    jnp.moveaxis(v[0], 1, 0)
                )
                o = cache_attend(
                    q,
                    self._gather(kp, row[None]),
                    self._gather(vp, row[None]),
                    p[None],
                )
                return o, (kp, vp)
            return attend

        for i in range(mcfg.n_layers):
            x, _, (kp, vp) = _block_apply(
                params, f"blk{i}", x, mk_attend(i), mcfg,
                moe_capacity_factor=float(max(mcfg.moe_experts, 1)),
            )
            new_k.append(kp)
            new_v.append(vp)
        xf = _layernorm(x, params["ln_f/scale"], params["ln_f/bias"])
        logits = (xf[0] @ params["embed/tok"].T)
        last = jnp.take(logits, jnp.maximum(n_valid - 1, 0), axis=0)
        return {**state, "k": tuple(new_k), "v": tuple(new_v)}, last

    def _admit_prog(self, state, slot, row):
        return {
            **state,
            "tables": state["tables"].at[slot].set(row),
            "pos": state["pos"].at[slot].set(0),
            "live": state["live"].at[slot].set(False),
        }

    def _activate_prog(self, state, slot, last_logits, plen, seed):
        rng = jax.random.PRNGKey(seed)
        k0, rng = jax.random.split(rng)
        if self.temperature <= 0.0:
            first = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
        else:
            first = jax.random.categorical(
                k0, last_logits / self.temperature
            ).astype(jnp.int32)
        return {
            **state,
            "tokens": state["tokens"].at[slot].set(first),
            "pos": state["pos"].at[slot].set(plen),
            "live": state["live"].at[slot].set(True),
            "rng": state["rng"].at[slot].set(rng),
        }, first

    def _retire_prog(self, state, slot):
        return {
            **state,
            "live": state["live"].at[slot].set(False),
            "tables": state["tables"].at[slot].set(
                jnp.zeros((self.pool.max_blocks_per_seq,), jnp.int32)
            ),
        }

    # ------------------------------------------------------------------
    # admission-path API (host-driven, one fused dispatch each, never on
    # the tick path of OTHER slots' decode)
    # ------------------------------------------------------------------

    def admit(self, slot: int, n_total_tokens: int) -> list[int]:
        """Allocate ``blocks_for(n_total_tokens)`` blocks to ``slot`` and
        install its block table (raises PoolExhausted untouched —
        admission backpressure). The slot stays dead until activate()."""
        blocks = self.allocator.alloc(self.pool.blocks_for(n_total_tokens))
        row = np.zeros((self.pool.max_blocks_per_seq,), np.int32)
        row[: len(blocks)] = blocks
        self.state = self._admit_jit(
            self.state, jnp.int32(slot), jnp.asarray(row)
        )
        self._slot_blocks[slot] = blocks
        return blocks

    def prefill_chunk(self, slot: int, tokens: np.ndarray, pos0: int):
        """Run one prompt chunk (<= max_prefill_chunk tokens) for
        ``slot``; returns the device logits at the chunk's last valid
        position (meaningful only for the final chunk)."""
        c = self.serving.max_prefill_chunk
        n = len(tokens)
        if n > c:
            raise ValueError(f"prefill chunk {n} > max_prefill_chunk {c}")
        buf = np.zeros((c,), np.int32)
        buf[:n] = tokens
        self.state, last = self._prefill_jit(
            self.params, self.state, jnp.int32(slot), jnp.asarray(buf),
            jnp.int32(pos0), jnp.int32(n),
        )
        return last

    def activate(self, slot: int, last_logits, plen: int, seed: int) -> int:
        """Sample the first token from the final prefill chunk's logits
        (the same key discipline as generate(): k0 = first split of the
        request's key) and flip the slot live. -> the first token."""
        self.state, first = self._activate_jit(
            self.state, jnp.int32(slot), last_logits,
            jnp.int32(plen), jnp.int32(seed),
        )
        return int(first)

    def decode(self):
        """One tick: every live slot advances one token. -> emitted
        (slots,) int32 device array, -1 on dead slots."""
        self.state, emitted = self._decode_jit(self.params, self.state)
        return emitted

    def retire(self, slot: int) -> None:
        """Free the slot's blocks and kill its lane (its pool contents
        become reusable garbage, masked wherever gathered)."""
        self.state = self._retire_jit(self.state, jnp.int32(slot))
        blocks = self._slot_blocks.pop(slot, None)
        if blocks:
            self.allocator.free(blocks)
