"""Serving engine: fixed-shape prefill/decode over a slot-batched state.

The compute plane of the serving tier. Device state is ONE pytree —
per-slot token/position/liveness/RNG lanes, per-slot block tables, and
the per-layer paged K/V pools — and exactly two compiled programs touch
it:

  decode   ONE donated, jitted step advancing EVERY live slot one
           token: embed the slots' last tokens, run each transformer
           block against the pool (scatter the new K/V into each slot's
           current block, gather each slot's table back to a dense
           (S, H, cache_len, D) view, ``cache_attend`` masked by
           position), sample per-slot. Dead slots ride along masked —
           admitting or retiring a stream flips ``live`` and never
           changes a shape, so the step NEVER recompiles.
  prefill  a fixed (1, max_prefill_chunk) chunk of one slot's prompt
           through the same block body; long prompts take several
           chunks, so a decode tick is never blocked behind an
           unbounded prompt. Padding positions write to the trash block
           and are masked out of every softmax, which makes chunking
           bitwise split-invariant.
  verify   the speculative tick (spec_k > 0, serve/speculate.py): ONE
           donated fixed-shape pass scoring every live slot's current
           token PLUS its k drafted candidates — (slots, k+1) query
           positions through the paged pool, the prefill chunk shape
           turned sideways. Greedy acceptance takes the longest prefix
           of the draft matching the model's own argmax continuations
           plus one bonus token (up to k+1 tokens per slot per weight
           stream); a masked KV REWIND keeps only positions sequential
           decode would have written — the pool after any accept/
           reject pattern is bitwise what one-token ticks leave.

All programs run the SAME ``_block_apply``/``cache_attend``/``lm_head``
body as models/transformer.generate — paged-vs-dense parity AND
speculative-vs-sequential parity are shared code, not a tolerance.
Admission-path work (table updates, first-token sampling) is small
host-driven device ops, off the decode hot path.

Sampling is a per-slot TEMPERATURE LANE: a (slots,) array + masked
categorical, so mixed sampling configs (greedy and temperature slots
side by side) share one compiled program — admitting a temperature
request next to greedy ones never recompiles. Speculation is
greedy-only per slot: a temperature > 0 slot rides the verify tick
with zero drafts (it emits its one sampled token per tick; its key
discipline — one split per emitted token — is identical either way).

Sharding: pass a mesh and the pools lay their heads dim out over the
``model`` axis (parallel/shardings.serving_kv_shardings) — the serving
analog of kLayerPartition; everything else replicates.

ATTENTION IMPLEMENTATION is a per-engine knob (the ``kernels {
paged_attention }`` model-conf block): ``reference`` (the default)
keeps the bitwise-pinned gather -> ``cache_attend`` path above;
``fused`` swaps the Pallas paged-attention kernel
(ops/paged_attention.py) in at the ``attend`` closure seam of
``_block_apply`` — K/V blocks are read IN PLACE through the block
table, no dense ``(S, H, cache_len, D)`` materialization per layer.
Fused output is allclose to the reference (online softmax reorders the
reduction — the PR 9 cross-shape caveat at kernel granularity); greedy
token STREAMS are pinned identical in tests. ``kernels { interpret }``
(default true) runs the kernel through the Pallas interpreter — plain
XLA ops, CPU-safe and GSPMD-shardable — set false on a real TPU to
compile through Mosaic (geometry-gated: see paged_attention.fusable,
statically mirrored by netlint KRN001).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import (
    TransformerConfig,
    _block_apply,
    cache_attend,
    lm_head,
)
from .kv_pool import BlockAllocator, KVPool, PoolExhausted


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Serving-plane knobs (mirrors the ``serving`` model-conf block)."""

    slots: int = 8
    kv_block_len: int = 16
    kv_blocks: int = 0          # 0 = dense-equivalent sizing (see KVPool)
    max_prefill_chunk: int = 64
    #: draft tokens per live greedy slot per speculative tick
    #: (``serving { speculate { k } }``); 0 = one-token decode ticks
    spec_k: int = 0
    #: drafter name (serve/speculate.py DRAFTERS)
    spec_drafter: str = "ngram"
    #: ``serving { prefix_cache { enabled } }``: content-addressed,
    #: refcounted block sharing — admissions reuse cached full-block
    #: prompt prefixes instead of re-prefilling them
    prefix_cache: bool = False
    #: keep refcount-0 cached blocks on an LRU list (reclaimed lazily)
    #: instead of freeing them at retirement; False = share only among
    #: concurrently-live sequences
    prefix_lru: bool = True
    #: ``prefix_cache { tail_stride }``: > 0 indexes each prompt's last
    #: PARTIAL block at this sub-block token stride, so a prompt whose
    #: shared prefix ends mid-block COW-extends the deepest partial
    #: match instead of re-prefilling the whole block; must divide
    #: kv_block_len. 0 = full-block granularity only.
    prefix_tail_stride: int = 0
    #: ``prefix_cache { decode_blocks }``: register FULL decode-written
    #: blocks under the chained digest at retirement so multi-turn
    #: traffic hits its own history. Warm streams over these blocks are
    #: TOKEN-LEVEL identical to cold admission, not bitwise (the PR 9
    #: cross-shape caveat: decode/verify writes ride a different
    #: compiled shape than prefill).
    prefix_decode_blocks: bool = False
    #: ``prefix_cache { fetch_timeout_s }``: fleet hosts hold a request
    #: awaiting a peer's cache_ship this long before degrading to plain
    #: prefill (serve/fleet/host.py)
    prefix_fetch_timeout_s: float = 2.0
    #: ``kernels { paged_attention }``: "reference" = the gather +
    #: cache_attend oracle path (bitwise-pinned, the default); "fused"
    #: = the Pallas kernel reading K/V blocks in place via the block
    #: table (ops/paged_attention.py)
    attend_impl: str = "reference"
    #: ``kernels { interpret }``: run the fused kernel through the
    #: Pallas interpreter (plain XLA ops — CPU-safe, GSPMD-shardable;
    #: what CI exercises). False compiles through Mosaic on a real TPU
    #: and constrains the geometry (paged_attention.fusable / KRN001).
    interpret: bool = True

    @classmethod
    def from_conf(cls, serving, kernels=None) -> "EngineConfig":
        """From parsed ``serving { ... }`` / ``kernels { ... }`` config
        blocks (None = defaults)."""
        kw = {}
        if kernels is not None:
            kw = dict(
                attend_impl=kernels.paged_attention,
                interpret=kernels.interpret,
            )
        if serving is None:
            return cls(**kw)
        spec = serving.speculate
        pc = serving.prefix_cache
        return cls(
            slots=serving.slots,
            kv_block_len=serving.kv_block_len,
            kv_blocks=serving.kv_blocks,
            max_prefill_chunk=serving.max_prefill_chunk,
            spec_k=spec.k if spec is not None else 0,
            spec_drafter=spec.drafter if spec is not None else "ngram",
            prefix_cache=pc.enabled if pc is not None else False,
            prefix_lru=pc.lru if pc is not None else True,
            prefix_tail_stride=pc.tail_stride if pc is not None else 0,
            prefix_decode_blocks=(
                pc.decode_blocks if pc is not None else False
            ),
            prefix_fetch_timeout_s=(
                pc.fetch_timeout_s if pc is not None else 2.0
            ),
            **kw,
        )


@dataclasses.dataclass(frozen=True)
class Admission:
    """What admit() did for one request: the sequence's full block list
    (shared prefix blocks first), how many prompt tokens the prefix
    cache covered, where prefill must start (== ``cached_tokens``
    except on a WHOLE-prompt hit, where the last token re-runs through
    a COW'd block to re-derive the activation logits), and whether a
    copy-on-write happened."""

    blocks: list
    cached_tokens: int = 0
    prefill_from: int = 0
    cow_copied: bool = False
    #: tokens of ``cached_tokens`` served by COW-EXTENDING a registered
    #: partial tail (sub-block sharing: the deepest matched tail block
    #: was copied to a private fresh block and prefill starts past the
    #: covered tokens); 0 = the hit ended on a block boundary
    tail_tokens: int = 0


class Engine:
    """Slot-batched continuous-decode engine for the code-API LM."""

    def __init__(
        self,
        params: dict,
        cfg: TransformerConfig,
        serving: EngineConfig | None = None,
        *,
        mesh=None,
        temperature: float = 0.0,
    ):
        self.cfg = cfg
        self.serving = serving or EngineConfig()
        self.temperature = float(temperature)
        if self.serving.attend_impl not in ("reference", "fused"):
            raise ValueError(
                f"kernels.paged_attention must be 'reference' or "
                f"'fused', got {self.serving.attend_impl!r}"
            )
        self._fused = self.serving.attend_impl == "fused"
        if self._fused:
            from ..ops.paged_attention import fusable

            reason = fusable(
                self.serving.kv_block_len, cfg.head_dim,
                interpret=self.serving.interpret,
            )
            if reason is not None:
                # the runtime rejection KRN001 statically mirrors
                raise ValueError(
                    f"kernels {{ paged_attention: fused }}: {reason}"
                )
        self.pool = KVPool.for_model(
            cfg.max_len, self.serving.kv_block_len,
            self.serving.kv_blocks, self.serving.slots,
        )
        self.allocator = BlockAllocator(
            self.pool,
            prefix_cache=self.serving.prefix_cache,
            lru=self.serving.prefix_lru,
            tail_stride=self.serving.prefix_tail_stride,
        )
        self.params = params
        #: live-weight rollout versioning (serve/rollout.py): the tag of
        #: the LIVE param tree. A staged next-version tree sits alongside
        #: it until flip_params() swaps the reference at a tick boundary
        #: — every jitted program takes params per call, so the swap is
        #: atomic between ticks and recompiles nothing (same shapes).
        self.params_version = 0
        self._staged: tuple[int, dict] | None = None
        #: the pinned previous version a canary-abort rolls back to
        self._prev: tuple[int, dict] | None = None
        #: params version each slot was admitted/imported under — its
        #: K/V bytes are a function of THOSE weights, so registration
        #: into the prefix index is gated on the version still being live
        self._slot_version: dict[int, int] = {}
        s, mb = self.serving.slots, self.pool.max_blocks_per_seq
        shape = (
            self.pool.n_blocks, cfg.n_heads,
            self.pool.block_len, cfg.head_dim,
        )
        pool_sh = state_sh = None
        if mesh is not None:
            from ..parallel.shardings import serving_kv_shardings

            pool_sh, state_sh = serving_kv_shardings(mesh, cfg.n_heads)
        def put(a, sh):
            return a if sh is None else jax.device_put(a, sh)
        self.state = {
            "tokens": put(jnp.zeros((s,), jnp.int32), state_sh),
            "pos": put(jnp.zeros((s,), jnp.int32), state_sh),
            "live": put(jnp.zeros((s,), bool), state_sh),
            # per-slot sampling temperature lane: one compiled program
            # serves mixed sampling configs (0 = greedy, masked select)
            "temp": put(jnp.zeros((s,), jnp.float32), state_sh),
            "rng": put(
                jnp.zeros((s, 2), jnp.uint32), state_sh
            ),
            "tables": put(jnp.zeros((s, mb), jnp.int32), state_sh),
            "k": tuple(
                put(jnp.zeros(shape), pool_sh) for _ in range(cfg.n_layers)
            ),
            "v": tuple(
                put(jnp.zeros(shape), pool_sh) for _ in range(cfg.n_layers)
            ),
        }
        #: blocks owned per slot, freed at retire
        self._slot_blocks: dict[int, list[int]] = {}
        #: the admission-time digest chain per slot (register_prefix
        #: reuses it — one hashing pass per request, not two)
        self._slot_chain: dict[int, list[bytes]] = {}
        self._decode_jit = jax.jit(self._decode, donate_argnums=(1,))
        self._prefill_jit = jax.jit(self._prefill, donate_argnums=(1,))
        self._verify_jit = jax.jit(self._verify, donate_argnums=(1,))
        # admission-path lane updates fused into one dispatch each —
        # a request admission must not stall live slots' ticks behind a
        # storm of single-element device ops
        self._admit_jit = jax.jit(self._admit_prog, donate_argnums=(0,))
        self._activate_jit = jax.jit(
            self._activate_prog, donate_argnums=(0,)
        )
        self._retire_jit = jax.jit(self._retire_prog, donate_argnums=(0,))
        # copy-on-write: one fixed-shape block copy (src/dst are traced
        # scalars, so every COW reuses ONE compiled program)
        self._cow_jit = jax.jit(self._cow_prog, donate_argnums=(0,))
        # block migration (serve/fleet/migrate.py): one fixed-shape
        # gather of a slot's whole paged state for export, one
        # fixed-shape scatter + lane install for import — slot/rows are
        # traced, so every migration reuses ONE compiled program each
        self._export_jit = jax.jit(self._export_prog)
        self._import_jit = jax.jit(self._import_prog, donate_argnums=(0,))
        # fleet prefix shipping (serve/fleet/host.py): one fixed-shape
        # gather of arbitrary registered blocks for a cache_ship reply,
        # one fixed-shape scatter installing shipped bytes WITHOUT
        # touching any lane (the warmed blocks belong to the cache, not
        # to a slot) — rows are traced, so every ship reuses ONE
        # compiled program each
        self._export_blocks_jit = jax.jit(self._export_blocks_prog)
        self._install_jit = jax.jit(
            self._install_prog, donate_argnums=(0,)
        )

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------

    def _gather(self, pool_arr, tables):
        """(NB, H, BL, D) pool + (S', MB) tables -> (S', H, CL, D) dense
        per-sequence cache views (CL = MB * BL = the dense cache_len).

        Gather indices are promised in bounds: every table entry is an
        allocator-issued block id (rows beyond a sequence's allocation
        hold the trash block, 0), so XLA's per-index clamp — work whose
        only effect the attend mask would zero anyway — is skipped."""
        g = pool_arr.at[tables].get(mode="promise_in_bounds")
        g = jnp.moveaxis(g, 2, 1)                 # (S', H, MB, BL, D)
        s, h = g.shape[0], g.shape[1]
        return g.reshape(s, h, self.pool.cache_len, g.shape[-1])

    def _gather_kv(self, kp, vp, tables):
        """Both dense views of one layer's K and V pools — the ONE
        helper the reference attends share (decode/prefill/verify each
        used to spell the pair out)."""
        return self._gather(kp, tables), self._gather(vp, tables)

    def _paged_attend(self, q, kp, vp, tables, positions):
        """The fused path's write-then-read attend (decode + prefill):
        the fresh K/V were already scattered into ``kp``/``vp``, the
        kernel reads blocks in place through ``tables``."""
        from ..ops.paged_attention import paged_attention

        return paged_attention(
            q, kp, vp, tables, positions,
            interpret=self.serving.interpret,
        )

    def _sample(self, logits, keys, temps, live, prev):
        """Per-slot sampling through the temperature LANE: greedy argmax
        where a slot's temperature is 0 (bit-for-bit the generate()
        decision rule), per-slot categorical with the slot's own key
        stream otherwise — a masked select, so one compiled program
        serves any mix (slot-independent by construction: a stream's
        text can never depend on what shares the batch)."""
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        sampled = jax.vmap(
            lambda k, l, t: jax.random.categorical(k, l / t)
        )(keys, logits, jnp.maximum(temps, 1e-6)).astype(jnp.int32)
        nxt = jnp.where(temps > 0.0, sampled, greedy)
        return jnp.where(live, nxt, prev)

    def _split_keys(self, state):
        """One key split per slot per tick (= per emitted token for
        temperature slots, both in one-token and speculative ticks —
        the key discipline speculation must preserve). Greedy slots'
        splits are dead lanes the masked select never reads."""
        split = jax.vmap(jax.random.split)(state["rng"])
        return split[:, 0], split[:, 1]

    def _decode(self, params, state):
        cfg = self.pool
        tokens, pos, live = state["tokens"], state["pos"], state["live"]
        mcfg = self.cfg
        x = (
            params["embed/tok"][tokens][:, None, :]
            + params["embed/pos"][pos][:, None, :]
        )
        # each slot's write target: its current block, current offset.
        # Dead lanes route to the trash block explicitly — a slot that
        # is admitted-but-still-prefilling has a REAL table whose first
        # block must not be clobbered by its stale decode lane.
        bid = jnp.take_along_axis(
            state["tables"], (pos // cfg.block_len)[:, None], axis=1
        )[:, 0]
        bid = jnp.where(live, bid, 0)
        off = pos % cfg.block_len
        new_k, new_v = [], []

        def mk_attend(i):
            def attend(q, k, v):
                kp = state["k"][i].at[bid, :, off].set(k[:, :, 0, :])
                vp = state["v"][i].at[bid, :, off].set(v[:, :, 0, :])
                if self._fused:
                    o = self._paged_attend(
                        q, kp, vp, state["tables"], pos[:, None]
                    )
                else:
                    o = cache_attend(
                        q,
                        *self._gather_kv(kp, vp, state["tables"]),
                        pos[:, None],
                    )
                return o, (kp, vp)
            return attend

        for i in range(mcfg.n_layers):
            x, _, (kp, vp) = _block_apply(
                params, f"blk{i}", x, mk_attend(i), mcfg,
                moe_capacity_factor=float(max(mcfg.moe_experts, 1)),
            )
            new_k.append(kp)
            new_v.append(vp)
        logits = lm_head(params, x)[:, 0]
        new_rng, keys = self._split_keys(state)
        nxt = self._sample(logits, keys, state["temp"], live, tokens)
        new_state = {
            **state,
            "tokens": nxt,
            "pos": pos + live.astype(jnp.int32),
            "rng": new_rng,
            "k": tuple(new_k),
            "v": tuple(new_v),
        }
        return new_state, jnp.where(live, nxt, jnp.int32(-1))

    def _prefill(self, params, state, slot, chunk, pos0, n_valid):
        """One (1, C) prompt chunk of ``slot`` at absolute positions
        [pos0, pos0 + C): writes the chunk's K/V into the slot's blocks
        (padding positions to the trash block) and returns the logits
        at the last VALID position — garbage only where the mask
        already guarantees it cannot matter."""
        cfg, mcfg = self.pool, self.cfg
        c = chunk.shape[0]
        p = pos0 + jnp.arange(c)
        valid = jnp.arange(c) < n_valid
        # clip the embedding/table lookups for padding positions; their
        # values are masked, only their indices must stay in range
        p_safe = jnp.minimum(p, mcfg.max_len - 1)
        x = (
            params["embed/tok"][chunk]
            + params["embed/pos"][p_safe]
        )[None]
        row = state["tables"][slot]
        bid = jnp.where(
            valid,
            row[jnp.minimum(p_safe // cfg.block_len, row.shape[0] - 1)],
            0,
        )
        off = p_safe % cfg.block_len
        new_k, new_v = [], []

        def mk_attend(i):
            def attend(q, k, v):
                kp = state["k"][i].at[bid, :, off].set(
                    jnp.moveaxis(k[0], 1, 0)
                )
                vp = state["v"][i].at[bid, :, off].set(
                    jnp.moveaxis(v[0], 1, 0)
                )
                if self._fused:
                    o = self._paged_attend(q, kp, vp, row[None], p[None])
                else:
                    o = cache_attend(
                        q,
                        *self._gather_kv(kp, vp, row[None]),
                        p[None],
                    )
                return o, (kp, vp)
            return attend

        for i in range(mcfg.n_layers):
            x, _, (kp, vp) = _block_apply(
                params, f"blk{i}", x, mk_attend(i), mcfg,
                moe_capacity_factor=float(max(mcfg.moe_experts, 1)),
            )
            new_k.append(kp)
            new_v.append(vp)
        logits = lm_head(params, x)[0]
        last = jnp.take(logits, jnp.maximum(n_valid - 1, 0), axis=0)
        return {**state, "k": tuple(new_k), "v": tuple(new_v)}, last

    def _verify(self, params, state, draft, n_draft):
        """The speculative tick: score every live slot's current token
        plus its drafted candidates — (S, K+1) positions — in ONE
        forward through the paged pool, exactly the chunked-prefill
        shape discipline batched over slots.

        Sequence per slot: t_0 = the slot's current (last emitted)
        token at position pos, t_1..t_K = ``draft`` at pos+1..pos+K
        (``n_draft`` gates how many are real; the rest ride masked to
        the trash block, the prefill padding discipline). Query j's
        logits predict position pos+j+1 GIVEN the draft prefix — so
        greedy acceptance is the longest prefix of the draft matching
        the model's own argmax continuations (cumprod), plus the bonus
        token at the first mismatch. By induction every accepted
        token — and the bonus — is exactly what sequential one-token
        ticks would have emitted: speculation changes *when* tokens
        appear, never *which*.

        KV REWIND, by never writing what sequential decode would not
        have: attention runs against the GATHERED dense views with the
        chunk's fresh K/V OVERLAID (query j sees the draft prefix's
        entries without the pool being touched), and the pool itself
        takes ONE masked scatter after acceptance is known — accepted
        positions land, rejected/padding/dead positions route to the
        trash block. Un-advancing a rejected position is therefore a
        no-op on its pool bytes, and the pool after ANY accept/reject
        pattern is bitwise what one-token ticks leave (the parity
        tests pin it) at the same memory traffic as the decode tick
        (one gather + one scatter per pool array).

        Returns (state', emitted (S, K+1) — -1 beyond each slot's
        accepted run and on dead slots — and accepted (S,) draft-token
        counts for the acceptance-rate telemetry)."""
        cfg, mcfg = self.pool, self.cfg
        tokens, pos, live = state["tokens"], state["pos"], state["live"]
        kd = draft.shape[1]
        q = kd + 1
        seq = jnp.concatenate([tokens[:, None], draft], axis=1)  # (S, Q)
        j = jnp.arange(q)[None, :]
        p = pos[:, None] + j                                     # (S, Q)
        valid = live[:, None] & (j <= n_draft[:, None])
        p_safe = jnp.minimum(p, mcfg.max_len - 1)
        x = params["embed/tok"][seq] + params["embed/pos"][p_safe]
        row_idx = jnp.minimum(
            p_safe // cfg.block_len, state["tables"].shape[1] - 1
        )
        bid = jnp.take_along_axis(state["tables"], row_idx, axis=1)
        bid = jnp.where(valid, bid, 0)
        off = p_safe % cfg.block_len
        s_idx = jnp.arange(draft.shape[0])[:, None]  # (S, 1)
        fresh = []

        def overlay(pool_arr, new_shqd):
            """(S, H, C, D) gathered view with the fresh chunk K/V
            scattered over each slot's [pos, pos+kd] columns — the
            pool itself is NOT written here (rejected positions must
            stay untouched); entries beyond a slot's n_draft are
            garbage no valid query's causal mask can reach (query j
            attends positions <= pos + j only)."""
            dense = self._gather(pool_arr, state["tables"])
            return dense.at[s_idx, :, p_safe].set(
                jnp.moveaxis(new_shqd, 1, 2)
            )

        def mk_attend(i):
            def attend(qh, kh, vh):
                if self._fused:
                    # the kernel's overlay form IS the rewind contract
                    # (pool never written before acceptance) at every
                    # draft width, so kd == 0 needs no special case —
                    # the post-acceptance scatter routes identically
                    from ..ops.paged_attention import (
                        paged_attention_overlay,
                    )

                    o = paged_attention_overlay(
                        qh, state["k"][i], state["v"][i],
                        state["tables"], p, kh, vh, valid,
                        interpret=self.serving.interpret,
                    )
                    return o, (kh, vh)
                if kd == 0:
                    # zero draft width: rewind is definitionally inert
                    # (nothing can be rejected), so take the decode
                    # tick's write-then-gather memory pattern instead
                    # of double-buffering an overlay view — this shape
                    # IS serve_bench's isolated-machinery probe, and
                    # the write targets (bid routes dead lanes to
                    # trash) equal the post-acceptance routing below
                    kp = state["k"][i].at[bid, :, off].set(
                        jnp.moveaxis(kh, 1, 2)
                    )
                    vp = state["v"][i].at[bid, :, off].set(
                        jnp.moveaxis(vh, 1, 2)
                    )
                    o = cache_attend(
                        qh,
                        *self._gather_kv(kp, vp, state["tables"]),
                        p,
                    )
                    return o, (kp, vp)
                o = cache_attend(
                    qh,
                    overlay(state["k"][i], kh),
                    overlay(state["v"][i], vh),
                    p,
                )
                return o, (kh, vh)
            return attend

        for i in range(mcfg.n_layers):
            x, _, extras = _block_apply(
                params, f"blk{i}", x, mk_attend(i), mcfg,
                moe_capacity_factor=float(max(mcfg.moe_experts, 1)),
            )
            fresh.append(extras)
        logits = lm_head(params, x)                              # (S, Q, V)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        new_rng, keys = self._split_keys(state)
        # position 0 samples through the temperature lane (temperature
        # slots ride the verify tick with n_draft == 0: their one
        # emitted token per tick is this sample); positions >= 1 are
        # greedy-only — temperature slots never accept drafts
        first = self._sample(logits[:, 0], keys, state["temp"], live, tokens)
        g = jnp.concatenate([first[:, None], greedy[:, 1:]], axis=1)
        match = (draft == g[:, :kd]) & (
            jnp.arange(kd)[None, :] < n_draft[:, None]
        )
        acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        emit_mask = live[:, None] & (j <= acc[:, None])
        emitted = jnp.where(emit_mask, g, jnp.int32(-1))
        last_tok = jnp.take_along_axis(g, acc[:, None], axis=1)[:, 0]
        # the rewind-by-construction scatter: ONLY positions sequential
        # decode would have written (j <= acc, live) land in real
        # blocks; everything else routes to trash. At kd == 0 the
        # reference attend already wrote the pool with that exact
        # routing (the fused path never writes in attend, so it takes
        # the scatter at every draft width — at kd == 0 emit_mask is
        # exactly ``live``, the same routing).
        if kd == 0 and not self._fused:
            new_k = [kp for kp, _ in fresh]
            new_v = [vp for _, vp in fresh]
        else:
            bid_keep = jnp.where(emit_mask, bid, 0)
            new_k, new_v = [], []
            for (kh, vh) in fresh:
                new_k.append(
                    state["k"][len(new_k)].at[bid_keep, :, off].set(
                        jnp.moveaxis(kh, 1, 2)
                    )
                )
                new_v.append(
                    state["v"][len(new_v)].at[bid_keep, :, off].set(
                        jnp.moveaxis(vh, 1, 2)
                    )
                )
        new_state = {
            **state,
            "tokens": jnp.where(live, last_tok, tokens),
            "pos": pos + jnp.where(live, acc + 1, 0),
            "rng": new_rng,
            "k": tuple(new_k),
            "v": tuple(new_v),
        }
        return new_state, emitted, jnp.where(live, acc, 0)

    def _admit_prog(self, state, slot, row):
        return {
            **state,
            "tables": state["tables"].at[slot].set(row),
            "pos": state["pos"].at[slot].set(0),
            "live": state["live"].at[slot].set(False),
        }

    def _activate_prog(self, state, slot, last_logits, plen, seed, temp):
        rng = jax.random.PRNGKey(seed)
        k0, rng = jax.random.split(rng)
        greedy = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
        sampled = jax.random.categorical(
            k0, last_logits / jnp.maximum(temp, 1e-6)
        ).astype(jnp.int32)
        first = jnp.where(temp > 0.0, sampled, greedy)
        return {
            **state,
            "tokens": state["tokens"].at[slot].set(first),
            "pos": state["pos"].at[slot].set(plen),
            "live": state["live"].at[slot].set(True),
            "temp": state["temp"].at[slot].set(temp),
            "rng": state["rng"].at[slot].set(rng),
        }, first

    def _retire_prog(self, state, slot):
        return {
            **state,
            "live": state["live"].at[slot].set(False),
            "tables": state["tables"].at[slot].set(
                jnp.zeros((self.pool.max_blocks_per_seq,), jnp.int32)
            ),
        }

    def _export_prog(self, state, slot):
        """Gather one slot's paged K/V through its block table — every
        layer stacked into ONE (L, MB, H, BL, D) bulk value — plus its
        decode lanes. The device half of block migration's export: one
        gather per pool array, no per-block chatter (the one-shot
        transfer shape of arxiv 1805.08430), and ``slot`` is traced so
        every export reuses the same compiled program. Pad rows beyond
        the sequence's allocation gather the trash block; the host side
        trims them before serialization."""
        row = state["tables"][slot]
        k = jnp.stack([kp[row] for kp in state["k"]])
        v = jnp.stack([vp[row] for vp in state["v"]])
        return (
            k, v, state["tokens"][slot], state["pos"][slot],
            state["temp"][slot], state["rng"][slot],
        )

    def _import_prog(self, state, slot, table_row, scatter_row,
                     kblk, vblk, tok, pos, temp, rng):
        """Scatter a migrated sequence's (L, MB, H, BL, D) K/V bytes
        into this pool's freshly allocated blocks and install its lanes
        LIVE — the device half of block migration's import, one fused
        dispatch. ``table_row`` is the slot's new block table;
        ``scatter_row`` routes pad rows AND prefix-cache-shared rows to
        the trash block (a shared block's bytes already live in this
        pool bit-for-bit — writing them again is skipped, not risked),
        so duplicate trash writes can only disagree about garbage the
        attend mask zeroes exactly."""
        new_k = tuple(
            kp.at[scatter_row].set(kblk[i])
            for i, kp in enumerate(state["k"])
        )
        new_v = tuple(
            vp.at[scatter_row].set(vblk[i])
            for i, vp in enumerate(state["v"])
        )
        return {
            **state,
            "k": new_k,
            "v": new_v,
            "tables": state["tables"].at[slot].set(table_row),
            "tokens": state["tokens"].at[slot].set(tok),
            "pos": state["pos"].at[slot].set(pos),
            "temp": state["temp"].at[slot].set(temp),
            "rng": state["rng"].at[slot].set(rng),
            "live": state["live"].at[slot].set(True),
        }

    def _export_blocks_prog(self, state, row):
        """Gather an arbitrary block list's per-layer K/V into ONE
        (L, MB, H, BL, D) bulk value — the device half of serving a
        ``cache_fetch`` (pad rows gather the trash block; the host
        trims them before the ship frame is serialized)."""
        k = jnp.stack([kp[row] for kp in state["k"]])
        v = jnp.stack([vp[row] for vp in state["v"]])
        return k, v

    def _install_prog(self, state, scatter_row, kblk, vblk):
        """Scatter shipped (L, MB, H, BL, D) K/V bytes into freshly
        allocated blocks — the same one-compiled-scatter discipline as
        ``_import_prog`` minus the lane install: shipped prefix blocks
        warm the CACHE, no slot goes live. Pad rows route to the trash
        block."""
        return {
            **state,
            "k": tuple(
                kp.at[scatter_row].set(kblk[i])
                for i, kp in enumerate(state["k"])
            ),
            "v": tuple(
                vp.at[scatter_row].set(vblk[i])
                for i, vp in enumerate(state["v"])
            ),
        }

    def _cow_prog(self, state, src, dst):
        """Copy block ``src``'s K/V to block ``dst`` in every layer —
        the copy-on-write a whole-prompt prefix hit needs before its
        last-token prefill chunk may write (the source stays shared,
        only this sequence's table points at the copy)."""
        return {
            **state,
            "k": tuple(k.at[dst].set(k[src]) for k in state["k"]),
            "v": tuple(v.at[dst].set(v[src]) for v in state["v"]),
        }

    # ------------------------------------------------------------------
    # admission-path API (host-driven, one fused dispatch each, never on
    # the tick path of OTHER slots' decode)
    # ------------------------------------------------------------------

    def admit(self, slot: int, n_total_tokens: int,
              prompt=None) -> Admission:
        """Allocate ``blocks_for(n_total_tokens)`` blocks to ``slot`` and
        install its block table (raises PoolExhausted untouched —
        admission backpressure). The slot stays dead until activate().

        With the prefix cache on and a ``prompt`` given, the prompt's
        longest cached block-prefix is SHARED instead of allocated:
        matched blocks are retained (refcount bumped, LRU blocks
        revived) and only the uncached tail draws fresh blocks — the
        all-or-nothing contract still holds: hit-plus-tail feasibility
        is checked BEFORE any state is touched, so a backpressured
        admission raises PoolExhausted as a true no-op (free list,
        LRU order, index, and reclaim telemetry untouched — the
        request retries next tick). A hit covering the WHOLE
        prompt still needs the last prompt position's logits to sample
        the first token, so the final matched block is COPY-ON-WRITTEN
        (one fixed-shape compiled copy) and ``prefill_from`` points at
        the last prompt token — one 1-token chunk re-derives the
        activation logits, writing bitwise the bytes the shared source
        already holds, into the private copy only.

        With ``prefix_cache { tail_stride }`` on, a hit whose last
        shared tokens end MID-block COW-EXTENDS the deepest registered
        partial tail: the tail block is copied into this sequence's
        fresh block at the next chain position (same fixed-shape
        compiled copy) and prefill starts past the covered tokens —
        the copied positions are prefill-written bytes under the
        identical left context, so they are bitwise what this
        sequence's own cold prefill would write."""
        needed = self.pool.blocks_for(n_total_tokens)
        alloc = self.allocator
        hit: list[int] = []
        chain: list[bytes] = []
        if alloc.cache is not None and prompt is not None:
            # ONE digest pass per admission: the same chain serves the
            # match here and register_prefix() after prefill completes
            chain = alloc.cache.chain(prompt)
            hit = alloc.cache.match_chain(chain)
        cached = len(hit) * self.pool.block_len
        cow = bool(hit) and cached >= len(prompt)
        tail_src = tail_tokens = 0
        if (
            not cow
            and alloc.cache is not None
            and prompt is not None
            and alloc.cache.tail_stride
        ):
            tail_src, tail_tokens = alloc.cache.match_tail(
                prompt, len(hit), chain
            )
            cached += tail_tokens
        fresh_n = needed - len(hit) + (1 if cow else 0)
        protect = hit + ([tail_src] if tail_tokens else [])
        if fresh_n > alloc.headroom_excluding(protect):
            raise PoolExhausted(
                f"need {fresh_n} fresh blocks beyond a {len(hit)}-block "
                f"prefix hit, {alloc.headroom_excluding(protect)} "
                "allocatable"
            )
        if hit:
            alloc.retain(hit)
        if tail_tokens:
            # pin the tail source across alloc(): a fresh allocation may
            # otherwise LRU-reclaim the very block we are about to copy
            alloc.retain([tail_src])
        fresh = alloc.alloc(fresh_n)
        if cow:
            # the whole prompt is cached: COW the last matched block so
            # the re-derivation chunk can write without touching the
            # shared source, then drop our extra reference to it
            src, dst = hit[-1], fresh[0]
            blocks = hit[:-1] + [dst] + fresh[1:]
            self.state = self._cow_jit(
                self.state, jnp.int32(src), jnp.int32(dst)
            )
            alloc.release([src])
        elif tail_tokens:
            # partial-tail hit: copy the matched tail block into this
            # sequence's own block at the next chain position; bytes
            # beyond the covered tokens are re-prefilled or causally
            # masked, so only the covered prefix is ever observed
            blocks = hit + fresh
            self.state = self._cow_jit(
                self.state, jnp.int32(tail_src), jnp.int32(fresh[0])
            )
            alloc.release([tail_src])
        else:
            blocks = hit + fresh
        row = np.zeros((self.pool.max_blocks_per_seq,), np.int32)
        row[: len(blocks)] = blocks
        self.state = self._admit_jit(
            self.state, jnp.int32(slot), jnp.asarray(row)
        )
        self._slot_blocks[slot] = blocks
        self._slot_chain[slot] = chain
        self._slot_version[slot] = self.params_version
        return Admission(
            blocks=blocks,
            cached_tokens=cached,
            prefill_from=min(cached, max(len(prompt), 1) - 1)
            if prompt is not None else 0,
            cow_copied=cow,
            tail_tokens=tail_tokens,
        )

    def register_prefix(self, slot: int, prompt) -> int:
        """Index ``slot``'s fully-prompt-covered blocks by their chained
        content digests (called once the slot's prompt is completely
        prefilled — every registered position is prefill-written, so a
        later hit's bytes are bitwise a cold prefill's). Digests already
        present are skipped (shared blocks; concurrent identical
        prompts keep the first writer); new entries link to their
        parent digest, the chain structure eviction cascades through.
        -> newly registered blocks."""
        cache = self.allocator.cache
        if cache is None:
            return 0
        if self._slot_version.get(slot, self.params_version) \
                != self.params_version:
            # the slot's bytes were prefilled under a now-replaced
            # version (a rollout flipped mid-flight) — indexing them
            # would poison new-version admissions
            return 0
        blocks = self._slot_blocks.get(slot)
        if not blocks:
            return 0
        chain = self._slot_chain.get(slot) or cache.chain(prompt)
        new = 0
        for i, digest in enumerate(chain):
            if not cache.has(digest):
                new += cache.register(
                    digest, blocks[i],
                    parent=chain[i - 1] if i else None,
                )
        # partial-tail index: the prompt's LAST, partial block (if this
        # sequence owns one) registers at every covered stride multiple
        nb = len(chain)
        if cache.tail_stride and len(blocks) > nb:
            cache.register_tail(prompt, blocks[nb])
        return new

    def register_history(self, slot: int, tokens) -> int:
        """Index ``slot``'s FULL blocks under the chained digests of
        ``tokens`` — the whole prompt + emitted history, called at
        retirement with ``prefix_cache { decode_blocks }`` on, so a
        follow-up turn whose prompt replays this conversation hits the
        decode-written blocks too. Digests over the prompt prefix are
        identical to register_prefix()'s (chains are prefix-stable) and
        skip as already-present; the NEW registrations cover
        decode/verify-written bytes, which ride a different compiled
        shape than prefill — a warm stream over them is TOKEN-LEVEL
        identical to cold admission, not bitwise (the PR 9 cross-shape
        caveat). Only blocks every position of which was actually
        WRITTEN register: the last emitted token's K/V never is (a
        token's cache entry is written by the tick that processes it,
        which a finished stream never runs), so the chain clips to
        ``len(tokens) - 1`` positions. -> newly registered blocks."""
        cache = self.allocator.cache
        if cache is None:
            return 0
        if self._slot_version.get(slot, self.params_version) \
                != self.params_version:
            # stale-version slot (admitted before a rollout flip): its
            # decode-written bytes belong to the old weights — skip
            return 0
        blocks = self._slot_blocks.get(slot)
        if not blocks:
            return 0
        safe = (len(tokens) - 1) // self.pool.block_len
        chain = cache.chain(tokens)[:safe]
        new = 0
        for i, digest in enumerate(chain[: len(blocks)]):
            if not cache.has(digest):
                new += cache.register(
                    digest, blocks[i],
                    parent=chain[i - 1] if i else None,
                )
        return new

    def prefill_chunk(self, slot: int, tokens: np.ndarray, pos0: int):
        """Run one prompt chunk (<= max_prefill_chunk tokens) for
        ``slot``; returns the device logits at the chunk's last valid
        position (meaningful only for the final chunk)."""
        c = self.serving.max_prefill_chunk
        n = len(tokens)
        if n > c:
            raise ValueError(f"prefill chunk {n} > max_prefill_chunk {c}")
        buf = np.zeros((c,), np.int32)
        buf[:n] = tokens
        self.state, last = self._prefill_jit(
            self.params, self.state, jnp.int32(slot), jnp.asarray(buf),
            jnp.int32(pos0), jnp.int32(n),
        )
        return last

    def activate(self, slot: int, last_logits, plen: int, seed: int,
                 temperature: float | None = None) -> int:
        """Sample the first token from the final prefill chunk's logits
        (the same key discipline as generate(): k0 = first split of the
        request's key), install the slot's temperature lane, and flip
        it live. ``temperature`` None = the engine default. -> the
        first token."""
        temp = self.temperature if temperature is None else float(temperature)
        self.state, first = self._activate_jit(
            self.state, jnp.int32(slot), last_logits,
            jnp.int32(plen), jnp.int32(seed), jnp.float32(temp),
        )
        return int(first)

    def decode(self):
        """One tick: every live slot advances one token. -> emitted
        (slots,) int32 device array, -1 on dead slots."""
        self.state, emitted = self._decode_jit(self.params, self.state)
        return emitted

    def verify(self, draft, n_draft):
        """One speculative tick: every live slot advances by its
        accepted-prefix length + 1. ``draft`` (slots, K) int32 proposed
        tokens, ``n_draft`` (slots,) int32 how many are real (0 = the
        slot rides as a one-token tick; temperature slots always 0).
        K is fixed per engine (EngineConfig.spec_k sizes the compiled
        program; any K works but each distinct K is its own compile).
        -> (emitted (slots, K+1) int32 device array — -1 beyond each
        accepted run and on dead slots — accepted (slots,) int32 draft
        tokens accepted)."""
        self.state, emitted, accepted = self._verify_jit(
            self.params, self.state,
            jnp.asarray(draft, jnp.int32), jnp.asarray(n_draft, jnp.int32),
        )
        return emitted, accepted

    def export_slot(self, slot: int) -> dict:
        """One admitted slot's full migratable state as host values:
        per-layer K/V blocks gathered through the block table and
        TRIMMED to the sequence's actual allocation, the decode lanes
        (current token, position, temperature, RNG key — the key ships
        bit-for-bit, so a temperature stream's continuation samples
        through the exporter's exact key schedule), and the admission
        digest chain (so the importer can re-register prefix-cached
        blocks without re-hashing). The slot itself is untouched — the
        caller retires it once the bytes are safely on the wire."""
        blocks = self._slot_blocks.get(slot)
        if not blocks:
            raise ValueError(f"slot {slot} owns no blocks (not admitted?)")
        n = len(blocks)
        k, v, tok, pos, temp, rng = self._export_jit(
            self.state, jnp.int32(slot)
        )
        return {
            "k": np.asarray(k)[:, :n],
            "v": np.asarray(v)[:, :n],
            "token": int(tok),
            "pos": int(pos),
            "temp": float(temp),
            "rng": np.asarray(rng),
            "chain": list(self._slot_chain.get(slot) or ()),
        }

    def import_slot(self, slot: int, payload: dict) -> dict:
        """Install an exported sequence into dead ``slot``: allocate
        blocks for its K/V — SHARING this pool's cached prefix blocks
        wherever the shipped digest chain already matches (cross-host
        cache reuse: a matched block's bytes here are bitwise what the
        exporter shipped, both being prefill-written under the same
        left context) — scatter the shipped bytes into the fresh
        blocks, install the lanes live, and register fully-prompt-
        covered blocks under their shipped digests for future local
        hits. Feasibility is checked BEFORE any state is touched, so a
        backpressured import raises PoolExhausted as a true no-op (the
        fleet host retries next tick). Only fully-prefilled (activated)
        sequences may migrate: the chain's registration contract needs
        every prompt position already written. -> {"blocks", "shared",
        "registered"}."""
        alloc = self.allocator
        n = int(payload["k"].shape[1])
        chain = list(payload.get("chain") or ())
        hit: list[int] = []
        if alloc.cache is not None and chain:
            hit = alloc.cache.match_chain(chain)[:n]
        fresh_n = n - len(hit)
        if fresh_n > alloc.headroom_excluding(hit):
            raise PoolExhausted(
                f"import needs {fresh_n} fresh blocks beyond a "
                f"{len(hit)}-block prefix hit, "
                f"{alloc.headroom_excluding(hit)} allocatable"
            )
        if hit:
            alloc.retain(hit)
        fresh = alloc.alloc(fresh_n)
        blocks = hit + fresh
        mb = self.pool.max_blocks_per_seq
        table_row = np.zeros((mb,), np.int32)
        table_row[:n] = blocks
        # shared rows + pad rows scatter to trash: their bytes are
        # already here (shared) or masked garbage (pads)
        scatter_row = np.zeros((mb,), np.int32)
        scatter_row[len(hit):n] = fresh
        shape = (self.cfg.n_layers, mb) + payload["k"].shape[2:]
        kblk = np.zeros(shape, payload["k"].dtype)
        vblk = np.zeros(shape, payload["v"].dtype)
        kblk[:, :n] = payload["k"]
        vblk[:, :n] = payload["v"]
        self.state = self._import_jit(
            self.state, jnp.int32(slot),
            jnp.asarray(table_row), jnp.asarray(scatter_row),
            jnp.asarray(kblk), jnp.asarray(vblk),
            jnp.int32(payload["token"]), jnp.int32(payload["pos"]),
            jnp.float32(payload["temp"]),
            jnp.asarray(payload["rng"], jnp.uint32),
        )
        self._slot_blocks[slot] = blocks
        self._slot_chain[slot] = chain
        self._slot_version[slot] = self.params_version
        registered = 0
        if alloc.cache is not None:
            for i, digest in enumerate(chain[:n]):
                if not alloc.cache.has(digest):
                    registered += alloc.cache.register(
                        digest, blocks[i],
                        parent=chain[i - 1] if i else None,
                    )
        return {
            "blocks": blocks,
            "shared": len(hit),
            "registered": registered,
        }

    def export_blocks(self, blocks: list[int]) -> tuple:
        """Gather arbitrary registered blocks' per-layer K/V as host
        arrays ``(k, v)`` shaped (L, n, H, BL, D) — the byte payload of
        a ``cache_ship`` reply. The caller retains the blocks across
        the gather (an unlucky concurrent admission could otherwise
        LRU-reclaim them mid-read)."""
        n = len(blocks)
        mb = self.pool.max_blocks_per_seq
        if n > mb:
            raise ValueError(
                f"export_blocks of {n} blocks exceeds the "
                f"{mb}-block fixed gather shape"
            )
        row = np.zeros((mb,), np.int32)
        row[:n] = blocks
        k, v = self._export_blocks_jit(self.state, jnp.asarray(row))
        return np.asarray(k)[:, :n], np.asarray(v)[:, :n]

    def install_prefix(self, chain: list[bytes], k, v) -> dict:
        """Warm this pool with a peer's shipped prefix: allocate fresh
        blocks for every chain position not already cached locally,
        scatter the shipped per-layer K/V bytes into them (one compiled
        dispatch, no lane touched), register them under the shipped
        digests, and PARK them on the LRU — the next admission matching
        this chain shares them exactly as if they had been prefilled
        here. Feasibility is checked before any state is touched:
        a backpressured install raises PoolExhausted as a true no-op
        (the fleet host degrades the request to plain prefill). ->
        {"installed", "shared"} block counts. Idempotent: re-delivering
        the same ship installs nothing."""
        alloc = self.allocator
        if alloc.cache is None or not alloc.lru_enabled:
            # without LRU parking a refcount-0 block cannot outlive the
            # install call — nothing to warm (the host only fetches
            # when prefix_lru is on)
            return {"installed": 0, "shared": 0}
        n = len(chain)
        mb = self.pool.max_blocks_per_seq
        if n > mb or int(k.shape[1]) != n:
            raise ValueError(
                f"install_prefix: {n} digests vs {int(k.shape[1])} "
                f"shipped blocks (table width {mb})"
            )
        have = alloc.cache.match_chain(chain)
        todo = n - len(have)
        if todo == 0:
            return {"installed": 0, "shared": n}
        if todo > alloc.headroom_excluding(have):
            raise PoolExhausted(
                f"install needs {todo} fresh blocks beyond a "
                f"{len(have)}-block local prefix, "
                f"{alloc.headroom_excluding(have)} allocatable"
            )
        # pin the locally-matched parents across alloc(): evicting one
        # would orphan the chain we are about to extend
        if have:
            alloc.retain(have)
        fresh = alloc.alloc(todo)
        scatter_row = np.zeros((mb,), np.int32)
        scatter_row[:todo] = fresh
        shape = (self.cfg.n_layers, mb) + tuple(k.shape[2:])
        kblk = np.zeros(shape, k.dtype)
        vblk = np.zeros(shape, v.dtype)
        kblk[:, :todo] = k[:, len(have):]
        vblk[:, :todo] = v[:, len(have):]
        self.state = self._install_jit(
            self.state, jnp.asarray(scatter_row),
            jnp.asarray(kblk), jnp.asarray(vblk),
        )
        for i in range(len(have), n):
            alloc.cache.register(
                chain[i], fresh[i - len(have)],
                parent=chain[i - 1] if i else None,
            )
        # the warmed blocks belong to no sequence: release parks them
        # (registered, refcount 0) on the LRU for future admissions
        alloc.release(fresh)
        if have:
            alloc.release(have)
        return {"installed": todo, "shared": len(have)}

    def retire(self, slot: int) -> None:
        """Release the slot's blocks (refcount decrement: shared prefix
        blocks stay live for their other owners, registered refcount-0
        blocks park on the LRU list, the rest return to the free list
        as reusable garbage, masked wherever gathered) and kill its
        lane."""
        self.state = self._retire_jit(self.state, jnp.int32(slot))
        self._slot_chain.pop(slot, None)
        self._slot_version.pop(slot, None)
        blocks = self._slot_blocks.pop(slot, None)
        if blocks:
            self.allocator.release(blocks)

    # ------------------------------------------------------------------
    # live weight rollout (serve/rollout.py): dual-version param slots
    # ------------------------------------------------------------------

    @property
    def staged_version(self) -> int | None:
        """Version tag of the staged (not yet live) tree, or None."""
        return self._staged[0] if self._staged is not None else None

    def stage_params(self, params: dict, version: int) -> int:
        """Hold next-version ``params`` ALONGSIDE the live tree (dual-
        resident: both fit in HBM until the flip — netlint ROL001 prices
        this statically). Validated against the live tree's exact
        key set, shapes, and dtypes: the compiled programs are reused
        across the flip, so a mismatched save must be rejected HERE,
        loudly, never staged. -> staged byte count."""
        version = int(version)
        if version == self.params_version:
            raise ValueError(
                f"stage_params: version {version} is already live"
            )
        cur = self.params
        missing = sorted(set(cur) - set(params))
        extra = sorted(set(params) - set(cur))
        if missing or extra:
            raise ValueError(
                f"stage_params v{version}: param tree mismatch "
                f"(missing {missing[:3]}, extra {extra[:3]})"
            )
        nbytes = 0
        for name, live in cur.items():
            a = np.asarray(params[name])
            if tuple(a.shape) != tuple(live.shape):
                raise ValueError(
                    f"stage_params v{version}: {name!r} shape "
                    f"{tuple(a.shape)} != live {tuple(live.shape)}"
                )
            if a.dtype != np.asarray(live).dtype:
                raise ValueError(
                    f"stage_params v{version}: {name!r} dtype "
                    f"{a.dtype} != live {np.asarray(live).dtype}"
                )
            nbytes += a.nbytes
        self._staged = (version, dict(params))
        return nbytes

    def unstage(self) -> None:
        """Drop the staged tree (a quarantined/aborted version)."""
        self._staged = None

    def flip_params(self) -> dict:
        """Atomic tick-boundary hot-swap: the staged tree becomes live,
        the previous tree stays PINNED for rollback, and the prefix
        cache is purged (its bytes were written under the old weights —
        a warm hit across versions would poison the pool). In-flight
        slots ride through on their already-written K/V; nothing drains.
        -> {"version", "prev_version", "purged_blocks"}."""
        if self._staged is None:
            raise ValueError("flip_params: nothing staged")
        version, params = self._staged
        self._prev = (self.params_version, self.params)
        self.params, self.params_version = params, version
        self._staged = None
        return {
            "version": version,
            "prev_version": self._prev[0],
            "purged_blocks": self.allocator.purge_cache(),
        }

    def rollback_params(self) -> dict:
        """Restore the pinned previous version (canary parity abort).
        Purges the cache again — blocks written under the aborted
        version are garbage to the restored one. Idempotent hazard-free:
        raises if no previous version is pinned."""
        if self._prev is None:
            raise ValueError("rollback_params: no previous version pinned")
        version, params = self._prev
        aborted = self.params_version
        self.params, self.params_version = params, version
        self._prev = None
        self._staged = None
        return {
            "version": version,
            "aborted_version": aborted,
            "purged_blocks": self.allocator.purge_cache(),
        }
