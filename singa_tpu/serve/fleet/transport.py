"""Fleet transport: one-shot messages + latest-wins status, two wirings.

The fleet never RPCs (arxiv 1805.08430's complaint): hosts exchange
self-contained one-shot messages — a migrated sequence, a forwarded
request, a shutdown — and publish latest-wins status snapshots the
router's occupancy feedback reads. Three interchangeable wirings behind
one API (the third, ``comm.wire.SocketTransport``, is the production
TCP path — CRC'd frames, retry/backoff, at-least-once redelivery with
dedupe — selected by ``fleet { transport: socket }``; this module holds
the two deterministic drill wirings):

  ``LocalTransport``   in-process deques: the serve_bench ``--fleet``
      drill and the unit tests run a whole multi-host fleet in one
      process, deterministically, with the REAL wire bytes (migrate
      payloads are serialized/deserialized even in-process, so every
      CI run proves the codec).

  ``Mailbox``          filesystem mailboxes under one shared root
      (``<root>/<host>/inbox/*.msg``): the cross-OS-process wiring —
      the same shape the 2-rank mp drills launch, no sockets, no
      jax.distributed. Every file lands via the coord plane's
      ``atomic_write_bytes`` (pid-suffixed tmp + rename), so a reader
      sees a message absent or complete, never torn — the commit
      markers' discipline at message grain. Ordering is per-sender
      monotonic (a send counter in the filename); cross-sender order
      follows wall time, which is all a fleet needs (each message is
      self-contained).

Message kinds (``Message.kind``): ``migrate`` (a serialized
MigratedSequence), ``request`` (a JSON-encoded generation request),
``result`` (a JSON-encoded finished stream), ``cache_fetch`` (a
JSON-encoded prefix-digest chain a host wants a peer's warm blocks
for) and its bulk reply ``cache_ship`` (the matched blocks' per-layer
K/V bytes as ONE frame — the fleet prefix cache,
serve/fleet/migrate.py), ``weight_ship`` (a next-version param tree as
ONE CRC-guarded bulk frame) and ``rollout`` (the JSON control channel
driving stage/flip/rollback and their acks — the live weight rollout,
serve/rollout.py), ``shutdown`` (empty payload). ``status`` is
NOT a message — it rides the latest-wins ``publish``/``statuses``
side channel so a slow consumer never backs up the feedback loop.
"""

from __future__ import annotations

import collections
import dataclasses
import glob
import json
import os
import time

from ...resilience.coord import atomic_write_bytes

#: message kinds the fleet speaks
KINDS = (
    "migrate", "request", "result", "shutdown",
    "cache_fetch", "cache_ship", "weight_ship", "rollout",
)


@dataclasses.dataclass(frozen=True)
class Message:
    kind: str
    src: str
    payload: bytes


class LocalTransport:
    """In-process transport: per-endpoint FIFO deques + a status dict.
    Deterministic (no clocks in the order), single-threaded by
    construction — the fleet drill's tick loop is the only driver."""

    def __init__(self):
        self._inbox: dict[str, collections.deque[Message]] = {}
        self._status: dict[str, dict] = {}

    def register(self, name: str) -> None:
        self._inbox.setdefault(name, collections.deque())

    def send(self, dst: str, kind: str, payload: bytes, *,
             src: str) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown message kind {kind!r}")
        if dst not in self._inbox:
            raise KeyError(f"unknown destination {dst!r}")
        self._inbox[dst].append(Message(kind, src, payload))

    def recv(self, name: str) -> list[Message]:
        """Drain and return every queued message for ``name``."""
        box = self._inbox.get(name)
        if not box:
            return []
        out = list(box)
        box.clear()
        return out

    def publish(self, name: str, status: dict) -> None:
        self._status[name] = dict(status)

    def statuses(self) -> dict[str, dict]:
        """Latest published status per endpoint (latest wins)."""
        return {k: dict(v) for k, v in self._status.items()}


class Mailbox:
    """Filesystem transport rooted at one shared directory. Safe for
    one reader per inbox and any number of writers (atomic publish,
    unique per-sender filenames)."""

    def __init__(self, root: str):
        self.root = root
        self._seq: dict[str, int] = {}
        os.makedirs(os.path.join(root, "status"), exist_ok=True)

    def _inbox_dir(self, name: str) -> str:
        return os.path.join(self.root, name, "inbox")

    def register(self, name: str) -> None:
        os.makedirs(self._inbox_dir(name), exist_ok=True)

    def send(self, dst: str, kind: str, payload: bytes, *,
             src: str) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown message kind {kind!r}")
        os.makedirs(self._inbox_dir(dst), exist_ok=True)
        n = self._seq[src] = self._seq.get(src, 0) + 1
        header = json.dumps(
            {"kind": kind, "src": src, "seq": n}
        ).encode("utf-8")
        name = f"{time.time_ns():020d}_{src}_{n:06d}.msg"
        atomic_write_bytes(
            os.path.join(self._inbox_dir(dst), name),
            header + b"\n" + payload,
        )

    def recv(self, name: str) -> list[Message]:
        """Read-and-delete every complete message in arrival order."""
        out: list[Message] = []
        for path in sorted(
            glob.glob(os.path.join(self._inbox_dir(name), "*.msg"))
        ):
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue  # racing a writer's rename; next recv gets it
            head, _, payload = data.partition(b"\n")
            try:
                header = json.loads(head.decode("utf-8"))
            except ValueError:
                continue  # foreign file; leave it
            os.unlink(path)
            out.append(
                Message(header["kind"], header["src"], payload)
            )
        return out

    def publish(self, name: str, status: dict) -> None:
        atomic_write_bytes(
            os.path.join(self.root, "status", f"{name}.json"),
            json.dumps(status).encode("utf-8"),
        )

    def statuses(self) -> dict[str, dict]:
        out: dict[str, dict] = {}
        for path in glob.glob(os.path.join(self.root, "status", "*.json")):
            try:
                with open(path, encoding="utf-8") as f:
                    out[os.path.basename(path)[:-5]] = json.load(f)
            except (OSError, ValueError):
                continue  # torn/absent never poisons the feedback loop
        return out
