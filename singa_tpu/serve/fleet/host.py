"""Fleet host: one engine + scheduler wearing a role in a multi-host
serving fleet.

The reference binary picked Worker or Server by process rank
(src/main.cc:49-55); a fleet host picks ``prefill``, ``decode``, or
``unified`` the same way (``role_for_rank``, fed by the ``fleet {}``
conf block and ``-procsID``):

  prefill   runs admission + chunked prefill ONLY (the scheduler's
            decode phase is gated off): once a request's prompt is
            fully prefilled and its first token sampled, the filled
            sequence is EXPORTED — paged KV blocks, lanes, digest
            chain, one bulk message (fleet/migrate.py) — to the
            least-loaded decode-capable peer. Prefill is the
            compute-bound, batch-1 half of serving; giving it its own
            hosts keeps long prompts from ever stealing a decode
            tick (the disaggregation argument).
  decode    accepts migrated sequences into free slots and runs the
            fixed-shape decode/verify tick ONLY. It executes ZERO
            prefill chunks — the deterministic role-split proof the
            serve_bench ``--fleet`` gate pins.
  unified   both halves on one host (the PR 9 single-host behavior;
            also the degenerate 1-host fleet).

Token streams are IDENTICAL to a single unified host by construction:
migration copies pool bytes and lanes bitwise (fleet/migrate.py's
correctness bar), and the decode program depends only on a slot's own
lanes and table.

A SIGTERM'd host drains at a tick boundary like any training rank
(resilience/coord.py discipline) — but ``drain`` routes in-flight
sequences to a PEER over the migration path instead of only handing
them back to the launcher: decoding sequences migrate (their streams
resume mid-token to full parity), prefilling/queued requests forward
as fresh request messages (their prefill work re-runs from scratch,
the PR 9 hand-back semantics), and only a fleet with no capable peer
falls back to the launcher hand-back. Either way the host exits
EXIT_RESUMABLE (75).
"""

from __future__ import annotations

import json
import time

import numpy as np

from ...comm.wire import WireError
from ...resilience.faults import InjectedCrash
from ...resilience.preemption import EXIT_RESUMABLE
from ..engine import Engine, EngineConfig
from ..kv_pool import PoolExhausted
from ..scheduler import Request, Scheduler
from . import migrate
from .router import (
    DECODE_CAPABLE,
    MAX_PUBLISHED_DIGESTS,
    PREFILL_CAPABLE,
    chain_coverage,
    decode_request,
    load_score,
)

ROLES = ("unified", "prefill", "decode")

#: the well-known mailbox finished streams are reported to when a host
#: runs detached from its driver (``results_to``)
FRONTDOOR = "frontdoor"

#: rollout parity probes ride the normal request path under reserved
#: rids at/below this base (probe i -> PROBE_RID_BASE - i); their
#: finished streams report to the rollout controller over the
#: ``rollout`` channel, never to the front door
PROBE_RID_BASE = -1_000_000


def role_for_rank(fleet_cfg, rank: int) -> str:
    """The reference's rank-picks-role dispatch (main.cc:49-55:
    ``procsID < nworker_procs`` -> Worker, else Server): with ``role:
    auto``, ranks below ``prefill_hosts`` prefill and the rest decode;
    an explicit role pins every rank (the single-role fleet)."""
    if fleet_cfg.role != "auto":
        return fleet_cfg.role
    return "prefill" if rank < max(1, fleet_cfg.prefill_hosts) else "decode"


def fleet_topology(fleet_cfg, n_hosts: int) -> list[tuple[str, str]]:
    """-> [(name, role)] in rank order. Explicit ``peers`` entries ARE
    the topology (one per rank, the hostfile pattern); otherwise
    ``n_hosts`` synthetic names take their role from
    ``role_for_rank``."""
    if fleet_cfg.peers:
        return [(p.name, p.role) for p in fleet_cfg.peers]
    return [
        (f"host{k}", role_for_rank(fleet_cfg, k)) for k in range(n_hosts)
    ]


class FleetHost:
    """One serving host of a fleet: a role-gated Scheduler plus the
    migration/forwarding glue. ``peers`` maps every OTHER host's name
    to its role (the static topology); live placement reads the
    transport's status feedback and falls back to the static map while
    a peer has not published yet.

    ``latent`` names the ELASTIC slice of the topology (``fleet {
    min_hosts / max_hosts }``): peers that are declared but not
    launched yet. A latent peer gets NO static-fallback placements —
    exporting a sequence to a host that may never start would strand
    it — until it JOINS by publishing a serving status (its announce;
    the observer logs a ``fleet_join`` event and starts placing onto
    it). Leaving is the existing drain-to-peer path: the tombstone
    status takes the host out of every candidate set, and re-joining
    is just publishing a serving status again."""

    def __init__(self, name: str, role: str, engine: Engine, transport,
                 *, peers: dict[str, str] | None = None,
                 latent: set[str] | None = None, recorder=None,
                 preemption=None, results_to: str | None = None,
                 fault_plan=None, log=lambda s: None):
        if role not in ROLES:
            raise ValueError(f"fleet role must be one of {ROLES}, got "
                             f"{role!r}")
        self.name = name
        self.role = role
        self.engine = engine
        self.transport = transport
        self.peers = dict(peers or {})
        #: declared-but-not-yet-joined peers (elastic fleet): no
        #: static-fallback placements until they publish a status
        self._latent = set(latent or ()) & set(self.peers)
        self.results_to = results_to
        self.preemption = preemption
        self.log = log
        # the runtime half of netlint FLT001: a split-role host with no
        # peer for the other half can never finish (or never start) a
        # stream — reject at construction, before any request is taken.
        # LATENT peers don't count: a capable peer that may never
        # launch is not a counterpart — the live fleet must cover both
        # halves on its own
        live_roles = [
            r for n, r in self.peers.items() if n not in self._latent
        ]
        if role == "decode" and not any(
            r in PREFILL_CAPABLE for r in live_roles
        ):
            raise ValueError(
                f"decode-role host {name!r} has no prefill-capable peer "
                "among live (non-latent) hosts: nothing can ever fill "
                "its KV blocks (netlint FLT001 flags this statically)"
            )
        if role == "prefill" and not any(
            r in DECODE_CAPABLE for r in live_roles
        ):
            raise ValueError(
                f"prefill-role host {name!r} has no decode-capable peer "
                "among live (non-latent) hosts: filled sequences would "
                "have nowhere to stream (netlint FLT001 flags this "
                "statically)"
            )
        self.sched = Scheduler(
            engine, recorder=recorder, preemption=preemption, log=log,
        )
        self.sched.decode_enabled = role != "prefill"
        #: migrated sequences awaiting a free slot / blocks (import
        #: backpressure: deferred, never dropped)
        self._pending: list[tuple[migrate.MigratedSequence, str]] = []
        self._shutdown = False
        self._reported: set[int] = set()
        #: high-water mark into sched.finished (append-only), so each
        #: _flush_results pass walks only NEW results — not the whole
        #: ever-growing list every tick
        self._flushed = 0
        #: published-status change detection: the idle serve loop ticks
        #: every few ms, and rewriting an identical snapshot (possibly
        #: thousands of cached digests) through the mailbox each round
        #: is pure filesystem churn
        self._last_status: dict | None = None
        self._digest_hex: tuple[int, list[str]] = (-1, [])
        #: rotation cursor for load-score ties (_pick_peer)
        self._rr = 0
        #: peers the wire tombstoned (peer_death): excluded from every
        #: placement until the transport reports them healed — the
        #: liveness watchdog's verdict set (socket transport only; the
        #: mailbox/local wirings never raise WireError)
        self._dead: set[str] = set()
        self.migrate_in = 0
        self.migrate_out = 0
        self.blocks_in = 0
        self.blocks_out = 0
        #: fleet prefix cache: requests held out of admission while a
        #: peer's cache_ship is in flight — rid -> (request, monotonic
        #: deadline, peer, first uncovered digest). Deadline expiry (or
        #: the peer's tombstone) degrades to plain prefill; a held
        #: request is never dropped and never hangs.
        self._awaiting: dict[
            int, tuple[Request, float, str, bytes]
        ] = {}
        #: one fetch attempt per request, ever — a miss after a ship
        #: (or a degrade) must not re-fetch in a loop
        self._fetch_tried: set[int] = set()
        self.cache_fetches = 0
        self.cache_fetch_timeouts = 0
        self.cache_ships_in = 0
        self.cache_ships_out = 0
        self.ship_blocks_in = 0
        self.ship_blocks_out = 0
        self.ship_bytes_in = 0
        self.ship_bytes_out = 0
        #: rollout fault hooks (resilience/faults.py): torn_weights /
        #: swap_die key on weight-ship ordinals counted PER HOST
        self._fault_plan = fault_plan
        self._ship_seen = 0
        #: in-flight parity probes (rollout controller): reserved rids
        #: still running -> finished streams collected so far, plus the
        #: controller mailbox the probe_done report goes back to
        self._probe_wait: set[int] = set()
        self._probe_streams: dict[int, list[int]] = {}
        self._probe_reply_to: str | None = None
        transport.register(name)
        # run-start provenance: which role this rank serves — the
        # cross-rank merge keys its per-host rows on this event
        self._event("fleet_role", host=name, role=role)
        self.publish_status()

    # -- plumbing -------------------------------------------------------

    def _event(self, kind: str, **payload) -> None:
        self.sched._event(kind, **payload)

    def submit(self, req: Request) -> None:
        """Direct client-side submission (the router normally delivers
        ``request`` messages instead)."""
        self.sched.submit(req)

    @property
    def busy(self) -> bool:
        return bool(
            self.sched.busy or self._pending or self._awaiting
        )

    def _peer_snapshots(self, roles, exclude: str | None = None):
        """Published statuses of capable peers, least-loaded first;
        peers that have never published ride at the end on their
        static-topology role (boot window) — EXCEPT latent (elastic,
        not-yet-launched) peers, which join the candidate set only once
        they have announced themselves by publishing. A peer whose
        PUBLISHED role fell out of ``roles`` is excluded outright —
        that is how a drained host's tombstone (role "drained") takes
        it out of every placement decision."""
        published = {
            s.get("host"): s
            for s in self.transport.statuses().values()
            if s.get("host") in self.peers
        }
        self._note_joins(published)
        out = [
            s for h, s in published.items()
            if s.get("role") in roles and h != exclude
            and h not in self._dead
        ]
        out.sort(key=load_score)
        out.extend(
            {"host": n, "role": r}
            for n, r in sorted(self.peers.items())
            if r in roles and n not in published and n != exclude
            and n not in self._latent and n not in self._dead
        )
        return out

    def _note_joins(self, published: dict) -> None:
        """A latent peer that published a serving status has JOINED the
        fleet: admit it to placement and record the scale event (once
        per join — a later tombstone re-latents it, so a re-join is
        observable too)."""
        for h, s in published.items():
            role = s.get("role")
            if h in self._dead:
                # a tombstoned peer's LAST status lingers in the store;
                # only the wire healing it (_note_peer_deaths) may
                # re-admit it, never its stale snapshot
                continue
            if h in self._latent and role in ROLES:
                self._latent.discard(h)
                self._event("fleet_join", host=h, role=role)
                self.log(f"fleet host {self.name}: peer {h!r} joined "
                         f"as {role}")
            elif h not in self._latent and role == "drained" and (
                h in self.peers
            ):
                # a drained peer is latent again: placements stop (the
                # tombstone already guarantees that) AND a future
                # serving status counts as a fresh join event
                self._latent.add(h)
                self._event("fleet_leave", host=h)
                self.log(f"fleet host {self.name}: peer {h!r} left "
                         "(drained)")

    def _mark_dead(self, peer: str, reason: str) -> None:
        """The loud tombstone: a peer whose wire exhausted a send's
        retry budget leaves every candidate set NOW (waiting on it
        would strand sequences behind a dead endpoint). It re-latents
        too — if it ever heals, its next serving status is a fresh
        ``fleet_join``, the elastic rejoin path."""
        if peer in self._dead or peer not in self.peers:
            return
        self._dead.add(peer)
        self._latent.add(peer)
        self._event("peer_death", peer=peer, via="wire", reason=reason)
        self.log(
            f"fleet host {self.name}: peer {peer!r} unreachable "
            f"({reason}) — tombstoned"
        )

    def _note_peer_deaths(self) -> None:
        """Reconcile with the transport's liveness view each tick
        (socket transport's ``dead_peers``; the mailbox/local wirings
        have no liveness view and skip). New suspects tombstone; a
        healed peer (successful send or fresh status) drops its
        tombstone and waits in ``_latent`` for its join announce."""
        dead_fn = getattr(self.transport, "dead_peers", None)
        if dead_fn is None:
            return
        now_dead = {p for p in dead_fn() if p in self.peers}
        for p in sorted(now_dead - self._dead):
            self._mark_dead(p, "wire liveness")
        for p in self._dead - now_dead:
            self._dead.discard(p)

    def _export_with_failover(self, slot: int, req) -> str | None:
        """Export to the least-loaded decode-capable peer, tombstoning
        any whose wire fails and re-placing until one takes it or no
        candidate remains. The send happens BEFORE the slot retires
        (_export_to), so a failed attempt leaves the sequence intact
        in its slot — nothing is ever half-exported."""
        tried: set[str] = set()
        while True:
            dst = self._pick_peer(DECODE_CAPABLE, exclude=self.name)
            if dst is None or dst in tried:
                return None
            try:
                self._export_to(slot, req, dst)
                return dst
            except WireError as e:
                tried.add(dst)
                self._mark_dead(dst, str(e))

    def _send_with_failover(self, roles, kind: str,
                            payload: bytes) -> str | None:
        """One self-contained message to the least-loaded capable peer,
        with the same tombstone-and-re-place discipline."""
        tried: set[str] = set()
        while True:
            dst = self._pick_peer(roles, exclude=self.name)
            if dst is None or dst in tried:
                return None
            try:
                self.transport.send(dst, kind, payload, src=self.name)
                return dst
            except WireError as e:
                tried.add(dst)
                self._mark_dead(dst, str(e))

    def _marooned(self) -> bool:
        """A split-role host whose EVERY declared counterpart is
        tombstoned can neither finish nor start a stream — the verdict
        is a loud drain (hand-back accounting) + EXIT_RESUMABLE, never
        a silent idle loop behind a dead wire."""
        if self.role == "unified" or not self._dead:
            return False
        need = DECODE_CAPABLE if self.role == "prefill" else PREFILL_CAPABLE
        capable = {n for n, r in self.peers.items() if r in need}
        return bool(capable) and capable <= self._dead

    def _pick_peer(self, roles, exclude: str | None = None) -> str | None:
        """Least-loaded target, rotating among score TIES: published
        statuses refresh only when a peer ticks, so two exports in one
        round would otherwise both pile onto the same stale-idlest
        peer (and a cold fleet would never spread at all)."""
        snaps = self._peer_snapshots(roles, exclude=exclude)
        if not snaps:
            return None
        best = load_score(snaps[0])[:3]  # name excluded: ties rotate
        ties = [s for s in snaps if load_score(s)[:3] == best]
        pick = ties[self._rr % len(ties)]["host"]
        self._rr += 1
        return pick

    # -- the tick -------------------------------------------------------

    def tick(self) -> int:
        """One fleet round: drain the inbox (requests queue, migrations
        go pending), install pending imports into free slots, run the
        role-gated scheduler tick, export filled sequences (prefill
        role), publish fresh status. -> tokens emitted."""
        self._recv()
        self._note_peer_deaths()
        self._expire_fetches()
        self._maybe_fetch()
        self._import_pending()
        emitted = self.sched.tick()
        if self.role == "prefill":
            self._export_ready()
        self._flush_probes()
        self._flush_results()
        self.publish_status()
        return emitted

    def _recv(self) -> None:
        for msg in self.transport.recv(self.name):
            if msg.kind == "request":
                req = decode_request(msg.payload)
                try:
                    self.sched.submit(req)
                except ValueError as e:
                    # single-host submit raises to ITS caller (the
                    # client holding the Request); here the caller is
                    # a wire peer, and one inadmissible request must
                    # not take the host down — reject it back to the
                    # front door instead
                    self._event("reject", rid=req.rid, reason=str(e))
                    self.log(f"fleet host {self.name}: rejected "
                             f"request {req.rid}: {e}")
                    if self.results_to is not None:
                        try:
                            self.transport.send(
                                self.results_to, "result",
                                json.dumps({
                                    "rid": req.rid, "tokens": [],
                                    "host": self.name, "error": str(e),
                                }).encode("utf-8"),
                                src=self.name,
                            )
                        except WireError:
                            pass  # front door gone too; verdict logged
            elif msg.kind == "migrate":
                self._pending.append(
                    (migrate.deserialize(msg.payload), msg.src)
                )
            elif msg.kind == "cache_fetch":
                self._serve_fetch(msg)
            elif msg.kind == "cache_ship":
                self._install_ship(msg)
            elif msg.kind == "weight_ship":
                self._handle_weight_ship(msg)
            elif msg.kind == "rollout":
                self._handle_rollout(msg)
            elif msg.kind == "shutdown":
                self._shutdown = True

    def _import_pending(self) -> None:
        """Install migrated sequences into free slots (FIFO). A full
        pool/slot set defers the rest to the next tick — admission
        backpressure at fleet grain, requests wait and are never
        dropped."""
        while self._pending:
            free = [
                s for s in range(self.engine.serving.slots)
                if s not in self.sched._slot_req
            ]
            if not free:
                break
            mseq, src = self._pending[0]
            slot = free[0]
            if mseq.version != self.engine.params_version:
                # version skew (mid-rollout fleet): the migrated KV was
                # written by DIFFERENT weights — scattering it into our
                # pool would poison the prefix cache and splice two
                # models into one stream. Degrade to a cold re-prefill
                # from the original prompt under OUR weights: emitted
                # tokens only ever deliver at finish (_flush_results),
                # so the client still sees exactly one consistent
                # stream. Never a drop, never a poisoned pool.
                self._pending.pop(0)
                req = Request(
                    rid=mseq.rid,
                    prompt=np.asarray(mseq.prompt, np.int32),
                    max_new_tokens=mseq.max_new_tokens,
                    temperature=mseq.temperature,
                    seed=mseq.seed,
                    eos=None if mseq.eos is None else int(mseq.eos),
                )
                self.migrate_in += 1
                self._event(
                    "migrate_in", rid=req.rid, src=src, slot=-1,
                    blocks=0, shared=0, registered=0, tokens_done=0,
                    skew=True, frame_version=mseq.version,
                    live_version=self.engine.params_version,
                )
                self.sched.submit(req)
                continue
            try:
                info = migrate.import_sequence(self.engine, slot, mseq)
            except PoolExhausted:
                self._event(
                    "backpressure", queued=len(self._pending),
                    free_blocks=self.engine.allocator.free_blocks,
                    site="migrate_in",
                )
                break
            self._pending.pop(0)
            now = time.perf_counter()
            req = Request(
                rid=mseq.rid,
                prompt=np.asarray(mseq.prompt, np.int32),
                max_new_tokens=mseq.max_new_tokens,
                temperature=mseq.temperature,
                seed=mseq.seed,
                eos=None if mseq.eos is None else int(mseq.eos),
            )
            req.status = "decoding"
            req.slot = slot
            req.tokens = list(mseq.emitted)
            req._prefilled = len(req.prompt)
            # queue-inclusive latency survives migration inside one
            # clock domain; a cross-host import re-stamps at arrival
            req.enqueue_mono = mseq.enqueue_mono or now
            req.admit_mono = req.enqueue_mono
            req.admit_wall = time.time()
            req.first_token_mono = now
            self.sched._slot_req[slot] = req
            self.migrate_in += 1
            self.blocks_in += mseq.n_blocks
            self._event(
                "migrate_in", rid=req.rid, src=src, slot=slot,
                blocks=mseq.n_blocks, shared=info["shared"],
                registered=info["registered"],
                tokens_done=len(req.tokens),
            )

    # -- fleet prefix cache (cache_fetch / cache_ship) ------------------

    def _maybe_fetch(self) -> None:
        """For each NEW queued request whose prompt chain a peer's
        published digests cover deeper than our own cache, send ONE
        ``cache_fetch`` and hold the request out of admission until
        the ship lands (or the deadline passes — degrade to plain
        prefill, never a hang). One attempt per request, ever. Any
        peer role qualifies as a source: decode hosts hold migrated
        and decode-registered history too."""
        cache = self.engine.allocator.cache
        if (
            cache is None
            or not self.engine.serving.prefix_lru
            or not self.peers
            or not self.sched._queue
        ):
            return
        snaps = [
            s for s in self.transport.statuses().values()
            if s.get("host") in self.peers
            and s.get("host") not in self._dead
            and s.get("role") in ROLES
            and s.get("cached_digests")
        ]
        if not snaps:
            return
        timeout = self.engine.serving.prefix_fetch_timeout_s
        inflight = {head for _, _, _, head in self._awaiting.values()}
        for req in list(self.sched._queue):
            if req.rid in self._fetch_tried:
                continue
            chain = cache.chain(req.prompt)
            if not chain:
                self._fetch_tried.add(req.rid)
                continue
            local = len(cache.match_chain(chain))
            if local >= len(chain):
                self._fetch_tried.add(req.rid)
                continue
            if chain[local] in inflight:
                # a ship covering this request's first uncovered block
                # is already in flight (the shared-prefix workload:
                # every queued request misses on the SAME prefix) — do
                # not multiply the wire traffic, but DO hold the
                # request: admitted now it would prefill cold and
                # register the very blocks the ship carries, wasting
                # both. The landing ship releases every held request
                # it covers (or the deadline degrades them)
                self._fetch_tried.add(req.rid)
                kept = [r for r in self.sched._queue if r is not req]
                self.sched._queue.clear()
                self.sched._queue.extend(kept)
                self._awaiting[req.rid] = (
                    req, time.monotonic() + timeout, "", chain[local],
                )
                continue
            self._fetch_tried.add(req.rid)
            hex_chain = [d.hex() for d in chain]
            best, best_n = None, local
            for s in snaps:
                n = chain_coverage(hex_chain, s)
                if n > best_n:
                    best, best_n = s.get("host"), n
            if best is None:
                continue
            try:
                self.transport.send(
                    best, "cache_fetch",
                    migrate.serialize_fetch(
                        req.rid, chain,
                        version=self.engine.params_version,
                    ),
                    src=self.name,
                )
            except WireError as e:
                self._mark_dead(best, str(e))
                continue
            # hold the request aside (identity filter: Request's
            # dataclass == would compare prompt arrays); it re-enters
            # via submit() when the ship lands or the deadline passes
            kept = [r for r in self.sched._queue if r is not req]
            self.sched._queue.clear()
            self.sched._queue.extend(kept)
            self._awaiting[req.rid] = (
                req, time.monotonic() + timeout, best, chain[local],
            )
            inflight.add(chain[local])
            self.cache_fetches += 1
            self._event(
                "cache_fetch", rid=req.rid, peer=best,
                blocks=len(chain), local_blocks=local,
                peer_blocks=best_n,
            )

    def _expire_fetches(self) -> None:
        """Degrade every held request whose ship deadline passed (or
        whose source peer died) to plain prefill — backpressure on the
        fetch path must never strand a request."""
        if not self._awaiting:
            return
        now = time.monotonic()
        for rid in list(self._awaiting):
            req, deadline, peer, _head = self._awaiting[rid]
            if now < deadline and peer not in self._dead:
                continue
            del self._awaiting[rid]
            self.cache_fetch_timeouts += 1
            self._event("cache_fetch_timeout", rid=rid, peer=peer)
            self.sched.submit(req)

    def _serve_fetch(self, msg) -> None:
        """Answer a peer's ``cache_fetch`` with ONE ``cache_ship``
        bulk frame: our longest cached prefix of its digest chain,
        blocks retained across the compiled gather so a concurrent
        admission cannot reclaim them mid-read. An empty match still
        ships (zero blocks): the requester degrades immediately
        instead of waiting out its deadline on our stale
        advertisement."""
        try:
            rid, chain, version = migrate.deserialize_fetch(msg.payload)
        except ValueError as e:
            self.log(f"fleet host {self.name}: bad cache_fetch from "
                     f"{msg.src!r}: {e}")
            return
        cache = self.engine.allocator.cache
        blocks: list[int] = []
        if version != self.engine.params_version:
            # version skew (mid-rollout fleet): our cached KV was
            # written by weights the requester is not running — answer
            # with the EXISTING empty ship so it degrades to plain
            # prefill immediately instead of installing poison (or
            # waiting out its deadline)
            self._event(
                "cache_fetch", rid=rid, peer=msg.src, dir="serve",
                skew=True, frame_version=version,
                live_version=self.engine.params_version,
            )
        elif cache is not None:
            blocks = cache.match_chain(chain)[
                : self.engine.pool.max_blocks_per_seq
            ]
        if blocks:
            self.engine.allocator.retain(blocks)
            try:
                k, v = self.engine.export_blocks(blocks)
            finally:
                self.engine.allocator.release(blocks)
        else:
            shape = (
                self.engine.cfg.n_layers, 0, self.engine.cfg.n_heads,
                self.engine.pool.block_len, self.engine.cfg.head_dim,
            )
            k = np.zeros(shape, np.float32)
            v = np.zeros(shape, np.float32)
        data = migrate.serialize_ship(
            rid, chain[: len(blocks)], k, v,
            version=self.engine.params_version,
        )
        try:
            self.transport.send(msg.src, "cache_ship", data,
                                src=self.name)
        except WireError as e:
            self._mark_dead(msg.src, str(e))
            return
        self.cache_ships_out += 1
        self.ship_blocks_out += len(blocks)
        self.ship_bytes_out += len(data)
        self._event(
            "cache_ship", rid=rid, peer=msg.src, dir="out",
            blocks=len(blocks), bytes=len(data),
        )

    def _install_ship(self, msg) -> None:
        """Install a peer's ``cache_ship`` into our pool (scatter +
        register + LRU-park, engine.install_prefix) and release the
        held request back into admission — where it now hits locally,
        sharing the installed blocks exactly like home-grown ones. A
        backpressured (or empty, or duplicate) ship still releases
        the request: worst case is plain prefill."""
        waiting = None
        try:
            ship = migrate.deserialize_ship(msg.payload)
        except ValueError as e:
            self.log(f"fleet host {self.name}: bad cache_ship from "
                     f"{msg.src!r}: {e}")
            return
        waiting = self._awaiting.pop(ship["rid"], None)
        installed = shared = 0
        skew = ship["version"] != self.engine.params_version
        if skew:
            # version skew: the shipped KV was written under different
            # weights (the sender flipped — or we did — between fetch
            # and ship). Installing it would poison the pool; skip the
            # scatter but STILL release every held request below, so
            # worst case stays plain prefill
            ship = dict(ship, chain=[])
        if ship["chain"]:
            try:
                info = self.engine.install_prefix(
                    ship["chain"], ship["k"], ship["v"]
                )
                installed = info["installed"]
                shared = info["shared"]
            except PoolExhausted:
                self._event(
                    "backpressure",
                    queued=len(self.sched._queue),
                    free_blocks=self.engine.allocator.free_blocks,
                    site="cache_ship",
                )
        self.cache_ships_in += 1
        self.ship_blocks_in += installed
        self.ship_bytes_in += len(msg.payload)
        self._event(
            "cache_ship", rid=ship["rid"], peer=msg.src, dir="in",
            blocks=installed, shared=shared, bytes=len(msg.payload),
            cached_tokens=int(
                (installed + shared) * self.engine.pool.block_len
            ),
            skew=skew,
        )
        # release the ship's own request AND every piggybacked hold
        # whose first uncovered block the installed chain covers — they
        # re-enter admission and hit the freshly registered blocks
        covered = set(ship["chain"])
        for rid in list(self._awaiting):
            held, _deadline, _peer, head = self._awaiting[rid]
            if head in covered:
                del self._awaiting[rid]
                self.sched.submit(held)
        if waiting is not None:
            self.sched.submit(waiting[0])

    # -- live weight rollout (serve/rollout.py) -------------------------

    def _rollout_ack(self, dst: str, cmd: str, **fields) -> None:
        """One control reply to the rollout controller (kind
        ``rollout``). A dead controller is a tombstone like any other
        peer — the rollout pauses on ITS timeout, the host keeps
        serving."""
        body = {"cmd": cmd, "host": self.name}
        body.update(fields)
        try:
            self.transport.send(
                dst, "rollout", json.dumps(body).encode("utf-8"),
                src=self.name,
            )
        except WireError as e:
            self._mark_dead(dst, str(e))

    def _handle_weight_ship(self, msg) -> None:
        """Stage a shipped next-version param tree alongside the live
        one (engine.stage_params). Serving is untouched either way: a
        torn frame (CRC/format reject) nacks back to the controller —
        which retries, then quarantines the version — while the live
        weights keep answering every stream."""
        self._ship_seen += 1
        payload = msg.payload
        if self._fault_plan is not None:
            if self._fault_plan.fire("swap_die", at=self._ship_seen):
                # host death mid-stage: propagates out of the serve
                # loop; peers tombstone it (liveness), streams fail
                # over, and the controller's stage-ack timeout turns
                # the rollout verdict into "paused"
                raise InjectedCrash(
                    f"fleet host {self.name}: swap_die at weight_ship "
                    f"{self._ship_seen}"
                )
            if self._fault_plan.fire("torn_weights", at=self._ship_seen):
                # tear the bulk frame in half: the codec's CRC (or the
                # npz container itself) must reject it downstream
                payload = payload[: max(1, len(payload) // 2)]
        try:
            version, tree = migrate.deserialize_weights(payload)
        except Exception as e:  # torn frame: format/CRC/zip all land here
            self._event(
                "weight_ship", dir="in", ok=False,
                bytes=len(payload), error=str(e)[:200],
            )
            self.log(f"fleet host {self.name}: rejected weight_ship "
                     f"from {msg.src!r}: {e}")
            self._rollout_ack(msg.src, "stage_ack", ok=False,
                             error="torn")
            return
        try:
            staged_bytes = self.engine.stage_params(tree, version)
        except ValueError as e:
            self._event(
                "rollout_stage", version=version, ok=False,
                error=str(e)[:200],
            )
            self._rollout_ack(msg.src, "stage_ack", ok=False,
                             version=version, error=str(e)[:200])
            return
        self._event(
            "weight_ship", dir="in", ok=True, version=version,
            bytes=len(msg.payload),
        )
        self._event(
            "rollout_stage", version=version, ok=True,
            staged_bytes=staged_bytes,
        )
        self._rollout_ack(msg.src, "stage_ack", ok=True, version=version)

    def _handle_rollout(self, msg) -> None:
        """Rollout control plane: flip / rollback / unstage / probe.
        The handler runs in _recv, BETWEEN scheduler ticks — applying a
        flip here IS the atomic tick boundary: no stream ever decodes
        one token under each version within a tick."""
        try:
            body = json.loads(msg.payload.decode("utf-8"))
        except ValueError as e:
            self.log(f"fleet host {self.name}: bad rollout frame from "
                     f"{msg.src!r}: {e}")
            return
        cmd = body.get("cmd")
        if cmd == "flip":
            try:
                res = self.engine.flip_params()
            except ValueError as e:
                self._rollout_ack(msg.src, "flip_ack", ok=False,
                                 error=str(e)[:200])
                return
            self._event(
                "rollout_flip", version=res["version"],
                prev_version=res["prev_version"], tick=self.sched.ticks,
                purged_blocks=res["purged_blocks"],
            )
            self.log(f"fleet host {self.name}: flipped to weights "
                     f"v{res['version']} at tick {self.sched.ticks} "
                     f"(purged {res['purged_blocks']} cached blocks)")
            self._rollout_ack(msg.src, "flip_ack", ok=True,
                             version=res["version"],
                             tick=self.sched.ticks)
        elif cmd == "rollback":
            if self.engine._prev is not None:
                res = self.engine.rollback_params()
                self._event(
                    "rollout_flip", version=res["version"],
                    rollback=True,
                    aborted_version=res["aborted_version"],
                    tick=self.sched.ticks,
                    purged_blocks=res["purged_blocks"],
                )
                self.log(f"fleet host {self.name}: rolled back to "
                         f"weights v{res['version']} (aborted "
                         f"v{res['aborted_version']})")
            else:
                # never flipped here: just drop anything staged
                self.engine.unstage()
            self._rollout_ack(msg.src, "rollback_ack", ok=True,
                             version=self.engine.params_version)
        elif cmd == "unstage":
            self.engine.unstage()
            self._rollout_ack(msg.src, "unstage_ack", ok=True,
                             version=self.engine.params_version)
        elif cmd == "probe":
            self._start_probes(msg.src, body)
        else:
            self.log(f"fleet host {self.name}: unknown rollout cmd "
                     f"{cmd!r} from {msg.src!r}")

    def _start_probes(self, src: str, body: dict) -> None:
        """Submit the controller's parity probes through the REAL
        serving path (scheduler admission, post-flip cold prefill —
        the cache was purged at the flip, so probes exercise the new
        weights end to end). Reserved rids keep them out of the front
        door; _flush_probes reports the finished streams back."""
        prompts = body.get("prompts") or []
        max_new = int(body.get("max_new", 8))
        temperature = float(body.get("temperature", 0.0))
        seeds = body.get("seeds") or [0] * len(prompts)
        self._probe_wait = set()
        self._probe_streams = {}
        self._probe_reply_to = src
        for i, prompt in enumerate(prompts):
            rid = PROBE_RID_BASE - i
            req = Request(
                rid=rid, prompt=np.asarray(prompt, np.int32),
                max_new_tokens=max_new, temperature=temperature,
                seed=int(seeds[i]),
            )
            try:
                self.sched.submit(req)
            except ValueError as e:
                self._rollout_ack(src, "probe_done", ok=False,
                                 error=str(e)[:200])
                self._probe_wait = set()
                self._probe_reply_to = None
                return
            self._probe_wait.add(rid)
        if not self._probe_wait:
            self._rollout_ack(src, "probe_done", ok=True, streams={})
            self._probe_reply_to = None

    def _flush_probes(self) -> None:
        """Collect finished probe streams; when the whole batch is
        done, report it to the controller in one ``probe_done``."""
        if not self._probe_wait:
            return
        for req in self.sched.finished:
            if req.rid in self._probe_wait:
                self._probe_wait.discard(req.rid)
                self._probe_streams[req.rid] = [int(t) for t in req.tokens]
        if self._probe_wait:
            return
        dst = self._probe_reply_to
        streams = {str(r): t for r, t in self._probe_streams.items()}
        self._probe_streams = {}
        self._probe_reply_to = None
        if dst is not None:
            self._rollout_ack(dst, "probe_done", ok=True,
                             streams=streams)

    def _export_ready(self) -> None:
        """Ship every filled (decoding-status) sequence to a decode
        peer. With no peer reachable the sequence WAITS in its slot —
        the decode gate keeps it frozen, nothing is lost."""
        for slot in sorted(self.sched._slot_req):
            req = self.sched._slot_req[slot]
            if req.status != "decoding":
                continue
            if self._export_with_failover(slot, req) is None:
                break

    def _export_to(self, slot: int, req: Request, dst: str) -> None:
        mseq = migrate.export_sequence(self.engine, req, slot)
        data = migrate.serialize(mseq)
        self.transport.send(dst, "migrate", data, src=self.name)
        # the slot frees for the next admission; registered prefix
        # blocks park on OUR LRU too — the same prompt now serves
        # prefix hits on both hosts
        self.engine.retire(slot)
        del self.sched._slot_req[slot]
        self.migrate_out += 1
        self.blocks_out += mseq.n_blocks
        self._event(
            "migrate_out", rid=req.rid, dst=dst, slot=slot,
            blocks=mseq.n_blocks, bytes=len(data),
            tokens_done=len(req.tokens),
        )

    def _flush_results(self) -> None:
        if self.results_to is None:
            return
        # finished is append-only; an external clear (bench warmup
        # resets) can only shrink it, so clamp and rescan from there
        self._flushed = min(self._flushed, len(self.sched.finished))
        new, self._flushed = (
            self.sched.finished[self._flushed:],
            len(self.sched.finished),
        )
        for idx, req in enumerate(new):
            if req.rid in self._reported:
                continue
            if req.rid <= PROBE_RID_BASE:
                # rollout parity probes report over the rollout channel
                # (_flush_probes), never to the front door
                continue
            self._reported.add(req.rid)
            try:
                self.transport.send(
                    self.results_to, "result",
                    json.dumps({
                        "rid": req.rid,
                        "tokens": [int(t) for t in req.tokens],
                        "host": self.name,
                    }).encode("utf-8"),
                    src=self.name,
                )
            except WireError:
                # the front door is unreachable: rewind so this result
                # and everything after it retry next tick — a finished
                # stream is never silently unreported
                self._reported.discard(req.rid)
                self._flushed -= len(new) - idx
                break

    # -- status feedback ------------------------------------------------

    def status(self) -> dict:
        s = {
            "host": self.name,
            "role": self.role,
            "free_slots": self.engine.serving.slots
            - len(self.sched._slot_req),
            "kv_blocks_free": self.engine.allocator.free_blocks,
            "queue_depth": len(self.sched._queue) + len(self._pending)
            + len(self._awaiting),
            "live": len(self.sched._slot_req),
            # weight version feedback: the rollout controller (and the
            # router's skew view) read fleet versions off statuses
            "version": self.engine.params_version,
        }
        if self.engine.staged_version is not None:
            s["staged_version"] = self.engine.staged_version
        cache = self.engine.allocator.cache
        if cache is not None:
            # hexing thousands of digests every tick is the hot-path
            # cost here — re-derive only when the index changed
            if self._digest_hex[0] != cache.version:
                self._digest_hex = (cache.version, [
                    d.hex() for d in cache.digests(MAX_PUBLISHED_DIGESTS)
                ])
            s["cached_digests"] = self._digest_hex[1]
        return s

    def publish_status(self) -> None:
        s = self.status()
        if s != self._last_status:
            self._last_status = s
            self.transport.publish(self.name, s)

    # -- drain-to-peer --------------------------------------------------

    def drain(self, reason: str, *, grace_s: float = 0.0) -> dict:
        """Preemption drain, fleet edition: decoding sequences MIGRATE
        to a decode-capable peer (their streams resume mid-token, to
        full parity), prefilling and queued requests FORWARD to a
        prefill-capable peer as fresh requests (prefill re-runs from
        scratch, the PR 9 hand-back semantics), and only with no
        capable peer does a request fall back to the launcher
        hand-back. ``grace_s`` > 0 keeps reading the inbox for that
        long AFTER the tombstone publishes, re-forwarding stragglers —
        on a cross-process transport a peer that read our
        pre-tombstone status may have a migrate message (the ONLY copy
        of its sequence) already in flight; single-threaded in-process
        drills have no concurrent senders and keep the default 0. The
        caller exits EXIT_RESUMABLE (75)."""
        # absorb anything already delivered to our inbox: a migrate
        # message a peer sent before seeing the tombstone must re-enter
        # the fleet through the forwarding below, not rot unread
        self._recv()
        self._event(
            "drain", reason=reason,
            in_flight=len(self.sched._slot_req),
            queued=len(self.sched._queue) + len(self._pending),
        )
        migrated, forwarded, handed_back = [], [], []
        for slot in sorted(self.sched._slot_req):
            req = self.sched._slot_req[slot]
            if req.status == "decoding":
                dst = self._export_with_failover(slot, req)
                if dst is not None:
                    self._event(
                        "evict", rid=req.rid, slot=slot, state="migrated",
                        tokens_done=len(req.tokens), dst=dst,
                    )
                    migrated.append(
                        {"rid": req.rid, "dst": dst,
                         "tokens_done": len(req.tokens)}
                    )
                    continue
            from .router import encode_request

            self.engine.retire(slot)
            del self.sched._slot_req[slot]
            req.status = "evicted"
            dst = self._send_with_failover(
                PREFILL_CAPABLE, "request", encode_request(req)
            )
            state = "forwarded" if dst is not None else "in_flight"
            self._event(
                "evict", rid=req.rid, slot=slot, state=state,
                tokens_done=len(req.tokens), prefilled=req._prefilled,
            )
            if dst is not None:
                forwarded.append({"rid": req.rid, "dst": dst})
            else:
                handed_back.append(
                    {"rid": req.rid, "tokens_done": len(req.tokens)}
                )
        # pending (not-yet-installed) imports re-enter the fleet as
        # fresh requests: their KV was never scattered here, so the
        # hand-back semantics (re-prefill from scratch) are the honest
        # ones — the partial output was already delivered at export
        pending_reqs = [
            Request(
                rid=m.rid,
                prompt=np.asarray(m.prompt, np.int32),
                max_new_tokens=m.max_new_tokens,
                temperature=m.temperature,
                seed=m.seed,
                eos=None if m.eos is None else int(m.eos),
            )
            for m, _ in self._pending
        ]
        self._pending.clear()
        # requests held for an in-flight cache_ship forward like any
        # queued request — the warm blocks were an optimization, the
        # request itself must leave with the drain
        awaiting_reqs = [v[0] for v in self._awaiting.values()]
        self._awaiting.clear()
        for req in list(self.sched._queue) + pending_reqs + awaiting_reqs:
            from .router import encode_request

            dst = self._send_with_failover(
                PREFILL_CAPABLE, "request", encode_request(req)
            )
            if dst is not None:
                forwarded.append({"rid": req.rid, "dst": dst})
            else:
                handed_back.append({"rid": req.rid, "tokens_done": 0})
        self.sched._queue.clear()
        # the tombstone: a published role no placement accepts takes
        # this host out of every peer's candidate set (its static
        # topology entry stops mattering once it has published)
        self.transport.publish(
            self.name, {**self.status(), "role": "drained"},
        )
        if grace_s > 0:
            deadline = time.monotonic() + grace_s
            while True:
                for msg in self.transport.recv(self.name):
                    self._reroute_straggler(
                        msg, migrated, forwarded, handed_back,
                    )
                if time.monotonic() >= deadline:
                    break
                time.sleep(0.01)
        if self.sched.recorder is not None:
            self.sched.recorder.flush()
        return {
            "reason": reason,
            "migrated": migrated,
            "forwarded": forwarded,
            "handed_back": handed_back,
            "finished": [r.rid for r in self.sched.finished],
        }

    def _reroute_straggler(self, msg, migrated, forwarded,
                           handed_back) -> None:
        """Re-forward one inbox message that arrived mid-drain. The
        payloads are self-contained, so a straggler moves to a capable
        peer as the SAME raw bytes under a fresh envelope — a migrate
        keeps its mid-stream device state (deserialized only for
        accounting), a request keeps its stamp semantics."""
        if msg.kind == "migrate":
            mseq = migrate.deserialize(msg.payload)
            dst = self._send_with_failover(
                DECODE_CAPABLE, "migrate", msg.payload
            )
            if dst is not None:
                self._event(
                    "migrate_out", rid=mseq.rid, dst=dst, slot=-1,
                    blocks=mseq.n_blocks, bytes=len(msg.payload),
                    tokens_done=len(mseq.emitted), rerouted=True,
                )
                migrated.append(
                    {"rid": mseq.rid, "dst": dst,
                     "tokens_done": len(mseq.emitted)}
                )
            else:
                handed_back.append(
                    {"rid": mseq.rid,
                     "tokens_done": len(mseq.emitted)}
                )
        elif msg.kind == "request":
            req = decode_request(msg.payload)
            dst = self._send_with_failover(
                PREFILL_CAPABLE, "request", msg.payload
            )
            if dst is not None:
                forwarded.append({"rid": req.rid, "dst": dst})
            else:
                handed_back.append({"rid": req.rid, "tokens_done": 0})

    # -- detached serve loop (the OS-process / main.py path) ------------

    def serve_forever(self, *, idle_sleep: float = 0.002,
                      max_idle_s: float | None = None,
                      drain_grace_s: float = 0.5):
        """Tick until a shutdown message arrives and the host runs dry
        (or a preemption drains it, or ``max_idle_s`` of continuous
        idleness passes — the watchdog for a driver that died). The
        preemption check runs FIRST each round, the serve-loop
        discipline scheduler.serve follows. -> (exit code, drain
        accounting | None)."""
        idle_since = None
        while True:
            if self.preemption is not None and self.preemption.requested:
                acct = self.drain(
                    self.preemption.reason or "preempted",
                    grace_s=drain_grace_s,
                )
                return EXIT_RESUMABLE, acct
            emitted = self.tick()
            if self._marooned():
                # the wire tombstoned EVERY counterpart this split-role
                # host has: serving cannot proceed — drain loudly
                # (hand-back accounting, no capable peer left to take
                # the work) and exit resumable, never idle silently
                acct = self.drain(
                    "wire: no capable peer reachable",
                    grace_s=drain_grace_s,
                )
                return EXIT_RESUMABLE, acct
            if self.busy or emitted:
                idle_since = None
                continue
            if self._shutdown:
                self._flush_results()
                return 0, None
            now = time.monotonic()
            idle_since = idle_since if idle_since is not None else now
            if max_idle_s is not None and now - idle_since > max_idle_s:
                self.log(f"fleet host {self.name}: idle past "
                         f"{max_idle_s:g}s, exiting")
                return 0, None
            time.sleep(idle_sleep)


# ---------------------------------------------------------------------------
# conf-driven entry (main.py plumbing)
# ---------------------------------------------------------------------------


def lm_config_from_conf(model_cfg):
    """Engine geometry from the conf net's declared dims: the
    kEmbedding layer's vocab/width/window, the kAttention layers'
    head count and depth. The fleet serves the code-API LM at that
    geometry with seed-initialized weights (every rank inits the same
    params from the same seed, the mp drills' discipline); the conf's
    ``checkpoint`` field overlays trained weights on top —
    ``run_from_conf`` threads it through
    ``resilience.reshard.load_serving_params``, so a save from ANY
    training topology restores onto this serving host."""
    from ...models.transformer import TransformerConfig

    net = model_cfg.neuralnet
    if net is None:
        raise ValueError("fleet conf has no neuralnet block")
    emb = next(
        (l.embedding_param for l in net.layer
         if l.embedding_param is not None), None,
    )
    heads = [
        l.attention_param.num_heads for l in net.layer
        if l.attention_param is not None
    ]
    if emb is None or not heads:
        raise ValueError(
            "fleet conf needs a kEmbedding layer (vocab_size, "
            "embedding_dim, max_len) and at least one kAttention layer"
        )
    if not emb.max_len:
        raise ValueError(
            "fleet conf's kEmbedding must declare max_len (the serving "
            "window cannot come from a data layer that never runs here)"
        )
    d = emb.embedding_dim
    return TransformerConfig(
        vocab=emb.vocab_size, d_model=d, n_heads=heads[0],
        n_layers=len(heads), d_ff=4 * d, max_len=emb.max_len,
    )


def _build_transport(fleet, root: str, recorder, faults: str | None,
                     log=print):
    """The transport seam's factory: ``fleet { transport }`` picks the
    filesystem mailbox (deterministic CI drills; default) or the real
    socket wire (comm/wire.py — the production path). Socket fleets
    dial peers by their conf addresses (+ the wire block's
    frontdoor_address for the results endpoint) and may carry a
    ``-faults`` wire-fault plan; missing addresses reject here, before
    any host serves (netlint WIR001 flags them statically)."""
    if getattr(fleet, "transport", "mailbox") != "socket":
        from .transport import Mailbox

        return Mailbox(root)
    from ...comm.faults import WIRE_KINDS, WireFaults
    from ...comm.wire import SocketTransport
    from ...config.schema import WireConfig
    from ...resilience.faults import FaultPlan

    wire = fleet.wire if fleet.wire is not None else WireConfig()
    addresses = {p.name: p.address for p in fleet.peers if p.address}
    missing = [p.name for p in fleet.peers if not p.address]
    if not fleet.peers or missing:
        raise ValueError(
            "fleet transport: socket needs an address on every peers "
            f"entry; missing on {missing or '(no peers declared)'} "
            "(netlint WIR001 flags this statically)"
        )
    if wire.frontdoor_address:
        addresses[FRONTDOOR] = wire.frontdoor_address
    wf = None
    plan = FaultPlan.parse(faults)
    if any(s.kind in WIRE_KINDS for s in plan.specs):
        wf = WireFaults(plan)
        log(f"wire-fault plan armed: {plan}")
    return SocketTransport(
        addresses,
        connect_timeout_s=wire.connect_timeout_s,
        send_timeout_s=wire.send_timeout_s,
        max_retries=wire.max_retries,
        backoff_s=wire.backoff_s,
        backoff_cap_s=wire.backoff_cap_s,
        liveness_timeout_s=wire.liveness_timeout_s,
        recorder=recorder,
        faults=wf,
    )


def run_from_conf(model_cfg, cluster_cfg, *, procs_id: int = 0,
                  seed: int = 0, faults: str | None = None,
                  log=print) -> int:
    """The ``fleet {}`` dispatch target of ``singa_tpu.main``: build
    this rank's engine, take the role ``role_for_rank`` assigns, wire
    the transport the conf picks (mailbox or socket), and serve until
    shutdown / SIGTERM (exit 75 after a drain-to-peer). The launch
    line is the reference's (``-procsID k`` per host); no
    jax.distributed rendezvous is needed — fleet hosts share nothing
    but the transport. ``faults`` carries the ``-faults`` plan so
    wire-fault drills (wire_drop@K etc.) run through the same launch
    line as training fault drills."""
    import jax

    from ...models.transformer import init_lm
    from ...obs.recorder import FlightRecorder
    from ...resilience.preemption import PreemptionHandler

    fleet = model_cfg.fleet
    n_hosts = len(fleet.peers) or (
        cluster_cfg.nworkers if cluster_cfg is not None
        and cluster_cfg.nworkers else 1
    )
    # elastic sizing: the topology declares up to max_hosts ranks, only
    # [0, min_hosts) must be live at launch — the rest are latent until
    # they join by publishing status (a later `-procsID k` launch).
    # Explicit peers entries ARE the topology (rank order, names and
    # roles): max_hosts cannot invent hosts beyond them — reject the
    # contradiction instead of silently serving a smaller fleet than
    # the conf appears to declare
    if fleet.peers:
        if fleet.max_hosts and fleet.max_hosts > len(fleet.peers):
            raise ValueError(
                f"fleet max_hosts {fleet.max_hosts} exceeds the "
                f"{len(fleet.peers)} declared peers entries — peers "
                "name the whole topology, max_hosts cannot invent "
                "hosts (netlint FLT001 flags this statically)"
            )
    elif fleet.max_hosts:
        # max_hosts is a CAP, not a hint: a cluster conf declaring
        # MORE workers than the fleet's maximum is a contradiction —
        # silently synthesizing nworkers hosts would let latent ranks
        # beyond the cap join and serve
        if n_hosts > fleet.max_hosts:
            raise ValueError(
                f"cluster declares {n_hosts} workers but fleet "
                f"max_hosts is {fleet.max_hosts} — the fleet cannot "
                "exceed its declared maximum; raise max_hosts or "
                "lower nworkers"
            )
        n_hosts = fleet.max_hosts
    min_hosts = fleet.min_hosts or n_hosts
    if not 0 < min_hosts <= n_hosts:
        raise ValueError(
            f"fleet min_hosts {fleet.min_hosts} / max_hosts "
            f"{fleet.max_hosts} do not describe a fleet: need "
            f"0 < min_hosts <= {n_hosts} (netlint FLT001 flags this "
            "statically)"
        )
    topo = fleet_topology(fleet, n_hosts)
    if not 0 <= procs_id < len(topo):
        raise ValueError(
            f"-procsID {procs_id} out of range for a {len(topo)}-host "
            "fleet"
        )
    latent = {n for k, (n, _) in enumerate(topo) if k >= min_hosts}
    name, role = topo[procs_id]
    workspace = (
        cluster_cfg.workspace if cluster_cfg is not None else "."
    )
    root = fleet.mailbox or f"{workspace}/fleet"
    cfg = lm_config_from_conf(model_cfg)
    params = init_lm(jax.random.PRNGKey(seed), cfg)
    restored = None
    if model_cfg.checkpoint:
        from ...resilience.reshard import load_serving_params

        params, restored = load_serving_params(
            model_cfg.checkpoint, params, log=log,
        )
        log(f"fleet host rank {procs_id}: restored "
            f"{restored['restored']} params from {restored['path']!r} "
            f"(step {restored['step']}, {restored['format']}, "
            f"resharded {restored['resharded']})")
    serving = EngineConfig.from_conf(
        model_cfg.serving, getattr(model_cfg, "kernels", None)
    )
    engine = Engine(params, cfg, serving)
    recorder = FlightRecorder(
        f"{workspace}/events", rank=procs_id, run_id="fleet",
    )
    if restored is not None:
        recorder.event(
            "weights_restored", step=restored["step"],
            path=restored["path"], format=restored["format"],
            restored=restored["restored"],
            resharded=restored["resharded"],
            saved_nprocs=restored["saved_nprocs"] or 0,
        )
    handler = PreemptionHandler()
    handler.install()
    transport = _build_transport(fleet, root, recorder, faults, log=log)
    # rollout faults (torn_weights@K / swap_die@K) fire at the host's
    # weight-ship seam — parsed separately from the wire plan (the
    # transport's WireFaults instance only consumes wire_* kinds)
    host_plan = None
    if faults:
        from ...resilience.faults import FaultPlan

        parsed = FaultPlan.parse(faults)
        if any(s.kind in ("torn_weights", "swap_die")
               for s in parsed.specs):
            parsed.recorder = recorder
            host_plan = parsed
            log(f"rollout-fault plan armed: {parsed}")
    log(f"fleet host {name!r} (rank {procs_id}): role {role}, "
        f"transport {getattr(fleet, 'transport', 'mailbox')} ({root})")
    host = FleetHost(
        name, role, engine, transport,
        peers={n: r for n, r in topo if n != name},
        latent=latent - {name},
        recorder=recorder, preemption=handler,
        results_to=FRONTDOOR, fault_plan=host_plan, log=log,
    )
    rc, acct = host.serve_forever()
    if acct is not None:
        log("FLEET DRAIN: " + json.dumps(acct))
    close = getattr(transport, "close", None)
    if close is not None:
        close()
    recorder.event("run_stop", step=host.sched.ticks, exit_code=rc)
    recorder.close()
    return rc
