"""Front-door request router: least-loaded placement with prefix
affinity over per-host occupancy feedback.

The reference fronted its cluster with a Router tier that bound every
worker/server socket and forwarded by identity
(include/utils/router.h:16-57). The serving analog routes GENERATION
REQUESTS: each host publishes an occupancy snapshot (free slots, free
blocks, queue depth — ``scheduler.occupancy()`` reports all three —
plus, with the prefix cache on, a capped list of its cached block
digests), and the router places each incoming prompt by:

  1. PREFIX AFFINITY — hash the prompt's full-block chain (the PR 11
     chained-digest identity) and find the prefill-capable host whose
     published digest set covers the LONGEST prefix of it: routing a
     templated prompt to the host already holding its blocks turns
     cross-host cache reuse from an accident into a policy. Ties (and
     zero affinity) fall through to
  2. LEAST-LOADED — shallowest queue, then most free slots, then most
     free blocks, then name order (total and deterministic: the same
     snapshot state always routes the same way, so fleet drills
     replay).

Feedback is latest-wins and eventually consistent: a stale snapshot
can only cost placement quality, never correctness — a host that
cannot actually admit applies its own backpressure and the request
waits in ITS queue, exactly as on a single host.

Every placement emits a ``route`` lifecycle event (rid, host, policy,
affinity blocks) on the router's flight recorder, so
``tools/trace.py`` reconstructs route -> prefill -> migrate ->
decode-resume per request from the cross-rank merge.
"""

from __future__ import annotations

import json

import numpy as np

from ..kv_pool import PrefixCache

#: roles that accept routed prompts (run admission + prefill)
PREFILL_CAPABLE = ("prefill", "unified")
#: roles that accept migrated sequences (run the decode tick)
DECODE_CAPABLE = ("decode", "unified")
#: every serving role — the set whose statuses carry a weight version
#: (Router.versions; drained tombstones fall outside it)
ROLES_WITH_VERSION = ("prefill", "decode", "unified")

#: cap on published cached-digest lists (a snapshot is feedback, not a
#: replica of the index; 4096 16-byte digests ~ 64 KiB of hex)
MAX_PUBLISHED_DIGESTS = 4096


def load_score(status: dict) -> tuple:
    """Sort key for least-loaded placement (smaller = preferred)."""
    return (
        int(status.get("queue_depth", 0)),
        -int(status.get("free_slots", 0)),
        -int(status.get("kv_blocks_free", 0)),
        str(status.get("host", "")),
    )


def chain_coverage(chain_hex: list[str], status: dict) -> int:
    """How many leading digests of a prompt's hex chain a host's
    published snapshot covers. The router's affinity policy ranks
    prefill-capable hosts by it; a fleet host ranks ALL peers by it to
    pick a ``cache_fetch`` target (any role may hold warm history —
    decode hosts register migrated and decode-written blocks too)."""
    cached = set(status.get("cached_digests") or ())
    n = 0
    for d in chain_hex:
        if d not in cached:
            break
        n += 1
    return n


class Router:
    """Placement policy over published host statuses. The router holds
    NO host references — it reads snapshots from the transport's
    status side channel and delivers requests as one-shot ``request``
    messages, so the same object fronts an in-process drill or a
    mailbox fleet of OS processes."""

    def __init__(self, transport, *, name: str = "router",
                 block_len: int = 0, recorder=None):
        self.transport = transport
        self.name = name
        #: block geometry for affinity hashing (0 = affinity off)
        self._chain = (
            PrefixCache(block_len).chain if block_len > 0 else None
        )
        self.recorder = recorder
        self.routed = 0
        self.affinity_hits = 0

    # -- feedback -------------------------------------------------------

    def _snapshots(self, roles) -> list[dict]:
        return sorted(
            (
                s
                for s in self.transport.statuses().values()
                if s.get("role") in roles
            ),
            key=load_score,
        )

    def _affinity(self, prompt, snapshots) -> tuple[str | None, int]:
        """(host with the longest cached block-prefix of ``prompt``,
        matched block count); (None, 0) when nothing matches."""
        if self._chain is None:
            return None, 0
        chain = [d.hex() for d in self._chain(np.asarray(prompt))]
        if not chain:
            return None, 0
        best, best_n = None, 0
        for s in snapshots:  # already least-loaded-sorted: ties break
            n = chain_coverage(chain, s)
            if n > best_n:
                best, best_n = s.get("host"), n
        return best, best_n

    # -- placement ------------------------------------------------------

    def route(self, prompt, rid: int | None = None) -> str:
        """Pick the host for one prompt (raises LookupError when no
        prefill-capable host has published status yet — the fleet is
        still booting; callers retry)."""
        snaps = self._snapshots(PREFILL_CAPABLE)
        if not snaps:
            raise LookupError(
                "no prefill-capable host has published status"
            )
        host, blocks = self._affinity(prompt, snaps)
        policy = "affinity"
        if host is None:
            host, policy = snaps[0].get("host"), "least_loaded"
        else:
            self.affinity_hits += 1
        self.routed += 1
        if self.recorder is not None:
            self.recorder.event(
                "route", step=self.routed, rid=rid, host=host,
                policy=policy, affinity_blocks=int(blocks),
            )
        return host

    def submit(self, req) -> str:
        """Route one scheduler Request and deliver it as a ``request``
        message to the chosen host. -> the host name."""
        host = self.route(req.prompt, rid=req.rid)
        self.transport.send(
            host, "request", encode_request(req), src=self.name
        )
        return host

    def versions(self) -> dict[str, int]:
        """Per-host weight version off published statuses (the live
        rollout's skew view: during a canary or a paused promotion the
        fleet is legitimately mixed-version, and the migration /
        cache-ship paths degrade any cross-version frame to cold
        prefill rather than splice two models into one stream). Hosts
        that predate the rollout channel publish no version and read
        as 0 — the pre-rollout contract."""
        return {
            s["host"]: int(s.get("version", 0))
            for s in self.transport.statuses().values()
            if s.get("host") and s.get("role") in ROLES_WITH_VERSION
        }


# ---------------------------------------------------------------------------
# request wire codec (the router -> host and drain-forward message body)
# ---------------------------------------------------------------------------


def encode_request(req) -> bytes:
    import os
    import time

    return json.dumps({
        "rid": int(req.rid),
        "prompt": [int(t) for t in np.asarray(req.prompt)],
        "max_new_tokens": int(req.max_new_tokens),
        "temperature": float(req.temperature),
        "seed": int(req.seed),
        "eos": req.eos,
        # submit-time stamp so queue-inclusive latency covers the
        # routing hop. perf_counter origins are per-process, so the
        # stamp is tagged with its clock domain: a same-process
        # receiver (in-process drills, bench) keeps it, a cross-
        # process receiver re-stamps at arrival instead of mixing
        # clock origins into garbage latencies
        "enqueue_mono": float(req.enqueue_mono) or time.perf_counter(),
        "clock": os.getpid(),
    }).encode("utf-8")


def decode_request(payload: bytes):
    import os

    from ..scheduler import Request

    d = json.loads(payload.decode("utf-8"))
    req = Request(
        rid=int(d["rid"]),
        prompt=np.asarray(d["prompt"], np.int32),
        max_new_tokens=int(d["max_new_tokens"]),
        temperature=float(d.get("temperature", 0.0)),
        seed=int(d.get("seed", 0)),
        eos=d.get("eos"),
    )
    req.enqueue_mono = (
        float(d.get("enqueue_mono", 0.0))
        if d.get("clock") == os.getpid() else 0.0
    )
    return req
