"""Paged-KV block migration: one sequence's serving state as ONE bulk
message between hosts.

The reference moved parameter state through a request/response Router
tier (include/utils/router.h:16-57) — fine for kGet/kPut of one param
blob, hopeless for shipping a sequence's whole paged KV (hundreds of
blocks x layers of per-request chatter). "RPC Considered Harmful"
(arxiv 1805.08430) names the fix this module implements: bulk tensor
state moves as a ONE-SHOT device-to-wire transfer — gather the
sequence's blocks from the pool through its block table (one compiled
gather per export, engine._export_prog), serialize
``(blocks, block_table, pos, emitted tokens, rng lane, digest chain)``
as a single message, scatter into the peer pool's freshly allocated
blocks (one compiled scatter per import, engine._import_prog). No
per-block round trips, no wire format per layer.

The correctness bar is BITWISE: an imported sequence's subsequent
token stream is bit-for-bit the stream the exporting host would have
produced. That rides the PR 9 pinning chain — paged == dense is
bitwise, the gathered view reassembles exactly the dense layout, and a
slot's decode depends only on its own lanes and table — so copying
pool bytes + (token, pos, temp, rng) lanes exactly IS copying the
stream's future. The RNG lane ships bit-for-bit, so temperature
streams keep sampling through the exporter's exact key schedule.

Prefix-cache-registered blocks re-register on the importer via their
CHAINED digests (shipped, not re-hashed): a matched digest means the
importer already holds those bytes bit-for-bit (both sides
prefill-written under the same left context, the PR 11 invariant), so
import shares the matched blocks instead of re-writing them, and
newly imported full-prompt blocks join the importer's index — cross-
host cache reuse for the price of a list of digests on the wire.

The FLEET PREFIX CACHE rides the same one-shot discipline without a
sequence attached: a host whose admission misses locally but whose
peers' published digest chains cover the prompt sends ``cache_fetch``
(the prompt's digest chain, JSON) and receives ONE ``cache_ship``
bulk frame — the matched blocks' per-layer K/V bytes plus their
digests — which it scatters into fresh blocks and registers
(engine.install_prefix). Warm KV now moves over ANY transport
(in-process, mailbox, TCP wire); no shared filesystem is assumed
anywhere, and a host that never hears back degrades to plain prefill.

Serialization is numpy's npz container (every array in one buffer)
plus a JSON metadata record — self-describing, versioned, no pickle.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import zlib

import numpy as np

#: wire-format tag; bump on any incompatible layout change
MIGRATE_FORMAT = "singa-tpu-migrate-v1"
#: fleet prefix-cache frames (cache_fetch request / cache_ship reply)
FETCH_FORMAT = "singa-tpu-cachefetch-v1"
SHIP_FORMAT = "singa-tpu-cacheship-v1"
#: live-rollout weight frames: one bulk message per staged version
WEIGHT_FORMAT = "singa-tpu-weights-v1"


@dataclasses.dataclass
class MigratedSequence:
    """One in-flight sequence on the wire: the request's identity and
    budget bookkeeping (scheduler side) plus the engine's exported
    device state (``payload``: trimmed per-layer K/V blocks, lanes,
    digest chain — serve/engine.py ``Engine.export_slot``)."""

    rid: int
    prompt: np.ndarray
    emitted: list
    max_new_tokens: int
    temperature: float
    seed: int
    eos: int | None
    payload: dict
    #: submit-time monotonic stamp, carried so queue-inclusive latency
    #: survives migration (meaningful within one process/clock domain;
    #: cross-host reports fall back to import-time re-stamping)
    enqueue_mono: float = 0.0
    #: params version the exporter's K/V bytes were written under: a
    #: receiver whose live version differs must NOT scatter them
    #: (mixed-version KV poisons a pool) — it degrades the sequence to
    #: cold prefill under its own weights instead (serve/fleet/host.py)
    version: int = 0

    @property
    def n_blocks(self) -> int:
        return int(self.payload["k"].shape[1])


def export_sequence(engine, req, slot: int) -> MigratedSequence:
    """Gather ``slot``'s full serving state for request ``req`` into a
    wire-ready MigratedSequence. The slot is left serving; the caller
    retires it once the message is handed to the transport (after
    which the exporter's registered prefix blocks park on its LRU —
    the SAME prompt keeps serving prefix hits on BOTH hosts)."""
    return MigratedSequence(
        rid=req.rid,
        prompt=np.asarray(req.prompt, np.int32),
        emitted=list(req.tokens),
        max_new_tokens=int(req.max_new_tokens),
        temperature=float(req.temperature),
        seed=int(req.seed),
        eos=req.eos,
        payload=engine.export_slot(slot),
        enqueue_mono=float(req.enqueue_mono),
        version=int(getattr(engine, "params_version", 0)),
    )


def import_sequence(engine, slot: int, mseq: MigratedSequence) -> dict:
    """Install ``mseq`` into dead ``slot`` of ``engine`` (raises
    PoolExhausted untouched — fleet import backpressure, the caller
    retries next tick). -> the engine's import info
    ({"blocks", "shared", "registered"})."""
    return engine.import_slot(slot, mseq.payload)


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------


def serialize(mseq: MigratedSequence) -> bytes:
    """MigratedSequence -> one self-describing bytes message (npz
    container: arrays + a JSON metadata entry)."""
    p = mseq.payload
    meta = {
        "format": MIGRATE_FORMAT,
        "rid": mseq.rid,
        "emitted": [int(t) for t in mseq.emitted],
        "max_new_tokens": mseq.max_new_tokens,
        "temperature": mseq.temperature,
        "seed": mseq.seed,
        "eos": mseq.eos,
        # per-process perf_counter origin: a cross-process importer
        # re-stamps at arrival instead of trusting a foreign clock
        "enqueue_mono": mseq.enqueue_mono,
        "clock": os.getpid(),
        "version": int(mseq.version),
        "token": int(p["token"]),
        "pos": int(p["pos"]),
        "temp": float(p["temp"]),
        "chain": [d.hex() for d in p.get("chain") or ()],
    }
    buf = io.BytesIO()
    np.savez(
        buf,
        meta=np.frombuffer(
            json.dumps(meta).encode("utf-8"), dtype=np.uint8
        ),
        prompt=np.asarray(mseq.prompt, np.int32),
        k=np.asarray(p["k"]),
        v=np.asarray(p["v"]),
        rng=np.asarray(p["rng"], np.uint32),
    )
    return buf.getvalue()


def deserialize(data: bytes) -> MigratedSequence:
    """bytes -> MigratedSequence (raises ValueError on a foreign or
    future wire format — a fleet must not silently mis-scatter)."""
    with np.load(io.BytesIO(data)) as z:
        meta = json.loads(bytes(z["meta"]).decode("utf-8"))
        if meta.get("format") != MIGRATE_FORMAT:
            raise ValueError(
                f"migrate message format {meta.get('format')!r} != "
                f"{MIGRATE_FORMAT!r}"
            )
        payload = {
            "k": z["k"],
            "v": z["v"],
            "rng": z["rng"],
            "token": int(meta["token"]),
            "pos": int(meta["pos"]),
            "temp": float(meta["temp"]),
            "chain": [bytes.fromhex(h) for h in meta["chain"]],
        }
        return MigratedSequence(
            rid=int(meta["rid"]),
            prompt=z["prompt"],
            emitted=list(meta["emitted"]),
            max_new_tokens=int(meta["max_new_tokens"]),
            temperature=float(meta["temperature"]),
            seed=int(meta["seed"]),
            eos=meta["eos"],
            payload=payload,
            enqueue_mono=(
                float(meta.get("enqueue_mono", 0.0))
                if meta.get("clock") == os.getpid() else 0.0
            ),
            # pre-rollout senders carry no tag: version 0 by contract
            version=int(meta.get("version", 0)),
        )


# ---------------------------------------------------------------------------
# fleet prefix-cache frames
# ---------------------------------------------------------------------------


def serialize_fetch(rid: int, chain: list[bytes],
                    version: int = 0) -> bytes:
    """A ``cache_fetch``: the requesting host's prompt digest chain
    (prefix-ordered) plus its live params ``version`` — a peer at a
    DIFFERENT version answers with an empty ship (its warm bytes were
    written under other weights). The peer matches its longest cached
    prefix and replies with ONE ``cache_ship``; digests are tiny, so
    this frame is JSON."""
    return json.dumps(
        {"format": FETCH_FORMAT, "rid": int(rid),
         "chain": [d.hex() for d in chain], "version": int(version)}
    ).encode("utf-8")


def deserialize_fetch(data: bytes) -> tuple[int, list[bytes], int]:
    """bytes -> (rid, digest chain, requester's params version); raises
    ValueError on a foreign format."""
    meta = json.loads(data.decode("utf-8"))
    if meta.get("format") != FETCH_FORMAT:
        raise ValueError(
            f"cache_fetch format {meta.get('format')!r} != "
            f"{FETCH_FORMAT!r}"
        )
    return (
        int(meta["rid"]),
        [bytes.fromhex(h) for h in meta["chain"]],
        int(meta.get("version", 0)),
    )


def serialize_ship(rid: int, chain: list[bytes], k, v,
                   version: int = 0) -> bytes:
    """A ``cache_ship``: the matched prefix's digests plus its blocks'
    per-layer K/V bytes — ``k``/``v`` shaped (L, n, H, BL, D) from
    ``engine.export_blocks`` — as one bulk npz frame. ``n`` may be 0
    (the peer's advertisement was stale): an empty ship tells the
    requester to degrade to plain prefill immediately instead of
    waiting out its deadline."""
    meta = {
        "format": SHIP_FORMAT,
        "rid": int(rid),
        "chain": [d.hex() for d in chain],
        "version": int(version),
    }
    buf = io.BytesIO()
    np.savez(
        buf,
        meta=np.frombuffer(
            json.dumps(meta).encode("utf-8"), dtype=np.uint8
        ),
        k=np.asarray(k),
        v=np.asarray(v),
    )
    return buf.getvalue()


def deserialize_ship(data: bytes) -> dict:
    """bytes -> {"rid", "chain", "k", "v"}; raises ValueError on a
    foreign format (a fleet must not silently mis-scatter)."""
    with np.load(io.BytesIO(data)) as z:
        meta = json.loads(bytes(z["meta"]).decode("utf-8"))
        if meta.get("format") != SHIP_FORMAT:
            raise ValueError(
                f"cache_ship format {meta.get('format')!r} != "
                f"{SHIP_FORMAT!r}"
            )
        return {
            "rid": int(meta["rid"]),
            "chain": [bytes.fromhex(h) for h in meta["chain"]],
            "k": z["k"],
            "v": z["v"],
            "version": int(meta.get("version", 0)),
        }


# ---------------------------------------------------------------------------
# live-rollout weight frames
# ---------------------------------------------------------------------------


def serialize_weights(version: int, params: dict) -> bytes:
    """A ``weight_ship``: one next-version param tree as ONE bulk npz
    frame — sorted flat names, every array, and an APPLICATION-level
    CRC32 over the packed bytes. The transport's own frame CRC guards
    the wire; this one guards the whole staged artifact end to end, so
    a torn or bit-flipped ship is REJECTED at deserialize (the
    ``torn_weights`` verdict) and can never be staged into an engine."""
    names = sorted(params)
    arrays = [np.ascontiguousarray(np.asarray(params[n])) for n in names]
    crc = 0
    for a in arrays:
        crc = zlib.crc32(a.tobytes(), crc)
    meta = {
        "format": WEIGHT_FORMAT,
        "version": int(version),
        "names": names,
        "crc32": crc & 0xFFFFFFFF,
    }
    buf = io.BytesIO()
    np.savez(
        buf,
        meta=np.frombuffer(
            json.dumps(meta).encode("utf-8"), dtype=np.uint8
        ),
        **{f"w{i:04d}": a for i, a in enumerate(arrays)},
    )
    return buf.getvalue()


def deserialize_weights(data: bytes) -> tuple[int, dict]:
    """bytes -> (version, {name: array}); raises ValueError on a
    foreign format OR a CRC mismatch — a torn weight ship must die
    here, loudly, never half-staged."""
    with np.load(io.BytesIO(data)) as z:
        meta = json.loads(bytes(z["meta"]).decode("utf-8"))
        if meta.get("format") != WEIGHT_FORMAT:
            raise ValueError(
                f"weight_ship format {meta.get('format')!r} != "
                f"{WEIGHT_FORMAT!r}"
            )
        names = list(meta["names"])
        arrays = [np.ascontiguousarray(z[f"w{i:04d}"])
                  for i in range(len(names))]
    crc = 0
    for a in arrays:
        crc = zlib.crc32(a.tobytes(), crc)
    if (crc & 0xFFFFFFFF) != int(meta["crc32"]):
        raise ValueError(
            f"torn weight_ship v{meta.get('version')}: CRC mismatch "
            f"({crc & 0xFFFFFFFF:#010x} != {int(meta['crc32']):#010x})"
        )
    return int(meta["version"]), dict(zip(names, arrays))
