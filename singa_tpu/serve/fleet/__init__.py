"""Disaggregated serving fleet: router -> prefill hosts -> decode hosts.

One engine is a slot-count ceiling; a fleet is not. This package
composes the serving tier (serve/engine.py + serve/scheduler.py) into
a multi-host fleet with split roles — the serving-scale analog of the
reference's Worker/Server split (src/main.cc:49-55 picks a role by
rank) fronted by its Router tier (include/utils/router.h:16-57):

  ``migrate``    paged-KV block migration: a sequence's whole serving
                 state (K/V blocks through its block table, lanes,
                 digest chain) moves between hosts as ONE bulk
                 message — gather, wire, scatter; no RPC chatter
                 (arxiv 1805.08430). An imported sequence's token
                 stream is BITWISE the exporter's continuation.
  ``host``       the role split: prefill hosts run admission + chunked
                 prefill only and hand filled sequences to decode
                 hosts over the migration path; a SIGTERM'd host's
                 drain routes in-flight sequences to a PEER (decode
                 streams resume mid-token to full parity) instead of
                 only handing them back to the launcher.
  ``router``     the front door: least-loaded placement with
                 prefix-affinity over per-host occupancy feedback
                 (free slots / free blocks / queue depth, plus cached
                 block digests — a templated prompt routes to the
                 host already holding its prefix blocks).
  ``transport``  one-shot messages + latest-wins status, in-process
                 (deterministic drills) or filesystem mailboxes
                 (cross-OS-process, atomic tmp+rename — the commit
                 markers' discipline at message grain). The PRODUCTION
                 wiring of the same seam is ``comm.wire``'s TCP
                 ``SocketTransport`` (``fleet { transport: socket }``):
                 CRC'd frames, bounded-backoff retries, at-least-once
                 redelivery the importer dedupes, and peer-death
                 tombstones when a wire stays dead.

``tools/serve_bench.py --fleet`` is the load harness and CI gate;
``python -m singa_tpu.main`` with a ``fleet {}`` conf block launches
one host per ``-procsID``, the reference's launch line unchanged.
"""

from .host import (  # noqa: F401
    FleetHost,
    fleet_topology,
    role_for_rank,
    run_from_conf,
)
from .migrate import (  # noqa: F401
    MigratedSequence,
    deserialize,
    export_sequence,
    import_sequence,
    serialize,
)
from .router import Router  # noqa: F401
from .transport import LocalTransport, Mailbox  # noqa: F401
