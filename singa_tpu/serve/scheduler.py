"""Continuous batching: admit/retire request streams at each decode tick.

The control plane of the serving tier. The reference's Server answered
every worker's kGet/kPut from one process (src/server/server.cc); this
scheduler answers every client's generation request from one engine:

  - a FIFO request queue; at each tick, queued prompts are admitted
    into free slots while the block pool can cover their whole
    ``prompt + budget`` (all-or-nothing, so a live stream can never
    strand mid-generation on an exhausted pool) — an allocation that
    does not fit applies ADMISSION backpressure: the request waits for
    a retirement, it is never dropped;
  - admitted prompts prefill in fixed chunks, one chunk per request per
    tick, so a long prompt shares the host loop with live decode
    instead of stalling it; with the prefix cache on
    (``serving { prefix_cache { enabled } }``) admission first points
    the new sequence's block table at the pool's longest cached
    block-prefix of its prompt, so the chunk loop starts at the first
    UNCOVERED token — prefill work drops to the uncached tail, and the
    fully-prefilled prompt is registered for future hits once its last
    chunk lands;
  - every live slot advances one token per tick through the engine's
    single fixed-shape decode program; EOS or an exhausted budget
    retires the slot (blocks freed, available to the next admit — the
    continuous part of continuous batching);
  - with speculation on (``serving { speculate { k } }``), each tick
    instead drafts up to k tokens per live greedy slot (model-free
    n-gram lookup over the request's own prompt+output,
    serve/speculate.py), runs the engine's fixed-shape VERIFY program
    once, and fans every accepted token out to its request — EOS or
    budget hit INSIDE an accepted run retires at exactly the token
    sequential decode would have stopped at (the tail of the run is
    discarded, never delivered). Temperature slots ride the same tick
    with zero drafts. Token streams are identical to one-token ticks
    by construction; only tick count changes;
  - a SIGTERM'd serving host drains via the resilience plane: the
    serve loop observes ``PreemptionHandler.requested`` at a tick
    boundary, hands every in-flight sequence back (recorded, with its
    partial output), and the host exits EXIT_RESUMABLE (75) — the same
    discipline as a training drain.

Lifecycle events (``request_admit`` / ``prefill`` / ``decode_tick`` /
``retire`` / ``evict`` / ``backpressure`` / ``drain``) and per-request
spans flow into the PR 6 flight recorder, so
``tools/trace.py --summarize`` reports serving p50/p99 and tokens/sec
with no serving-specific plumbing.
"""

from __future__ import annotations

import collections
import dataclasses
import time

import numpy as np

from .engine import Engine
from .kv_pool import PoolExhausted
from .speculate import make_drafter


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle bookkeeping."""

    rid: int
    prompt: np.ndarray            # (P,) int32
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    eos: int | None = None

    # runtime (owned by the scheduler)
    status: str = "queued"        # queued|prefill|decoding|done|evicted
    slot: int | None = None
    tokens: list = dataclasses.field(default_factory=list)
    enqueue_mono: float = 0.0
    admit_wall: float = 0.0
    admit_mono: float = 0.0
    first_token_mono: float = 0.0
    finish_mono: float = 0.0
    _prefilled: int = 0

    @property
    def latency_s(self) -> float:
        """Admit -> finish wall seconds (0 until finished)."""
        return max(0.0, self.finish_mono - self.admit_mono)


class Scheduler:
    """Continuous-batching loop over one Engine."""

    def __init__(self, engine: Engine, *, recorder=None, preemption=None,
                 log=lambda s: None, drafter=None):
        self.engine = engine
        self.recorder = recorder
        self.preemption = preemption
        self.log = log
        #: speculative decode: k > 0 routes every decode tick through
        #: the engine's verify program; the drafter proposes (override
        #: for tests/probes — e.g. speculate.NullDrafter forces zero
        #: acceptance while keeping the whole verify path hot)
        self.spec_k = engine.serving.spec_k
        if drafter is not None:
            self.drafter = drafter
        else:
            self.drafter = (
                make_drafter(engine.serving.spec_drafter)
                if self.spec_k > 0 else None
            )
        self.spec_drafted = 0
        self.spec_accepted = 0
        #: role gate for the fleet's prefill/decode split
        #: (serve/fleet/host.py): False = ticks run admission + chunked
        #: prefill only and decoding-status requests wait for the fleet
        #: host to migrate them to a decode peer. True (default) = the
        #: unified single-host behavior.
        self.decode_enabled = True
        #: prefix-cache accounting (all zero with the cache off)
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.blocks_shared = 0
        self.cow_copies = 0
        #: hits whose shared prefix ended MID-block (a registered
        #: partial tail was COW-extended) and the tail tokens they saved
        self.partial_hits = 0
        self.tail_tokens_shared = 0
        #: full decode-written blocks indexed at retirement
        #: (``prefix_cache { decode_blocks }``)
        self.decode_blocks_registered = 0
        self.prefill_chunks = 0
        self.prefill_chunks_saved = 0
        # allocator lifecycle (lru_evict/lru_reclaim) rides the same
        # event path as the scheduler's own admissions
        engine.allocator.on_event = self._event
        self._queue: collections.deque[Request] = collections.deque()
        self._slot_req: dict[int, Request] = {}
        self.ticks = 0
        #: ticks that ran a decode/verify program (>= 1 slot decoding)
        self.decode_ticks = 0
        self.tokens_emitted = 0
        self.backpressure_ticks = 0
        #: sum over ticks of live (decoding) slots — occupancy reporting
        self._live_ticks = 0
        #: wall seconds / tokens over FULL-occupancy decode ticks only:
        #: the steady-state capacity number (admission work is a
        #: per-request constant; a long-running server lives here)
        self.full_tick_s = 0.0
        self.full_tick_tokens = 0
        self.finished: list[Request] = []
        # run-start provenance: which implementation the attend seam
        # runs (kernels { paged_attention }), so an incident report can
        # say which path this run took (trace.py --summarize
        # serving.attend_impl)
        self._event(
            "kernel_select", site="serve.paged_attention",
            impl=engine.serving.attend_impl,
        )

    def reset_counters(self) -> None:
        """Zero every accumulated statistic (ticks, token/draft counts,
        occupancy, backpressure, finished list) — the benchmark
        harnesses call this after a compile-warm request so warmup
        never contaminates measured numbers. Live/queued requests are
        untouched."""
        self.finished.clear()
        self.ticks = self.decode_ticks = 0
        self.tokens_emitted = 0
        self.spec_drafted = self.spec_accepted = 0
        self.prefix_lookups = self.prefix_hits = 0
        self.blocks_shared = self.cow_copies = 0
        self.partial_hits = self.tail_tokens_shared = 0
        self.decode_blocks_registered = 0
        self.prefill_chunks = self.prefill_chunks_saved = 0
        self.engine.allocator.reset_stats()
        self._live_ticks = 0
        self.backpressure_ticks = 0
        self.full_tick_s, self.full_tick_tokens = 0.0, 0

    # -- client side ----------------------------------------------------

    def submit(self, req: Request) -> None:
        # any temperature is admissible: the engine's per-slot
        # temperature lane means one compiled program serves every mix
        # of sampling configs (the old same-temperature rejection is
        # gone with it)
        total = len(req.prompt) + req.max_new_tokens
        if total > self.engine.cfg.max_len:
            raise ValueError(
                f"request {req.rid}: prompt {len(req.prompt)} + budget "
                f"{req.max_new_tokens} exceeds max_len "
                f"{self.engine.cfg.max_len}"
            )
        req.prompt = np.asarray(req.prompt, np.int32)
        # a request that crossed the fleet's front door (or a drain
        # forward) keeps its original stamp, so queue-inclusive latency
        # covers the routing hop too; fresh requests stamp here
        req.enqueue_mono = req.enqueue_mono or time.perf_counter()
        req.status = "queued"
        self._queue.append(req)

    @property
    def in_flight(self) -> list[Request]:
        return list(self._slot_req.values())

    @property
    def busy(self) -> bool:
        return bool(self._queue or self._slot_req)

    def _event(self, kind: str, **payload) -> None:
        if self.recorder is not None:
            self.recorder.event(kind, step=self.ticks, **payload)

    # -- the tick -------------------------------------------------------

    def _admit_some(self) -> None:
        free = [
            s for s in range(self.engine.serving.slots)
            if s not in self._slot_req
        ]
        stalled = False
        while self._queue and free:
            req = self._queue[0]
            try:
                adm = self.engine.admit(
                    free[0], len(req.prompt) + req.max_new_tokens,
                    prompt=req.prompt,
                )
            except PoolExhausted:
                stalled = True
                break
            self._queue.popleft()
            slot = free.pop(0)
            self._slot_req[slot] = req
            req.slot = slot
            req.status = "prefill"
            # prefill starts at the first token the prefix cache did
            # not cover (lane positions are seeded by pos0 each chunk,
            # so a hit just skips the covered chunks)
            req._prefilled = adm.prefill_from
            # a handed-back (drained) request restarts from scratch on
            # re-admission: its partial output was delivered at evict
            # time, regeneration must not append to it
            req.tokens = []
            req.admit_wall = time.time()
            req.admit_mono = time.perf_counter()
            self._event(
                "request_admit", rid=req.rid, slot=slot,
                prompt_len=int(len(req.prompt)), blocks=len(adm.blocks),
                queued_s=round(req.admit_mono - req.enqueue_mono, 6),
            )
            if self.engine.allocator.cache is not None:
                self.prefix_lookups += 1
            if adm.cached_tokens:
                c = self.engine.serving.max_prefill_chunk
                saved = (
                    -(-len(req.prompt) // c)
                    - -(-(len(req.prompt) - adm.prefill_from) // c)
                )
                self.prefix_hits += 1
                # blocks this sequence reads through another owner's
                # bytes (a COW'd tail block became private)
                shared = (
                    adm.cached_tokens // self.engine.pool.block_len
                    - (1 if adm.cow_copied else 0)
                )
                self.blocks_shared += shared
                self.prefill_chunks_saved += saved
                self._event(
                    "prefix_hit", rid=req.rid, slot=slot,
                    cached_tokens=int(adm.cached_tokens),
                    blocks_shared=int(shared), chunks_saved=int(saved),
                )
            if adm.tail_tokens:
                self.partial_hits += 1
                self.tail_tokens_shared += adm.tail_tokens
                self._event(
                    "partial_hit", rid=req.rid, slot=slot,
                    cached_tokens=int(adm.cached_tokens),
                    tail_tokens=int(adm.tail_tokens),
                )
            if adm.cow_copied:
                self.cow_copies += 1
                self._event("cow_copy", rid=req.rid, slot=slot)
        if stalled:
            self.backpressure_ticks += 1
            self._event(
                "backpressure",
                queued=len(self._queue),
                free_blocks=self.engine.allocator.free_blocks,
            )

    def _prefill_some(self) -> None:
        # one chunk per prefilling request per tick: decode never waits
        # behind more than slots * one chunk of prompt work
        for slot in sorted(self._slot_req):
            req = self._slot_req[slot]
            if req.status != "prefill":
                continue
            n = min(
                self.engine.serving.max_prefill_chunk,
                len(req.prompt) - req._prefilled,
            )
            last = self.engine.prefill_chunk(
                slot, req.prompt[req._prefilled:req._prefilled + n],
                req._prefilled,
            )
            req._prefilled += n
            self.prefill_chunks += 1
            self._event(
                "prefill", rid=req.rid, slot=slot, tokens=int(n),
                done=int(req._prefilled), of=int(len(req.prompt)),
            )
            if req._prefilled >= len(req.prompt):
                # every prompt position is now prefill-written: index
                # the fully-covered blocks for future prefix hits
                self.engine.register_prefix(slot, req.prompt)
                first = self.engine.activate(
                    slot, last, len(req.prompt), req.seed,
                    temperature=req.temperature,
                )
                req.tokens.append(first)
                req.status = "decoding"
                req.first_token_mono = time.perf_counter()
                self._check_done(slot, req, first)

    def _check_done(self, slot: int, req: Request, tok: int) -> bool:
        if (req.eos is not None and tok == req.eos) or (
            len(req.tokens) >= req.max_new_tokens
        ):
            self._finish(slot, req, "eos" if req.eos is not None
                         and tok == req.eos else "budget")
            return True
        return False

    def _finish(self, slot: int, req: Request, reason: str) -> None:
        if (
            self.engine.serving.prefix_decode_blocks
            and self.engine.allocator.cache is not None
            and req.tokens
        ):
            # multi-turn reuse: index the conversation's FULL blocks —
            # decode-written ones included — before the release below
            # parks them, so a follow-up prompt replaying this history
            # hits it (token-level parity: the PR 9 cross-shape caveat)
            n = self.engine.register_history(
                slot,
                np.concatenate(
                    [np.asarray(req.prompt, np.int32),
                     np.asarray(req.tokens, np.int32)]
                ),
            )
            if n:
                self.decode_blocks_registered += n
                self._event(
                    "decode_register", rid=req.rid, slot=slot,
                    blocks=int(n),
                )
        self.engine.retire(slot)
        del self._slot_req[slot]
        req.status = "done"
        req.finish_mono = time.perf_counter()
        self.finished.append(req)
        self._event(
            "retire", rid=req.rid, slot=slot, reason=reason,
            tokens=int(len(req.tokens)),
            latency_s=round(req.latency_s, 6),
        )
        if self.recorder is not None:
            self.recorder.record_span(
                "request", req.admit_wall, req.latency_s,
                track="requests", steps=len(req.tokens),
            )

    def _draft_for(self, req: Request) -> list[int]:
        """Draft tokens for one decoding request: greedy slots only
        (speculation is greedy-only per slot — a temperature slot's
        sampled continuation is not the drafter's to predict), clamped
        so the accepted run can never overshoot the budget (at most
        ``budget_remaining`` tokens emit per tick, the last being the
        bonus) nor write past the request's allocated blocks."""
        if req.temperature > 0.0:
            return []
        budget_rem = req.max_new_tokens - len(req.tokens)
        n = min(self.spec_k, budget_rem - 1)
        if n <= 0:
            return []
        ctx = list(req.prompt) + req.tokens
        return list(self.drafter.draft(ctx, n))[:n]

    def tick(self) -> int:
        """One scheduling round: retire happens inline as tokens land,
        admit fills freed slots, prefill advances one chunk each, then
        every live slot decodes — one token through the decode program,
        or up to spec_k + 1 through the verify program when speculation
        is on (skipped entirely on a prefill-role fleet host,
        ``decode_enabled`` False). -> tokens emitted."""
        self._admit_some()
        self._prefill_some()
        emitted_n = self._decode_some() if self.decode_enabled else 0
        self.ticks += 1
        return emitted_n

    def _decode_some(self) -> int:
        """The decode phase of one tick: every decoding-status slot
        advances through the decode (or speculative verify) program,
        accepted runs fan out to their requests, EOS/budget retires
        inline. Split out of ``tick`` so a fleet host can compose
        role-gated rounds (serve/fleet/host.py). -> tokens emitted."""
        decoding = {
            s: r for s, r in self._slot_req.items() if r.status == "decoding"
        }
        emitted_n = 0
        if decoding:
            accepted_n = 0
            t0w, t0 = time.time(), time.perf_counter()
            if self.spec_k > 0:
                slots = self.engine.serving.slots
                drafts = np.zeros((slots, self.spec_k), np.int32)
                nd = np.zeros((slots,), np.int32)
                for slot, req in decoding.items():
                    d = self._draft_for(req)
                    drafts[slot, :len(d)] = d
                    nd[slot] = len(d)
                drafted_n = int(nd.sum())
                self.spec_drafted += drafted_n
                self._event(
                    "spec_draft", drafted=drafted_n, live=len(decoding),
                )
                emitted_dev, accepted_dev = self.engine.verify(drafts, nd)
                emitted = np.asarray(emitted_dev)
                accepted_n = int(np.asarray(accepted_dev).sum())
                self.spec_accepted += accepted_n
            else:
                emitted = np.asarray(self.engine.decode())[:, None]
            dur = time.perf_counter() - t0
            for slot, req in sorted(decoding.items()):
                # fan the slot's accepted run out token by token: EOS
                # or budget INSIDE the run stops exactly where
                # sequential decode would have — the tail is discarded
                for tok in emitted[slot]:
                    if tok < 0:
                        break
                    req.tokens.append(int(tok))
                    emitted_n += 1
                    if self._check_done(slot, req, int(tok)):
                        break
            self._live_ticks += len(decoding)
            self.decode_ticks += 1
            self.tokens_emitted += emitted_n
            if len(decoding) == self.engine.serving.slots:
                self.full_tick_s += dur
                self.full_tick_tokens += emitted_n
            if self.recorder is not None:
                self.recorder.record_span(
                    "decode_tick", t0w, dur,
                    track="serving", steps=emitted_n,
                )
            if self.spec_k > 0:
                self._event(
                    "spec_accept", accepted=accepted_n, emitted=emitted_n,
                    drafted=drafted_n,
                )
            self._event(
                "decode_tick", live=len(decoding), emitted=emitted_n,
                blocks_used=self.engine.allocator.used_blocks,
            )
        return emitted_n

    # -- loops ----------------------------------------------------------

    def serve(self, max_ticks: int = 10 ** 9):
        """Tick until idle (or ``max_ticks``). Observes the resilience
        plane at every tick boundary: a requested preemption turns into
        a drain — the accounting dict return value; None means the
        queue ran dry normally. The check runs FIRST each round, so a
        signal arriving mid-tick drains at the next boundary —
        in-flight device work always completes, exactly the training
        loop's step-boundary discipline."""
        while self.busy and self.ticks < max_ticks:
            if self.preemption is not None and self.preemption.requested:
                return self.drain(self.preemption.reason or "preempted")
            self.tick()
        return None

    def drain(self, reason: str) -> dict:
        """Preemption drain: hand every in-flight sequence back (partial
        output recorded, blocks freed, request re-queued at the front so
        a relaunch finishes it first) and report the accounting the
        launcher needs. The caller exits EXIT_RESUMABLE (75)."""
        self._event(
            "drain", reason=reason,
            in_flight=len(self._slot_req), queued=len(self._queue),
        )
        handed_back = []
        for slot in sorted(self._slot_req):
            req = self._slot_req[slot]
            self.engine.retire(slot)
            req.status = "evicted"
            self._event(
                "evict", rid=req.rid, slot=slot, state="in_flight",
                tokens_done=int(len(req.tokens)),
                prefilled=int(req._prefilled),
            )
            handed_back.append(req)
        for req in reversed(handed_back):
            self._queue.appendleft(req)
        self._slot_req.clear()
        if self.recorder is not None:
            self.recorder.flush()
        return {
            "reason": reason,
            "handed_back": [
                {"rid": r.rid, "tokens_done": len(r.tokens)}
                for r in handed_back
            ],
            "queued": [r.rid for r in self._queue],
            "finished": [r.rid for r in self.finished],
        }

    # -- reporting ------------------------------------------------------

    def occupancy(self) -> dict:
        ticks = max(1, self.ticks)
        out = {
            "slot_occupancy": round(
                self._live_ticks / (ticks * self.engine.serving.slots), 4
            ),
            "kv_blocks_peak": self.engine.allocator.peak_used,
            "kv_blocks_total": self.engine.pool.n_blocks - 1,
            "backpressure_ticks": self.backpressure_ticks,
            # instantaneous feedback the fleet router's least-loaded
            # placement keys on (serve/fleet/router.py): slots with no
            # live request, allocatable blocks (free + reclaimable LRU),
            # and the request queue's current depth
            "free_slots": self.engine.serving.slots - len(self._slot_req),
            "kv_blocks_free": self.engine.allocator.free_blocks,
            "queue_depth": len(self._queue),
        }
        if self.spec_k > 0:
            # acceptance rate = accepted draft tokens / drafted; the
            # emitted bonus tokens ride free either way
            out["spec_drafted"] = self.spec_drafted
            out["spec_accepted"] = self.spec_accepted
            out["acceptance_rate"] = round(
                self.spec_accepted / max(1, self.spec_drafted), 4
            )
            out["tokens_per_tick"] = round(
                self.tokens_emitted / max(1, self.decode_ticks), 4
            )
        alloc = self.engine.allocator
        if alloc.cache is not None:
            out["prefix_hits"] = self.prefix_hits
            out["prefix_hit_rate"] = round(
                self.prefix_hits / max(1, self.prefix_lookups), 4
            )
            out["blocks_shared"] = self.blocks_shared
            out["cow_copies"] = self.cow_copies
            out["partial_hits"] = self.partial_hits
            out["tail_tokens_shared"] = self.tail_tokens_shared
            out["decode_blocks_registered"] = self.decode_blocks_registered
            out["prefill_chunks"] = self.prefill_chunks
            out["prefill_chunks_saved"] = self.prefill_chunks_saved
            out["lru_evictions"] = alloc.lru_evictions
            out["lru_reclaims"] = alloc.lru_reclaims
            out["kv_blocks_cached"] = alloc.cached_blocks
        return out
