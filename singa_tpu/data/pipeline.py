"""Host-side input pipeline: shard -> batched numpy arrays.

Replaces the reference's DataLayer machinery
(ShardDataLayer::ComputeFeature, src/worker/layer.cc:646-673). Parsing/
normalization itself is NOT done here — parser layers are elementwise
math and live inside the jitted step where XLA fuses them for free; this
pipeline just delivers raw record batches with the reference's
sequencing semantics (sequential reads with wraparound, one-time
random_skip). Read-ahead — the double-buffered ParserLayer::Prefetching
protocol (include/worker/base_layer.h:510-537) — lives one level up, in
data/device_prefetch.py: its feeders drive a pipeline from ONE thread
and overlap the device transfer too, which keeps this class thread-free
and therefore seek()-able at any point (checkpoint resume, guard
rollback).
"""

from __future__ import annotations

import numpy as np

from .records import decode_record
from .shard import ShardReader


def load_shard_arrays(folder: str) -> tuple[np.ndarray, np.ndarray]:
    """Decode every record in a shard into (images, labels) arrays.

    Images come back as float32 with the record's own shape appended after
    the batch dim; uint8 ``pixel`` payloads are widened (the reference's
    cast-to-uint8-then-float dance, layer.cc:390-400).

    Uniform-shape shards decode through the native C++ codec when built
    (singa_tpu.native — the counterpart of the reference's C++ data layer);
    anything it declines falls back to the Python codec below.
    """
    from .. import native
    from .shard import shard_path

    fast = native.load_dataset(shard_path(folder))
    if fast is not None:
        return fast

    images: list[np.ndarray] = []
    labels: list[int] = []
    with ShardReader(folder) as reader:
        for _, val in reader:
            rec = decode_record(val)
            shape = tuple(rec.shape) if rec.shape else (-1,)
            if rec.pixel:
                img = np.frombuffer(rec.pixel, dtype=np.uint8).astype(
                    np.float32
                )
            else:
                img = np.asarray(rec.data, dtype=np.float32)
            images.append(img.reshape(shape))
            labels.append(rec.label)
    if not images:
        raise ValueError(f"shard {folder!r} holds no records")
    return np.stack(images), np.asarray(labels, dtype=np.int32)


def load_lmdb_arrays(path: str) -> tuple[np.ndarray, np.ndarray]:
    """Decode every Datum in a Caffe LMDB into (images, labels) arrays,
    the in-memory equivalent of LMDBDataLayer's cursor loop + conversion
    (reference layer.cc:237-328).

    Uniform-geometry databases decode through the native C++ walker when
    built (singa_tpu.native, like the reference's liblmdb path); a missing
    toolchain or unsupported database feature falls back to the
    pure-Python B+tree reader. Mixed per-record geometry cannot be
    batched by either path and raises a clear error."""
    from .. import native
    from .lmdbio import LMDBReader, lmdb_data_path
    from .records import datum_to_image_record, decode_datum

    fast = native.load_lmdb_dataset(lmdb_data_path(path))
    if fast is not None:
        return fast

    images: list[np.ndarray] = []
    labels: list[int] = []
    first_shape: tuple | None = None
    with LMDBReader(path) as reader:
        for key, val in reader:
            rec = datum_to_image_record(decode_datum(val))
            shape = tuple(rec.shape) if any(rec.shape) else (-1,)
            if rec.pixel:
                img = np.frombuffer(rec.pixel, dtype=np.uint8).astype(
                    np.float32
                )
            else:
                img = np.asarray(rec.data, dtype=np.float32)
            img = img.reshape(shape)
            # compare post-reshape shapes so shapeless records (which all
            # normalize to (-1,)) still trip on differing lengths
            if first_shape is None:
                first_shape = img.shape
            elif img.shape != first_shape:
                raise ValueError(
                    f"LMDB {path!r}: record {key!r} has shape {img.shape}, "
                    f"others {first_shape} — mixed geometry cannot be "
                    "batched; re-export at a uniform size"
                )
            images.append(img)
            labels.append(rec.label)
    if not images:
        raise ValueError(f"LMDB {path!r} holds no records")
    return np.stack(images), np.asarray(labels, dtype=np.int32)


class BatchPipeline:
    """Batched sequential iteration with wraparound.

    Mirrors ShardDataLayer semantics: records are consumed in file order,
    wrapping at the end; ``random_skip`` skips ``rand() % random_skip``
    records once at startup (layer.cc:646-656). Read-ahead lives in the
    device feeders (data/device_prefetch.py), which overlap the device
    transfer as well and keep this class single-threaded — so ``seek``
    works at any point in a run.
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batchsize: int,
        *,
        random_skip: int = 0,
        seed: int | None = None,
    ):
        if len(images) != len(labels):
            raise ValueError("images/labels length mismatch")
        self.images = images
        self.labels = labels
        self.batchsize = batchsize
        self.n = len(images)
        self._pos = 0  # cursor (record index of the next unread batch)
        if random_skip:
            rng = np.random.RandomState(seed)
            self._pos = int(rng.randint(0, random_skip)) % self.n
        # CONSUMED bookkeeping: position derives from batches handed out,
        # relative to the post-skip start. A device feeder consuming this
        # pipeline from its thread reads ahead of the trainer; it tracks
        # the trainer-consumed view itself (DeviceFeeder.consumed_positions).
        self._start = self._pos
        self._consumed = 0

    @property
    def position(self) -> int:
        """Stream position (record index of the next batch). Checkpoints
        persist this; seek() restores it. The one-time random_skip draw
        is baked into it, so resume needs no separate RNG state."""
        return int((self._start + self._consumed * self.batchsize) % self.n)

    def seek(self, pos: int) -> None:
        """Reposition the stream (checkpoint resume / guard rollback /
        the chunk stager's window-boundary re-sync)."""
        self._pos = int(pos) % self.n
        self._start = self._pos
        self._consumed = 0

    def advance(self, nsteps: int) -> None:
        """Skip ``nsteps`` batches: the device-side chunk engine consumed
        them via on-device index math (Trainer.train_chunk)."""
        self._pos = int((self._pos + nsteps * self.batchsize) % self.n)
        self._consumed += nsteps

    def _next_indices(self) -> np.ndarray:
        idx = (self._pos + np.arange(self.batchsize)) % self.n
        self._pos = int((self._pos + self.batchsize) % self.n)
        return idx

    def next_indices(self) -> np.ndarray:
        """Advance the stream and return the batch's record indices
        without materializing arrays (device-cached datasets gather on
        device)."""
        idx = self._next_indices()
        self._consumed += 1
        return idx

    def next_batch(self) -> tuple[np.ndarray, np.ndarray]:
        idx = self._next_indices()
        self._consumed += 1
        return self.images[idx], self.labels[idx]

    def steps_per_epoch(self) -> int:
        return max(1, self.n // self.batchsize)
