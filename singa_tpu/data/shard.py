"""Shard record files, bit-compatible with the reference's shard::Shard.

Wire format (reference: src/utils/shard.cc:49-67): a flat stream of tuples

    [8-byte LE keylen][key bytes][8-byte LE vallen][val bytes]

inside ``<folder>/shard.dat``. Semantics preserved from the reference:

- keys are deduplicated per writer session (Insert returns False on a
  duplicate key or empty value, shard.cc:50-52)
- kAppend mode scans the existing file, seeds the dedup key set, and
  truncates a torn final tuple left by a crash (PrepareForAppend,
  shard.cc:175-206)
- readers stream sequentially with buffered IO and stop cleanly at a torn
  tail (Next returns False, shard.cc:104-149)
"""

from __future__ import annotations

import os
import struct

_LEN = struct.Struct("<Q")  # size_t on the reference's 64-bit LE platforms


class ShardError(IOError):
    pass


def shard_path(folder: str) -> str:
    return os.path.join(folder, "shard.dat")


def _read_tuple(f, size: int) -> tuple[bytes, bytes] | None:
    """One complete [keylen key vallen val] tuple, or None at EOF/torn
    tail (file position restored). The single copy of the torn-tail
    arithmetic shared by the reader and the append pre-scan: lengths
    are bounded against ``size`` BEFORE the read, so a corrupt u64
    length surfaces as a torn tail, never OverflowError/MemoryError
    from read() — the native codec applies the same guard
    (shardcodec.cc). Fuzz-pinned in test_records_fuzz.py."""
    pos = f.tell()
    head = f.read(8)
    if len(head) < 8:
        f.seek(pos)
        return None
    keylen = _LEN.unpack(head)[0]
    if keylen > size - pos - 8:
        f.seek(pos)
        return None
    key = f.read(keylen)
    head = f.read(8)
    if len(key) < keylen or len(head) < 8:
        f.seek(pos)
        return None
    vallen = _LEN.unpack(head)[0]
    if vallen > size - pos - 16 - keylen:
        f.seek(pos)
        return None
    val = f.read(vallen)
    if len(val) < vallen:
        f.seek(pos)
        return None
    return key, val


class ShardWriter:
    """Create or append a shard (reference modes kCreate / kAppend)."""

    def __init__(self, folder: str, append: bool = False):
        os.makedirs(folder, exist_ok=True)
        self.path = shard_path(folder)
        self.keys: set[bytes] = set()
        if append and os.path.exists(self.path):
            valid_end = self._scan_existing()
            self._f = open(self.path, "r+b")
            self._f.truncate(valid_end)  # drop a torn tail write
            self._f.seek(valid_end)
        else:
            self._f = open(self.path, "wb")

    def _scan_existing(self) -> int:
        """Scan complete tuples, fill the key set, return the offset after
        the last complete tuple (PrepareForAppend, shard.cc:175-206);
        torn/corrupt tails stop the scan (_read_tuple)."""
        valid_end = 0
        size = os.path.getsize(self.path)
        with open(self.path, "rb") as f:
            while (kv := _read_tuple(f, size)) is not None:
                self.keys.add(kv[0])
                valid_end = f.tell()
        return valid_end

    def insert(self, key: bytes | str, val: bytes) -> bool:
        """Append one tuple; False on duplicate key or empty value."""
        if isinstance(key, str):
            key = key.encode()
        if key in self.keys or not val:
            return False
        self.keys.add(key)
        self._f.write(_LEN.pack(len(key)))
        self._f.write(key)
        self._f.write(_LEN.pack(len(val)))
        self._f.write(val)
        return True

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ShardReader:
    """Sequential reader with wraparound (reference mode kRead)."""

    def __init__(self, folder: str, buffer_size: int = 1 << 20):
        self.path = shard_path(folder)
        if not os.path.exists(self.path):
            raise ShardError(f"no shard.dat under {folder!r}")
        self._bufsize = buffer_size
        self._f = open(self.path, "rb", buffering=buffer_size)
        # snapshot the size once: lengths are bounded against it in
        # next() (anything past the opened snapshot is a torn tail; a
        # per-record fstat would put a syscall on the training hot path)
        self._size = os.fstat(self._f.fileno()).st_size

    def next(self) -> tuple[bytes, bytes] | None:
        """Next (key, value), or None at EOF / torn tail (_read_tuple
        holds the shared torn-tail/corrupt-length arithmetic)."""
        return _read_tuple(self._f, self._size)

    def seek_to_first(self) -> None:
        self._f.seek(0)

    def count(self) -> int:
        """Number of complete tuples (reference: Shard::Count)."""
        pos = self._f.tell()
        self._f.seek(0)
        n = 0
        while self.next() is not None:
            n += 1
        self._f.seek(pos)
        return n

    def __iter__(self):
        while True:
            kv = self.next()
            if kv is None:
                return
            yield kv

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
