"""Device-side input prefetch: the other half of the Prefetching protocol.

The reference overlaps input with compute via the double-buffered
``ParserLayer::Prefetching`` protocol (include/worker/base_layer.h:510-537)
— while batch k trains, a thread assembles batch k+1 into the *other*
buffer. Our ``BatchPipeline`` reproduced only the host-side half of that:
the gather ran ahead, but every step still paid a synchronous
``jax.device_put`` (and, for the scan-chunk engine, device-cached datasets
were the only way to keep the host off the step path at all).

This module is the device-side half, in two grain sizes:

  ``DeviceFeeder`` — per-step double buffering. A daemon thread assembles
      batch k+1 on the host AND starts its ``jax.device_put`` to the
      batch shardings while step k runs; the trainer's ``_next_batch``
      becomes a buffer swap. The transfer overlaps compute (device_put
      is asynchronous — the arrays commit before the step that consumes
      them dispatches).

  ``ChunkStager`` — chunk-granularity double buffering for streaming
      ``lax.scan`` windows. While one staged block (the next N batches,
      stacked into one host→device transfer) is consumed by a running
      scan, the thread stages the following block. Memory is bounded at
      TWO blocks (one consuming + one staged): the thread waits on a
      slot before staging, it never runs ahead of that.

Stream semantics are preserved exactly:

  - batches/blocks come out in sequential wraparound order — the same
    index math as the synchronous path (the stager owns a private record
    cursor; the feeder drives the pipelines themselves, on one thread);
  - consumed positions are tracked per batch actually handed to the
    trainer, so a checkpoint written at a step boundary never skips
    read-ahead the trainer did not see (`consumed_positions`);
  - ``reset()`` discards all read-ahead and joins the thread, so a
    checkpoint restore (or guard rollback) can re-seek the streams and
    restart deterministically.

Both classes surface a worker-thread exception on the next ``next()`` /
``take()`` instead of dying silently.
"""

from __future__ import annotations

import queue
import threading

import numpy as np


class InputFeedError(RuntimeError):
    """A background input-feeder thread failed; re-raised on the step
    path so the trainer cannot silently train on missing data."""


class _Prefetcher:
    """Shared thread scaffolding: slot-bounded production, FIFO handoff,
    error surfacing, and a drain-and-join ``reset``."""

    #: blocks/batches staged-but-unconsumed at once (the double buffer's
    #: read-ahead side; the consumer's in-use item is the other half)
    _SLOTS = 1

    def __init__(self):
        self._q: queue.Queue = queue.Queue()
        self._slots = threading.Semaphore(self._SLOTS)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None

    # -- producer side -------------------------------------------------

    def _produce(self):
        """One item, or None to end the stream. Runs on the thread."""
        raise NotImplementedError

    def _run(self) -> None:
        while True:
            self._slots.acquire()
            if self._stop.is_set():
                return
            try:
                item = self._produce()
            except BaseException as e:
                self._error = e
                self._q.put(None)  # wake a blocked consumer
                return
            if item is None:
                # end of stream: leave a marker so a consumer that asks
                # for one item too many fails loudly instead of hanging
                self._q.put(None)
                return
            self._q.put(item)

    def _start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=type(self).__name__, daemon=True
        )
        self._thread.start()

    # -- consumer side -------------------------------------------------

    def _get(self):
        item = self._q.get()
        self._slots.release()
        if item is None:
            err = self._error
            # park the dead thread NOW: a caller that catches the error
            # and retries must restart production (and fail loudly again
            # if the condition persists), never block on an empty queue
            self.reset()
            if err is not None:
                raise InputFeedError(
                    f"background input feeder failed: "
                    f"{type(err).__name__}: {err}"
                ) from err
            raise InputFeedError("input feeder ended early")
        return item

    def reset(self) -> None:
        """Discard every read-ahead item and join the thread. After this
        the caller may re-seek the underlying streams; production
        restarts lazily on the next request."""
        t = self._thread
        if t is not None:
            self._stop.set()
            self._slots.release()  # unblock a producer waiting for a slot
            while t.is_alive():
                try:  # unblock a producer mid-put, then let it exit
                    self._q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.02)
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread = None
        self._stop = threading.Event()
        self._slots = threading.Semaphore(self._SLOTS)
        self._error = None


class DeviceFeeder(_Prefetcher):
    """Per-step double-buffered device feeder.

    ``assemble()`` runs on the feeder thread: it consumes one batch from
    the pipelines, starts its ``jax.device_put`` to the right shardings,
    and returns the batch dict — identical arrays, identical placement,
    to the synchronous path. ``positions()`` (same thread, right after)
    snapshots the stream positions AFTER that batch; the value travels
    with the batch so ``consumed_positions`` always reflects exactly the
    batches the trainer has taken, never the thread's read-ahead.
    """

    _SLOTS = 1  # one batch staged ahead + the one the step consumes

    def __init__(self, assemble, positions):
        super().__init__()
        self._assemble = assemble
        self._positions = positions
        #: stream positions after the last batch handed to the trainer
        #: (checkpoints persist THESE, not the pipelines' read-ahead)
        self.consumed_positions: dict[str, int] = {}

    def _produce(self):
        batch = self._assemble()
        return batch, dict(self._positions())

    def next(self) -> dict:
        """The buffer swap: return the already-transferred next batch
        and kick assembly of the one after."""
        if self._thread is None:
            self._start()
        batch, pos = self._get()
        self.consumed_positions = pos
        return batch

    def reset(self) -> None:
        super().reset()
        self.consumed_positions = {}


class BurstFeeder(_Prefetcher):
    """A device feeder bounded to EXACTLY ``n`` batches — the serving
    tier's request-batching discipline applied to a bounded burst (an
    eval/validation cadence): batch k+1 assembles + transfers on the
    worker thread while step k computes, and production STOPS after the
    n-th item, so the thread never consumes records past the burst —
    stream positions land exactly where the synchronous path leaves
    them (checkpoint/resume parity needs that, not just value parity).
    """

    _SLOTS = 1

    def __init__(self, assemble, n: int):
        super().__init__()
        self._assemble = assemble
        self._left = int(n)

    def _produce(self):
        if self._left <= 0:
            return None
        self._left -= 1
        # 1-tuple wrapper: the end-of-stream marker is None, a batch
        # must never be mistaken for it
        return (self._assemble(),)

    def next(self):
        if self._thread is None:
            self._start()
        return self._get()[0]


class ChunkStager(_Prefetcher):
    """Chunk-granularity double buffering for streaming scan windows.

    The stager owns a private wraparound cursor per stream (initialized
    from the pipelines at start) and follows the trainer's deterministic
    chunk schedule: block k covers ``schedule(step_k)`` steps starting
    where block k-1 ended. ``take(step0, nsteps)`` hands the staged
    block over (stacked ``(nsteps * batches_per_step * batchsize, ...)``
    arrays, already committed to the device) together with the stream
    positions after it, and unblocks staging of the next block. A
    schedule mismatch (the trainer asked for a window the stager did not
    predict) raises instead of silently feeding wrong records.
    """

    _SLOTS = 1  # one block staged ahead + the one the scan consumes

    def __init__(self, sources, batches_per_step, schedule, cursors, put):
        """``sources``: {layer: (images, labels, batchsize)} host arrays;
        ``schedule(step) -> nsteps`` (0 ends the stream);
        ``cursors() -> {layer: record position}`` read at start;
        ``put(np_array, layer, kind) -> device array`` commits a staged
        block — ``layer``/``kind`` ("image"/"label") let the trainer
        stage each array to its data-axis batch sharding (each device
        receives only its slice of the block) instead of a full-block
        broadcast to every device."""
        super().__init__()
        self._sources = sources
        self._bps = batches_per_step
        self._schedule = schedule
        self._cursors = cursors
        self._put = put
        self._step: int | None = None
        self._pos: dict[str, int] = {}

    def _produce(self):
        nsteps = int(self._schedule(self._step))
        if nsteps <= 0:
            return None
        block: dict = {}
        positions: dict[str, int] = {}
        for name, (images, labels, bs) in self._sources.items():
            n = len(images)
            span = nsteps * self._bps * bs
            idx = (self._pos[name] + np.arange(span)) % n
            block[name] = {
                "image": self._put(images[idx], name, "image"),
                "label": self._put(labels[idx], name, "label"),
            }
            self._pos[name] = int((self._pos[name] + span) % n)
            positions[name] = self._pos[name]
        step0, self._step = self._step, self._step + nsteps
        return step0, nsteps, block, positions

    def take(self, step0: int, nsteps: int):
        """-> (block, positions_after) for the window
        ``[step0, step0 + nsteps)``."""
        if self._thread is None:
            self._step = int(step0)
            self._pos = {k: int(v) for k, v in self._cursors().items()}
            self._start()
        s, n, block, positions = self._get()
        if (s, n) != (step0, nsteps):
            # discard the whole read-ahead before raising: a caller that
            # survives the error must restart from fresh cursors, not
            # keep draining a schedule that already diverged
            self.reset()
            raise InputFeedError(
                f"chunk stager staged window ({s}, {n}) but the trainer "
                f"asked for ({step0}, {nsteps}) — schedule drift"
            )
        return block, positions
