"""Dataset -> shard converter CLI (the reference's build/loader).

Mirrors tools/data_loader/ semantics: shards are opened in append mode so a
crashed run resumes where it stopped (data_loader.cc:12-14,122), and MNIST
idx files are parsed with the same big-endian magic/meta layout
(data_source.cc:25-95). Keys are zero-padded record indices.

Sources:
  mnist      train/test idx file pairs -> pixel-bytes records (shape 28x28)
  cifar      CIFAR-10 binary batches (data_batch_*.bin / test_batch.bin,
             1 label byte + 3072 RGB bytes per record) -> (3,32,32) records
  imagenet   ImageNet-layout folder (img/ + rid.txt label list) -> RGB
             (3,size,size) records via PIL resize, the reference's
             ImageNetSource (data_source.cc:97-196)
  digits     sklearn load_digits upscaled to 28x28 — a real, learnable
             stand-in when the MNIST files aren't on disk (this image has no
             network egress); accuracy-parity tests train on this
  synthetic  deterministic Gaussian-blob classes (grayscale or RGB via
             --channels), for benchmarks/smoke tests

Interop: ``shard2lmdb`` / ``lmdb2shard`` convert to/from Caffe-style LMDB
databases (singa_tpu/data/lmdbio.py) for kLMDBData configs.

Mean files: ``compute-mean`` writes a per-pixel mean.npy over a shard, the
counterpart of the reference's binaryproto image mean
(data_source.cc:129-137); rgbimage_param.meanfile points at it.

Usage:
  python -m singa_tpu.data.loader mnist  --image-file f --label-file f --output DIR
  python -m singa_tpu.data.loader cifar  --bin-files f1 f2 ... --output DIR
  python -m singa_tpu.data.loader digits --output DIR [--split train|test]
  python -m singa_tpu.data.loader synthetic --output DIR --n 1000 [--classes 10] [--channels 3]
  python -m singa_tpu.data.loader imagenet --folder DIR --output DIR [--size 256]
  python -m singa_tpu.data.loader compute-mean --input DIR --output mean.npy
  python -m singa_tpu.data.loader split --input DIR --prefix P --n N [--mode equal|head]
  python -m singa_tpu.data.loader shard2lmdb --input DIR --output DIR
  python -m singa_tpu.data.loader lmdb2shard --input DIR --output DIR
"""

from __future__ import annotations

import argparse
import os
import struct
import sys

import numpy as np

from .records import ImageRecord, encode_record
from .shard import ShardReader, ShardWriter


def _key(i: int) -> str:
    return f"{i:08d}"


def write_records(
    folder: str, images: np.ndarray, labels: np.ndarray, append: bool = True
) -> int:
    """Write uint8 (N,H,W) images + labels as Records; returns #inserted.

    Fresh shards encode through the native C++ codec when built
    (byte-identical output, singa_tpu/native); appends go through the
    Python writer because its key set deduplicates against existing
    records, matching the reference loader's resume semantics.
    """
    images = np.asarray(images, dtype=np.uint8)
    from .. import native
    from .shard import shard_path

    os.makedirs(folder, exist_ok=True)
    if not (append and os.path.exists(shard_path(folder))):
        fast = native.write_records(shard_path(folder), images, labels)
        if fast is not None:
            return fast
    n = 0
    with ShardWriter(folder, append=append) as w:
        for i, (img, label) in enumerate(zip(images, labels)):
            rec = ImageRecord(
                shape=list(img.shape), label=int(label), pixel=img.tobytes()
            )
            if w.insert(_key(i), encode_record(rec)):
                n += 1
        w.flush()
    return n


# ---------------------------- sources ----------------------------


def read_idx_images(path: str) -> np.ndarray:
    """Parse an MNIST idx3-ubyte image file (data_source.cc:31-54)."""
    with open(path, "rb") as f:
        magic, num, h, w = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad image magic {magic} (want 2051)")
        buf = f.read(num * h * w)
    return np.frombuffer(buf, dtype=np.uint8).reshape(num, h, w)


def read_idx_labels(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        magic, num = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{path}: bad label magic {magic} (want 2049)")
        buf = f.read(num)
    return np.frombuffer(buf, dtype=np.uint8)


def read_cifar_bins(paths: list[str]) -> tuple[np.ndarray, np.ndarray]:
    """Parse CIFAR-10 binary batch files: each record is 1 label byte
    followed by 3072 bytes (3 channels x 32x32, channel-major — already
    the (C,H,W) layout our RGB records use)."""
    rec = 1 + 3 * 32 * 32
    images, labels = [], []
    for path in paths:
        buf = np.fromfile(path, dtype=np.uint8)
        if buf.size % rec:
            raise ValueError(
                f"{path}: size {buf.size} is not a multiple of {rec}"
            )
        rows = buf.reshape(-1, rec)
        labels.append(rows[:, 0])
        images.append(rows[:, 1:].reshape(-1, 3, 32, 32))
    return np.concatenate(images), np.concatenate(labels)


def compute_mean(folder: str, out_path: str) -> np.ndarray:
    """Per-pixel float32 mean over every record in a shard, saved as .npy
    (the reference's mean binaryproto, data_source.cc:129-137)."""
    from .pipeline import load_shard_arrays

    images, _ = load_shard_arrays(folder)
    mean = images.astype(np.float64).mean(axis=0).astype(np.float32)
    np.save(out_path, mean)
    return mean


def digits_arrays(split: str = "train") -> tuple[np.ndarray, np.ndarray]:
    """sklearn 8x8 digits, nearest-upscaled to 28x28 uint8 images."""
    from sklearn.datasets import load_digits

    d = load_digits()
    images = (d.images / d.images.max() * 255.0).astype(np.uint8)
    # 8x8 -> 32x32 via kron, center-crop to 28x28
    big = np.kron(images, np.ones((1, 4, 4), dtype=np.uint8))
    big = big[:, 2:30, 2:30]
    labels = d.target.astype(np.uint8)
    # deterministic 80/20 split, interleaved so class balance holds
    test_mask = np.arange(len(big)) % 5 == 4
    if split == "test":
        return big[test_mask], labels[test_mask]
    return big[~test_mask], labels[~test_mask]


def synthetic_arrays(
    n: int,
    classes: int = 10,
    size: int = 28,
    seed: int = 0,
    noise_seed: int | None = None,
    channels: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Gaussian class-template blobs: learnable, deterministic, no IO.

    ``seed`` fixes the class templates, ``noise_seed`` the per-sample noise —
    pass different noise seeds to get disjoint train/test splits of the same
    classification problem. ``channels`` > 0 makes (C,H,W) RGB-style
    records (CIFAR-shaped with channels=3, size=32).
    """
    rng = np.random.RandomState(seed)
    shape = (channels, size, size) if channels else (size, size)
    templates = rng.rand(classes, *shape) * 160.0
    labels = (np.arange(n) % classes).astype(np.uint8)
    nrng = rng if noise_seed is None else np.random.RandomState(noise_seed)
    noise = nrng.rand(n, *shape) * 95.0
    images = (templates[labels] + noise).clip(0, 255).astype(np.uint8)
    return images, labels


def structured_rgb(
    n: int,
    classes: int = 10,
    seed: int = 0,
    noise_seed: int | None = None,
    class_amplitude: float | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Spatially-structured synthetic RGB: kron-upsampled 8x8 class
    templates (CIFAR-shaped 3x32x32). Weight-shared convs cannot
    discriminate the iid-noise templates of synthetic_arrays (each pixel
    independent), so conv-net convergence runs need low-frequency class
    structure. ``noise_seed`` works like synthetic_arrays'.

    ``class_amplitude`` (r5) controls class overlap: None keeps the
    legacy fully-independent templates (amplitude 160, trivially
    separable — fine for short smoke oracles but the 70k-step AlexNet
    run saturates at 100%, a ceiling-pinned metric that cannot detect a
    regression). A float A builds templates as shared_base + U(0, A)
    per-class delta against the U(0, 95) pixel noise, so the task has a
    real Bayes error: pairwise template separation is A*sqrt(3072/6) ~
    22.6*A against sample noise sigma 27.4 along the discriminant —
    A ~ 6 targets ~90% optimal accuracy for 10 classes (BASELINE.md r5
    records the measured landing point of the full AlexNet run)."""
    rng = np.random.RandomState(seed)
    if class_amplitude is None:
        small = rng.rand(classes, 3, 8, 8) * 160
    else:
        a = float(class_amplitude)
        base = rng.rand(1, 3, 8, 8) * (160.0 - a)
        small = base + rng.rand(classes, 3, 8, 8) * a
    templates = np.kron(small, np.ones((1, 1, 4, 4)))
    labels = (np.arange(n) % classes).astype(np.uint8)
    nrng = rng if noise_seed is None else np.random.RandomState(noise_seed)
    noise = nrng.rand(n, 3, 32, 32) * 95
    return (templates[labels] + noise).clip(0, 255).astype(np.uint8), labels


def load_label_lines(path: str) -> list[tuple[str, int]]:
    """Parse an ImageNet rid.txt label list: whitespace-separated
    "relative/img/path label" pairs (data_source.cc:109-127)."""
    with open(path) as f:
        toks = f.read().split()
    if len(toks) % 2:
        raise ValueError(f"{path}: odd token count (path without label)")
    return [(toks[i], int(toks[i + 1])) for i in range(0, len(toks), 2)]


def imagenet_records(folder: str, size: int):
    """Stream (key, ImageRecord) pairs from an ImageNet-layout folder:
    ``folder/img/`` + ``folder/rid.txt`` (data_source.cc:97-196).

    Images decode through PIL (the reference uses OpenCV), resize to
    size x size, and store raw channel-major RGB uint8. Two deliberate
    divergences from the reference, both documented here: channel order is
    RGB (not OpenCV's BGR — consistent within this framework's RGB
    pipeline), and the image mean is NOT subtracted at load time (the
    reference quantizes mean-subtracted floats back into bytes,
    data_source.cc:163-173, losing precision; here RGBImageLayer subtracts
    the float meanfile inside the jitted step instead)."""
    from PIL import Image

    lines = load_label_lines(os.path.join(folder, "rid.txt"))
    img_dir = os.path.join(folder, "img")
    for relpath, label in lines:
        path = os.path.join(img_dir, relpath)
        try:
            with Image.open(path) as im:
                im = im.convert("RGB")
                if size > 0:
                    im = im.resize((size, size), Image.BILINEAR)
                arr = np.asarray(im, dtype=np.uint8)
        except OSError as e:
            print(f"skipping invalid img {path}: {e}", file=sys.stderr)
            continue
        chw = np.ascontiguousarray(arr.transpose(2, 0, 1))  # (3,H,W)
        yield relpath, ImageRecord(
            shape=list(chw.shape), label=label, pixel=chw.tobytes()
        )


def write_imagenet(folder: str, output: str, size: int) -> int:
    """ImageNet folder -> shard, record-streamed (never holds the dataset
    in memory); append mode resumes a crashed conversion by key like the
    reference loader (data_loader.cc:12-14,122)."""
    n = 0
    shapes: set[tuple[int, ...]] = set()
    with ShardWriter(output, append=True) as w:
        for key, rec in imagenet_records(folder, size):
            shapes.add(tuple(rec.shape))
            if w.insert(key, encode_record(rec)):
                n += 1
        w.flush()
    if len(shapes) > 1:
        print(
            f"WARNING: {output} holds {len(shapes)} distinct image shapes "
            "(--size 0 with mixed-size inputs); such a shard cannot be "
            "batched at training time — rerun with --size N",
            file=sys.stderr,
        )
    return n


# ---------------------------- split (reference Split/SplitN) -----------


def split_shard(input_dir: str, prefix: str, n: int, mode: str = "equal"):
    with ShardReader(input_dir) as reader:
        tuples = list(reader)
    total = len(tuples)
    if mode == "equal":
        if n >= total:
            raise ValueError("too many sub-shards")
        sizes = [total // n + (total % n if i == 0 else 0) for i in range(n)]
        pos = 0
        for i, sz in enumerate(sizes):
            with ShardWriter(f"{prefix}-{i}", append=True) as w:
                for k, v in tuples[pos : pos + sz]:
                    w.insert(k, v)
                w.flush()
            pos += sz
    else:  # head: first n records into -0, rest into -1
        if n >= total:
            raise ValueError("sub shard must be smaller than original")
        for i, chunk in enumerate((tuples[:n], tuples[n:])):
            with ShardWriter(f"{prefix}-{i}", append=True) as w:
                for k, v in chunk:
                    w.insert(k, v)
                w.flush()


def text_token_arrays(
    path: str, seq_len: int, stride: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Byte-level LM dataset from any text/binary file: overlapping
    fixed-length windows of raw bytes (vocab 256). Labels are unused (the
    kLMLoss target is the sequence itself)."""
    with open(path, "rb") as f:
        raw = np.frombuffer(f.read(), dtype=np.uint8)
    if len(raw) < seq_len + 1:
        raise ValueError(f"{path}: shorter than one {seq_len}-byte window")
    stride = stride or seq_len
    # inclusive stop: the window starting at len-seq_len is valid (kLMLoss
    # targets are within-window)
    starts = np.arange(0, len(raw) - seq_len + 1, stride)
    tokens = np.stack([raw[s : s + seq_len] for s in starts])
    return tokens, np.zeros(len(tokens), dtype=np.uint8)


def synthetic_token_arrays(
    n: int, seq_len: int = 128, vocab: int = 64, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Learnable synthetic sequences: a fixed random Markov chain over
    ``vocab`` symbols (deterministic given seed) — next-token accuracy
    well above chance is reachable, so LM convergence tests mean
    something."""
    if not 2 <= vocab <= 256:
        raise ValueError(
            f"vocab must be in [2, 256] (uint8 token records), got {vocab}"
        )
    rng = np.random.RandomState(seed)
    # each symbol strongly prefers one successor (80%), else uniform
    succ = rng.randint(0, vocab, size=vocab)
    seqs = np.empty((n, seq_len), dtype=np.uint8)
    state = rng.randint(0, vocab, size=n)
    for t in range(seq_len):
        seqs[:, t] = state
        follow = rng.rand(n) < 0.8
        state = np.where(follow, succ[state], rng.randint(0, vocab, size=n))
    return seqs, np.zeros(n, dtype=np.uint8)


# ------------------- LMDB interop (reference kLMDBData) -------------------


def shard_to_lmdb(input_dir: str, output_dir: str) -> int:
    """Re-encode a shard as a Caffe-style LMDB of Datum messages, keyed
    like Caffe's convert tools (%08d). Lets kLMDBData configs run against
    data produced by this loader."""
    from .lmdbio import LMDBError, write_lmdb
    from .records import Datum, decode_record, encode_datum

    def datums():
        with ShardReader(input_dir) as reader:
            for key, val in reader:
                rec = decode_record(val)
                shape = list(rec.shape) + [1] * (3 - len(rec.shape))
                if len(rec.shape) == 2:  # (H,W) grayscale -> C=1
                    shape = [1, rec.shape[0], rec.shape[1]]
                d = Datum(
                    channels=shape[0], height=shape[1], width=shape[2],
                    data=rec.pixel, label=rec.label, float_data=rec.data,
                )
                # latin-1 mirrors lmdb_to_shard's decode: keys are raw bytes
                yield (key.encode("latin-1") if isinstance(key, str)
                       else key, encode_datum(d))

    try:
        # loader-written shards insert zero-padded ascending keys, so the
        # streaming O(page)-memory path normally wins
        return write_lmdb(output_dir, datums(), assume_sorted=True)
    except LMDBError as e:
        if "out of order" not in str(e):
            raise
        return write_lmdb(output_dir, datums())


def lmdb_to_shard(input_dir: str, output_dir: str) -> int:
    """Convert a Caffe LMDB into a shard (the migration path the old
    kLMDBData error message promised)."""
    from .lmdbio import LMDBReader
    from .records import datum_to_image_record, decode_datum, encode_record

    n = 0
    with LMDBReader(input_dir) as reader, ShardWriter(
        output_dir, append=True
    ) as w:
        for key, val in reader:
            rec = datum_to_image_record(decode_datum(val))
            if w.insert(key.decode("latin-1"), encode_record(rec)):
                n += 1
        w.flush()
    return n


# ---------------------------- CLI ----------------------------


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="singa_tpu.data.loader")
    sub = ap.add_subparsers(dest="source", required=True)

    p = sub.add_parser("mnist")
    p.add_argument("--image-file", required=True)
    p.add_argument("--label-file", required=True)
    p.add_argument("--output", required=True)

    p = sub.add_parser("cifar")
    p.add_argument("--bin-files", nargs="+", required=True)
    p.add_argument("--output", required=True)

    p = sub.add_parser("digits")
    p.add_argument("--output", required=True)
    p.add_argument("--split", choices=("train", "test"), default="train")

    p = sub.add_parser("synthetic")
    p.add_argument("--output", required=True)
    p.add_argument("--n", type=int, default=1000)
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--size", type=int, default=28)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--channels", type=int, default=0)

    p = sub.add_parser("text")
    p.add_argument("--input", required=True, help="any text/binary file")
    p.add_argument("--output", required=True)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--stride", type=int, default=0,
                   help="window stride (default seq-len, non-overlapping)")

    p = sub.add_parser("tokens")
    p.add_argument("--output", required=True)
    p.add_argument("--n", type=int, default=1000)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)

    p = sub.add_parser("imagenet")
    p.add_argument("--folder", required=True,
                   help="dataset root holding img/ and rid.txt")
    p.add_argument("--output", required=True)
    p.add_argument("--size", type=int, default=256,
                   help="resize to size x size, squashing aspect ratio "
                   "like the reference loader (0 = keep original sizes; "
                   "only batchable if every image already matches)")

    p = sub.add_parser("compute-mean")
    p.add_argument("--input", required=True)
    p.add_argument("--output", required=True)

    p = sub.add_parser("shard2lmdb")
    p.add_argument("--input", required=True)
    p.add_argument("--output", required=True)

    p = sub.add_parser("lmdb2shard")
    p.add_argument("--input", required=True)
    p.add_argument("--output", required=True)

    p = sub.add_parser("split")
    p.add_argument("--input", required=True)
    p.add_argument("--prefix", required=True)
    p.add_argument("--n", type=int, required=True)
    p.add_argument("--mode", choices=("equal", "head"), default="equal")

    args = ap.parse_args(argv)
    if args.source == "mnist":
        images = read_idx_images(args.image_file)
        labels = read_idx_labels(args.label_file)
        if len(images) != len(labels):
            raise ValueError("image/label count mismatch")
        n = write_records(args.output, images, labels)
    elif args.source == "cifar":
        n = write_records(args.output, *read_cifar_bins(args.bin_files))
    elif args.source == "digits":
        n = write_records(args.output, *digits_arrays(args.split))
    elif args.source == "synthetic":
        n = write_records(
            args.output,
            *synthetic_arrays(
                args.n, args.classes, args.size, args.seed,
                channels=args.channels,
            ),
        )
    elif args.source == "text":
        n = write_records(
            args.output, *text_token_arrays(args.input, args.seq_len,
                                            args.stride)
        )
    elif args.source == "tokens":
        n = write_records(
            args.output,
            *synthetic_token_arrays(args.n, args.seq_len, args.vocab,
                                    args.seed),
        )
    elif args.source == "imagenet":
        n = write_imagenet(args.folder, args.output, args.size)
    elif args.source == "shard2lmdb":
        n = shard_to_lmdb(args.input, args.output)
        print(f"wrote {n} datums into {os.path.join(args.output, 'data.mdb')}")
        return 0
    elif args.source == "lmdb2shard":
        n = lmdb_to_shard(args.input, args.output)
    elif args.source == "compute-mean":
        mean = compute_mean(args.input, args.output)
        print(f"mean {tuple(mean.shape)} -> {args.output}")
        return 0
    else:
        split_shard(args.input, args.prefix, args.n, args.mode)
        print(f"split {args.input} -> {args.prefix}-*")
        return 0
    print(f"inserted {n} records into {os.path.join(args.output, 'shard.dat')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
