"""Data subsystem: record codec, shard files, dataset loaders, input pipeline.

Replaces the reference's L1/L9 data path — shard::Shard record files
(src/utils/shard.cc), protobuf Record values (src/proto/model.proto:279-305),
the data_loader tool (tools/data_loader/) and the prefetching data layers
(include/worker/base_layer.h:335-560) — with a host-side pipeline that feeds
device arrays to the jitted train step.
"""

from .records import ImageRecord, decode_record, encode_record
from .shard import ShardReader, ShardWriter
from .pipeline import BatchPipeline, load_shard_arrays
from .device_prefetch import ChunkStager, DeviceFeeder, InputFeedError

__all__ = [
    "ImageRecord",
    "decode_record",
    "encode_record",
    "ShardReader",
    "ShardWriter",
    "BatchPipeline",
    "load_shard_arrays",
    "DeviceFeeder",
    "ChunkStager",
    "InputFeedError",
]
