"""Protobuf wire-format codec for dataset records (no protobuf dependency).

The reference stores each sample as a binary-serialized ``Record`` proto
inside shard.dat (src/utils/shard.cc:43-47). For byte compatibility with
shards written by the reference's loader, this module hand-implements the
proto2 wire format for exactly these messages (src/proto/model.proto:279-305):

    message Record { optional Type type=1; optional SingleLabelImageRecord image=2; }
    message SingleLabelImageRecord {
      repeated int32 shape=1; optional int32 label=2;
      optional bytes pixel=3; repeated float data=4;
    }

The encoder writes canonical proto2 (unpacked repeated fields, ascending
field order); the decoder additionally accepts packed repeated encodings and
unknown fields, like any conforming proto2 reader.
"""

from __future__ import annotations

import dataclasses
import struct


class RecordError(ValueError):
    pass


RECORD_TYPE_SINGLE_LABEL_IMAGE = 0


@dataclasses.dataclass
class ImageRecord:
    """Decoded Record(kSingleLabelImage): the payload of one sample."""

    shape: list[int] = dataclasses.field(default_factory=list)
    label: int = 0
    pixel: bytes = b""  # raw uint8 pixels (exclusive with `data`)
    data: list[float] = dataclasses.field(default_factory=list)


# ---------------------------- varint / tags ----------------------------


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value += 1 << 64  # proto2 int32: negatives as 10-byte two's complement
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise RecordError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 64:
            raise RecordError("varint too long")


def _int32(value: int) -> int:
    value &= (1 << 64) - 1
    if value >= 1 << 63:
        value -= 1 << 64
    return int(value)


def _read_f32s(buf: bytes, pos: int, count: int) -> tuple[tuple, int]:
    """Bounds-checked little-endian float reads: a truncated buffer must
    raise RecordError, never leak struct.error (fuzz-pinned)."""
    end = pos + 4 * count
    if end > len(buf):
        raise RecordError("truncated float field")
    return struct.unpack_from(f"<{count}f", buf, pos), end


def _read_bytes(buf: bytes, pos: int, ln: int) -> tuple[bytes, int]:
    end = pos + ln
    if end > len(buf):
        raise RecordError("truncated bytes field")
    return buf[pos:end], end


def _skip_field(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == 0:
        _, pos = _read_varint(buf, pos)
    elif wire_type == 1:
        pos += 8
    elif wire_type == 2:
        ln, pos = _read_varint(buf, pos)
        pos += ln
    elif wire_type == 5:
        pos += 4
    else:
        raise RecordError(f"unsupported wire type {wire_type}")
    if pos > len(buf):
        raise RecordError("truncated field")
    return pos


# ---------------------------- encode ----------------------------


def _encode_image(rec: ImageRecord) -> bytes:
    out = bytearray()
    for s in rec.shape:
        out.append(0x08)  # field 1, varint
        _write_varint(out, s)
    out.append(0x10)  # field 2, varint
    _write_varint(out, rec.label)
    if rec.pixel:
        out.append(0x1A)  # field 3, bytes
        _write_varint(out, len(rec.pixel))
        out.extend(rec.pixel)
    for f in rec.data:
        out.append(0x25)  # field 4, fixed32
        out.extend(struct.pack("<f", f))
    return bytes(out)


def encode_record(rec: ImageRecord) -> bytes:
    """Serialize Record{type=kSingleLabelImage, image=rec} to proto2 bytes."""
    img = _encode_image(rec)
    out = bytearray()
    out.append(0x08)  # Record.type, field 1 varint
    _write_varint(out, RECORD_TYPE_SINGLE_LABEL_IMAGE)
    out.append(0x12)  # Record.image, field 2 length-delimited
    _write_varint(out, len(img))
    out.extend(img)
    return bytes(out)


# ---------------------------- decode ----------------------------


def _decode_image(buf: bytes) -> ImageRecord:
    rec = ImageRecord()
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if field == 1 and wt == 0:
            v, pos = _read_varint(buf, pos)
            rec.shape.append(_int32(v))
        elif field == 1 and wt == 2:  # packed repeated int32
            ln, pos = _read_varint(buf, pos)
            end = pos + ln
            if end > len(buf):
                raise RecordError("truncated packed field")
            while pos < end:
                v, pos = _read_varint(buf, pos)
                rec.shape.append(_int32(v))
            if pos != end:  # a varint straddled the declared boundary
                raise RecordError("malformed packed field")
        elif field == 2 and wt == 0:
            v, pos = _read_varint(buf, pos)
            rec.label = _int32(v)
        elif field == 3 and wt == 2:
            ln, pos = _read_varint(buf, pos)
            rec.pixel, pos = _read_bytes(buf, pos, ln)
        elif field == 4 and wt == 5:
            vals, pos = _read_f32s(buf, pos, 1)
            rec.data.append(vals[0])
        elif field == 4 and wt == 2:  # packed repeated float
            ln, pos = _read_varint(buf, pos)
            if ln % 4:
                raise RecordError("bad packed float length")
            vals, pos = _read_f32s(buf, pos, ln // 4)
            rec.data.extend(vals)
        else:
            pos = _skip_field(buf, pos, wt)
    return rec


@dataclasses.dataclass
class Datum:
    """Caffe's LMDB record message (the reference converts it to a
    SingleLabelImageRecord in LMDBDataLayer, layer.cc:306-328):

        message Datum { optional int32 channels=1; optional int32 height=2;
          optional int32 width=3; optional bytes data=4; optional int32
          label=5; repeated float float_data=6; optional bool encoded=7; }
    """

    channels: int = 0
    height: int = 0
    width: int = 0
    data: bytes = b""
    label: int = 0
    float_data: list[float] = dataclasses.field(default_factory=list)
    encoded: bool = False


def encode_datum(d: Datum) -> bytes:
    out = bytearray()
    for field, v in ((1, d.channels), (2, d.height), (3, d.width)):
        out.append(field << 3)
        _write_varint(out, v)
    if d.data:
        out.append(0x22)  # field 4, bytes
        _write_varint(out, len(d.data))
        out.extend(d.data)
    out.append(0x28)  # field 5, varint
    _write_varint(out, d.label)
    for f in d.float_data:
        out.append(0x35)  # field 6, fixed32
        out.extend(struct.pack("<f", f))
    if d.encoded:
        out.extend((0x38, 1))
    return bytes(out)


def decode_datum(buf: bytes) -> Datum:
    d = Datum()
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if field in (1, 2, 3, 5, 7) and wt == 0:
            v, pos = _read_varint(buf, pos)
            v = _int32(v)
            if field == 1:
                d.channels = v
            elif field == 2:
                d.height = v
            elif field == 3:
                d.width = v
            elif field == 5:
                d.label = v
            else:
                d.encoded = bool(v)
        elif field == 4 and wt == 2:
            ln, pos = _read_varint(buf, pos)
            d.data, pos = _read_bytes(buf, pos, ln)
        elif field == 6 and wt == 5:
            vals, pos = _read_f32s(buf, pos, 1)
            d.float_data.append(vals[0])
        elif field == 6 and wt == 2:  # packed repeated float
            ln, pos = _read_varint(buf, pos)
            if ln % 4:
                raise RecordError("bad packed float length")
            vals, pos = _read_f32s(buf, pos, ln // 4)
            d.float_data.extend(vals)
        else:
            pos = _skip_field(buf, pos, wt)
    return d


def datum_to_image_record(d: Datum) -> ImageRecord:
    """The reference's Datum -> SingleLabelImageRecord conversion
    (layer.cc:306-328): shape=(C,H,W); raw uint8 ``data`` xor float_data."""
    if d.encoded:
        raise RecordError(
            "encoded (compressed) Datum payloads are unsupported; "
            "re-export the database with raw pixels"
        )
    return ImageRecord(
        shape=[d.channels, d.height, d.width],
        label=d.label,
        pixel=d.data,
        data=list(d.float_data),
    )


def decode_record(buf: bytes) -> ImageRecord:
    """Parse a serialized Record; returns its SingleLabelImageRecord."""
    rtype = RECORD_TYPE_SINGLE_LABEL_IMAGE
    image: ImageRecord | None = None
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if field == 1 and wt == 0:
            rtype, pos = _read_varint(buf, pos)
        elif field == 2 and wt == 2:
            ln, pos = _read_varint(buf, pos)
            sub, pos = _read_bytes(buf, pos, ln)
            image = _decode_image(sub)
        else:
            pos = _skip_field(buf, pos, wt)
    if rtype != RECORD_TYPE_SINGLE_LABEL_IMAGE:
        raise RecordError(f"unsupported Record.type {rtype}")
    if image is None:
        raise RecordError("Record has no image payload")
    return image
