"""Pure-Python LMDB file codec (no liblmdb dependency).

The reference's LMDBDataLayer reads Caffe image databases through liblmdb
(src/worker/layer.cc:237-328). This environment ships no lmdb binding, so
this module implements the LMDB 0.9 on-disk format directly:

* ``LMDBReader`` — a read-only cursor over ``data.mdb``: picks the newest
  valid meta page, walks the main DB's B+tree left-to-right, and yields
  ``(key, value)`` pairs in key order, following big-value overflow chains.
  This is the moral equivalent of ``mdb_cursor_get(MDB_NEXT)`` in the
  reference's cursor wraparound loop (layer.cc:276-303).
* ``write_lmdb`` — a minimal single-transaction writer producing a
  database (leaf + branch + overflow pages, twin meta pages) laid out per
  the LMDB 0.9 format notes below. Verified round-trippable by this
  reader AND by the independent native C++ walker
  (singa_tpu/native/lmdbcodec.cc); compatibility with real liblmdb is by
  construction from the format, NOT verified — no liblmdb exists in this
  image to test against (checked: no system library, no python binding).

Format notes (LMDB 0.9, 64-bit little-endian layout — the only layout the
reference ever ran against):

    page header (16B): pgno u64 | pad u16 | flags u16 | lower u16 | upper u16
                       (overflow pages reuse lower/upper as a u32 page count)
    node (8B hdr):     lo u16 | hi u16 | flags u16 | ksize u16 | key | data
        leaf:   datasize = lo | hi<<16; F_BIGDATA => data is u64 overflow pgno
        branch: child pgno = lo | hi<<16 | flags<<32
    meta (at +16):     magic u32 = 0xBEEFC0DE | version u32 = 1 | address u64
                       | mapsize u64 | MDB_db[2] | last_pg u64 | txnid u64
    MDB_db (48B):      pad u32 | flags u16 | depth u16 | branch u64 | leaf u64
                       | overflow u64 | entries u64 | root u64
    page size lives in mm_dbs[0].md_pad; main DB is mm_dbs[1].
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Iterator

MDB_MAGIC = 0xBEEFC0DE
MDB_VERSION = 1
P_INVALID = (1 << 64) - 1

# page flags
P_BRANCH = 0x01
P_LEAF = 0x02
P_OVERFLOW = 0x04
P_META = 0x08
P_LEAF2 = 0x20
P_SUBP = 0x40

# node flags
F_BIGDATA = 0x01
F_SUBDATA = 0x02
F_DUPDATA = 0x04

PAGEHDRSZ = 16
NODEHDRSZ = 8
METASZ = 4 + 4 + 8 + 8 + 48 * 2 + 8 + 8

_DB = struct.Struct("<IHHQQQQQ")  # MDB_db
_PAGEHDR = struct.Struct("<QHHHH")
_NODEHDR = struct.Struct("<HHHH")


class LMDBError(ValueError):
    pass


def lmdb_data_path(path: str) -> str:
    """Resolve a Caffe-style path: a directory containing data.mdb, or the
    data file itself (MDB_NOSUBDIR)."""
    if os.path.isdir(path):
        return os.path.join(path, "data.mdb")
    return path


class _Meta:
    __slots__ = (
        "psize", "depth", "branch_pages", "leaf_pages",
        "overflow_pages", "entries", "root", "last_pg", "txnid", "flags",
    )


def _parse_meta(buf: bytes, off: int) -> _Meta:
    magic, version = struct.unpack_from("<II", buf, off)
    if magic != MDB_MAGIC:
        raise LMDBError(f"bad meta magic {magic:#x}")
    if version != MDB_VERSION:
        raise LMDBError(f"unsupported LMDB data version {version}")
    m = _Meta()
    # skip address(8) + mapsize(8)
    free = _DB.unpack_from(buf, off + 24)
    main = _DB.unpack_from(buf, off + 24 + 48)
    m.psize = free[0]
    m.flags = main[1]
    m.depth = main[2]
    m.branch_pages = main[3]
    m.leaf_pages = main[4]
    m.overflow_pages = main[5]
    m.entries = main[6]
    m.root = main[7]
    m.last_pg, m.txnid = struct.unpack_from("<QQ", buf, off + 24 + 96)
    return m


class LMDBReader:
    """Sequential (key, value) iteration over an LMDB main database."""

    def __init__(self, path: str):
        self.path = lmdb_data_path(path)
        try:
            self._f = open(self.path, "rb")
        except OSError as e:
            raise LMDBError(f"cannot open LMDB at {path!r}: {e}") from e
        self._size = os.fstat(self._f.fileno()).st_size
        if self._size < 2 * 512:
            raise LMDBError(f"{self.path!r}: too small to be an LMDB file")
        metas = [self._try_meta(0, 0)]
        if metas[0] is not None:
            # meta 1 lives at the page size meta 0 declares
            metas.append(self._try_meta(metas[0].psize, 1))
        else:
            # meta 0 torn: scan plausible OS page sizes for meta 1
            for ps in (4096, 8192, 16384, 32768, 65536):
                m = self._try_meta(ps, 1)
                if m is not None and m.psize == ps:
                    metas.append(m)
                    break
        live = [m for m in metas if m is not None]
        if not live:
            raise LMDBError(f"{self.path!r}: no valid meta page")
        self.meta = max(live, key=lambda m: m.txnid)
        self.psize = self.meta.psize
        if self.psize < 512 or self.psize & (self.psize - 1):
            raise LMDBError(f"{self.path!r}: bad page size {self.psize}")
        self.entries = self.meta.entries
        if self.meta.flags & ~0x08:  # allow MDB_INTEGERKEY-free main dbs only
            raise LMDBError(
                f"{self.path!r}: main DB flags {self.meta.flags:#x} "
                "unsupported (dupsort/sub-databases)"
            )

    # -- low-level --

    def _try_meta(self, off: int, pgno: int) -> _Meta | None:
        """Parse the meta page at byte offset ``off``; None if invalid."""
        if off + PAGEHDRSZ + METASZ > self._size:
            return None
        buf = self._pread(off, PAGEHDRSZ + METASZ)
        hdr = _PAGEHDR.unpack_from(buf, 0)
        if not hdr[2] & P_META:
            return None  # torn/garbage: the twin meta may still be live
        try:
            return _parse_meta(buf, PAGEHDRSZ)
        except LMDBError:
            return None

    def _pread(self, off: int, n: int) -> bytes:
        # bound BEFORE seeking: a corrupt 48-bit page number times the
        # page size can exceed the OS offset range and make seek() raise
        # ValueError (fuzz-pinned) — every out-of-file read must be a
        # clean LMDBError instead
        if off < 0 or off + n > self._size:
            raise LMDBError(f"{self.path!r}: truncated read at {off}")
        self._f.seek(off)
        data = self._f.read(n)
        if len(data) < n:
            raise LMDBError(f"{self.path!r}: truncated read at {off}")
        return data

    def _page(self, pgno: int) -> bytes:
        # _pread holds the single authoritative out-of-file bound
        return self._pread(pgno * self.psize, self.psize)

    def _iter_page(
        self, pgno: int, visits: list[int], depth: int = 0
    ) -> Iterator[tuple[bytes, bytes]]:
        # guard corrupt/crafted B+trees the same way the native walker
        # does (native/lmdbcodec.cc): a depth cap plus a visit budget of
        # one traversal per page in the file, so a branch-page cycle
        # raises LMDBError instead of RecursionError. The budget is local
        # to each __iter__ call (concurrent iterators don't share it).
        if depth > 64:
            raise LMDBError(f"{self.path!r}: corrupt B+tree (depth > 64)")
        visits[0] += 1
        if visits[0] > max(1, self._size // self.psize):
            raise LMDBError(f"{self.path!r}: corrupt B+tree (page cycle)")
        page = self._page(pgno)
        _, _, flags, lower, _ = _PAGEHDR.unpack_from(page, 0)
        if flags & P_LEAF2:
            raise LMDBError("MDB_DUPFIXED leaf2 pages unsupported")
        nkeys = (lower - PAGEHDRSZ) >> 1
        if nkeys < 0 or lower > self.psize:
            raise LMDBError(f"{self.path!r}: corrupt page {pgno}")
        ptrs = struct.unpack_from(f"<{nkeys}H", page, PAGEHDRSZ)

        def node(off: int):
            # node offsets are raw u16s out of a possibly-corrupt page:
            # bound them (and the key bytes they declare) before any
            # unpack so corruption raises LMDBError, not struct.error
            if off < PAGEHDRSZ or off + NODEHDRSZ > self.psize:
                raise LMDBError(
                    f"{self.path!r}: corrupt node offset {off} in page "
                    f"{pgno}"
                )
            return _NODEHDR.unpack_from(page, off)

        if flags & P_BRANCH:
            for off in ptrs:
                lo, hi, nflags, _ = node(off)
                child = lo | (hi << 16) | (nflags << 32)
                yield from self._iter_page(child, visits, depth + 1)
        elif flags & P_LEAF:
            for off in ptrs:
                lo, hi, nflags, ksize = node(off)
                if nflags & (F_SUBDATA | F_DUPDATA):
                    raise LMDBError("dupsort/sub-database nodes unsupported")
                dsize = lo | (hi << 16)
                dstart = off + NODEHDRSZ + ksize
                if dstart > self.psize:
                    raise LMDBError(
                        f"{self.path!r}: corrupt leaf key in page {pgno}"
                    )
                key = page[off + NODEHDRSZ : dstart]
                if nflags & F_BIGDATA:
                    if dstart + 8 > self.psize:
                        raise LMDBError(
                            f"{self.path!r}: corrupt bigdata node in "
                            f"page {pgno}"
                        )
                    (ovpgno,) = struct.unpack_from("<Q", page, dstart)
                    yield key, self._read_overflow(ovpgno, dsize)
                else:
                    if dstart + dsize > self.psize:
                        raise LMDBError(
                            f"{self.path!r}: corrupt leaf value in page "
                            f"{pgno}"
                        )
                    yield key, page[dstart : dstart + dsize]
        else:
            raise LMDBError(
                f"{self.path!r}: page {pgno} has unexpected flags {flags:#x}"
            )

    def _read_overflow(self, pgno: int, size: int) -> bytes:
        hdr = self._pread(pgno * self.psize, PAGEHDRSZ)
        _, _, flags, lower, upper = _PAGEHDR.unpack_from(hdr, 0)
        if not flags & P_OVERFLOW:
            raise LMDBError(f"{self.path!r}: page {pgno} is not overflow")
        npages = lower | (upper << 16)  # pb_pages u32 overlays lower/upper
        if PAGEHDRSZ + size > npages * self.psize:
            raise LMDBError(f"{self.path!r}: overflow chain too short")
        return self._pread(pgno * self.psize + PAGEHDRSZ, size)

    # -- public --

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        if self.meta.root == P_INVALID:
            return
        yield from self._iter_page(self.meta.root, visits=[0])

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------


def _node_bytes(key: bytes, data: bytes, flags: int, dsize: int) -> bytes:
    lo = dsize & 0xFFFF
    hi = dsize >> 16
    if hi > 0xFFFF:
        raise LMDBError(f"value too large ({dsize} bytes)")
    return _NODEHDR.pack(lo, hi, flags, len(key)) + key + data


def write_lmdb(
    path: str,
    items: Iterable[tuple[bytes, bytes]],
    *,
    psize: int = 4096,
    map_size: int | None = None,
    assume_sorted: bool = False,
) -> int:
    """Write ``items`` as a fresh single-transaction LMDB database.

    ``path`` is created as a directory holding ``data.mdb`` + an empty
    ``lock.mdb`` (the layout Caffe and the reference expect). Items must be
    in ascending key order (LMDB's invariant): by default they are
    materialized and sorted here; ``assume_sorted=True`` streams an
    already-ordered iterable with O(page) memory — out-of-order keys raise.
    Pages are emitted to disk in strictly increasing pgno order, so the
    file is written sequentially (metas patched in last); peak memory is
    one page plus the pending branch-level key lists, never the dataset.
    Returns the number of entries.
    """
    if not assume_sorted:
        items = sorted(items, key=lambda kv: kv[0])
    nodemax = ((psize - PAGEHDRSZ) // 2) & ~1
    next_pg = 2  # 0, 1 are metas
    n_overflow = 0

    os.makedirs(path, exist_ok=True)
    data_path = lmdb_data_path(path)
    f = open(data_path, "wb")
    f.write(b"\x00" * (2 * psize))  # meta placeholders, patched at the end

    def alloc(n: int = 1) -> int:
        nonlocal next_pg
        pg = next_pg
        next_pg += n
        return pg

    def write_page(pgno: int, raw: bytes) -> None:
        assert f.tell() == pgno * psize, "pages must stream in pgno order"
        f.write(raw)

    def emit(pgno: int, flags: int, nodes: list[bytes]) -> None:
        ptrs: list[int] = []
        # readers (ours and liblmdb) only follow mp_ptrs, so packing nodes
        # downward from the page top keeps upper/lower honest
        upper = psize
        body = bytearray(psize)
        for node in nodes:
            ln = len(node) + (len(node) & 1)  # keep 2-byte alignment
            upper -= ln
            body[upper : upper + len(node)] = node
            ptrs.append(upper)
        lower = PAGEHDRSZ + 2 * len(nodes)
        if lower > upper:
            raise LMDBError("page overflow during write (internal)")
        _PAGEHDR.pack_into(body, 0, pgno, 0, flags, lower, upper)
        struct.pack_into(f"<{len(ptrs)}H", body, PAGEHDRSZ, *ptrs)
        write_page(pgno, bytes(body))

    # ---- leaves (+ overflow chains) ----
    leaf_entries: list[tuple[bytes, int]] = []  # (first_key, pgno)
    cur_nodes: list[bytes] = []
    cur_first: bytes | None = None
    cur_used = 0

    def flush_leaf() -> None:
        nonlocal cur_nodes, cur_first, cur_used
        if not cur_nodes:
            return
        pg = alloc()
        emit(pg, P_LEAF, cur_nodes)
        leaf_entries.append((cur_first, pg))
        cur_nodes, cur_first, cur_used = [], None, 0

    n_items = 0
    prev_key: bytes | None = None
    for key, val in items:
        if not key or len(key) > 511:
            raise LMDBError(f"bad key length {len(key)}")
        if prev_key is not None and key <= prev_key:
            if key == prev_key:
                raise LMDBError(f"duplicate key {key!r}")
            raise LMDBError(
                f"keys out of order ({key!r} after {prev_key!r})"
            )
        prev_key = key
        n_items += 1
        # big values go to overflow pages; the chain streams out before the
        # node's leaf because leaves are allocated at flush time
        if NODEHDRSZ + len(key) + len(val) > nodemax:
            npg = (PAGEHDRSZ + len(val) + psize - 1) // psize
            ov = alloc(npg)
            n_overflow += npg
            chain = bytearray(npg * psize)
            _PAGEHDR.pack_into(chain, 0, ov, 0, P_OVERFLOW, npg & 0xFFFF,
                               npg >> 16)
            chain[PAGEHDRSZ : PAGEHDRSZ + len(val)] = val
            write_page(ov, bytes(chain))
            node = _node_bytes(key, struct.pack("<Q", ov), F_BIGDATA, len(val))
        else:
            node = _node_bytes(key, val, 0, len(val))
        need = len(node) + (len(node) & 1) + 2
        if cur_nodes and PAGEHDRSZ + cur_used + need > psize:
            flush_leaf()
        if cur_first is None:
            cur_first = key
        cur_nodes.append(node)
        cur_used += need
    flush_leaf()

    # ---- branches ----
    depth = 1 if leaf_entries else 0
    n_branch = 0
    level = leaf_entries
    while len(level) > 1:
        depth += 1
        next_level: list[tuple[bytes, int]] = []
        group: list[bytes] = []
        gfirst: bytes | None = None
        gused = 0

        def flush_branch() -> None:
            nonlocal group, gfirst, gused, n_branch
            if not group:
                return
            pg = alloc()
            emit(pg, P_BRANCH, group)
            n_branch += 1
            next_level.append((gfirst, pg))
            group, gfirst, gused = [], None, 0

        for i, (first_key, child) in enumerate(level):
            key = b"" if not group else first_key
            node = _NODEHDR.pack(
                child & 0xFFFF, (child >> 16) & 0xFFFF, child >> 32, len(key)
            ) + key
            need = len(node) + (len(node) & 1) + 2
            if group and PAGEHDRSZ + gused + need > psize:
                flush_branch()
                key = b""
                node = _NODEHDR.pack(
                    child & 0xFFFF, (child >> 16) & 0xFFFF, child >> 32, 0
                )
                need = len(node) + (len(node) & 1) + 2
            if gfirst is None:
                gfirst = first_key
            group.append(node)
            gused += need
        flush_branch()
        level = next_level

    root = level[0][1] if level else P_INVALID
    last_pg = next_pg - 1 if next_pg > 2 else 1

    # ---- metas (seek back and patch the placeholders) ----
    meta = bytearray(psize)
    free_db = _DB.pack(psize, 0, 0, 0, 0, 0, 0, P_INVALID)
    main_db = _DB.pack(
        0, 0, depth, n_branch, len(leaf_entries), n_overflow, n_items, root
    )
    if map_size is None:
        map_size = max(next_pg * psize, 1 << 20)
    body = struct.pack("<IIQQ", MDB_MAGIC, MDB_VERSION, 0, map_size)
    body += free_db + main_db + struct.pack("<QQ", last_pg, 1)
    for pg in (0, 1):
        _PAGEHDR.pack_into(meta, 0, pg, 0, P_META, 0, 0)
        meta[PAGEHDRSZ : PAGEHDRSZ + len(body)] = body
        f.seek(pg * psize)
        f.write(meta)
    f.close()
    lock = os.path.join(os.path.dirname(data_path), "lock.mdb")
    if not os.path.exists(lock):
        open(lock, "wb").close()
    return n_items
