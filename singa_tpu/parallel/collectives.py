"""Quantized + overlapped gradient collectives (``grad_comm``).

The reference hid gradient-sync cost behind its asynchronous parameter
server (Elastic-SGD / RandomSync over ZeroMQ, src/server/server.cc);
the synchronous GSPMD step instead pays one full-precision gradient
collective at every step end. This module is the trainer-side seam that
attacks that cost with the two levers PAPERS.md names:

**Quantized gradient reduction** (EQuARX, arxiv 2506.17615): each
bucket's gradients are cast to a scaled low-precision wire format —
symmetric int8 (per-bucket max-abs scale) or bf16 — so the value the
data-axis collective moves is a quarter / half the bytes, then
dequantized after the reduction. The compression error is NOT discarded:
with ``error_feedback`` (default on) each param carries a persistent
residual in the buffer pytree (``__gradres__/<param>``), the residual is
re-injected into the next step's gradient before quantization, and the
new residual is the fresh quantization error — the EF-SGD construction
that keeps compressed training converging to the uncompressed optimum.
Residuals thread the jitted step with the other buffers, so they
checkpoint, restore, and roll back with training state for free.

On this repo's CPU-hosted virtual meshes the collectives are emulated
(memcpys), so the quantized path here is the *numerics model* and the
*program seam*: the cast sits exactly where the data-axis reduction
materializes (composing with ``zero_update``'s reduce-scatter layout —
the sharding constraint is applied to the quantized tensor, and the
residuals live shard-local), which is where an XLA with EQuARX-style
quantized collectives picks the wire format up. The convergence harness
(tools/convergence.py ``--grad_comm q8``) validates the numerics end to
end; tools/collective_stall.py gates the machinery's step-time cost.

**Comm/compute overlap** (the async parameter-server heritage, made
synchronous): ``buckets: N`` partitions the params into N groups in
REVERSE topological order — the order backward produces their gradients
— and chains the groups with ``lax.optimization_barrier`` so the lowered
program issues bucket k's reduction before bucket k+1's, instead of
letting the scheduler sink every collective to the step end. On a real
accelerator the latency-hiding scheduler then overlaps bucket k's
collective with bucket k+1's still-running backward segment; bucket
granularity also sets the quantization-scale granularity (one max-abs
scale per bucket; ``buckets: 0`` = one scale per param, no ordering
chain).

``mode: exact`` (the default, also the behavior with no ``grad_comm``
block) is structurally inert: the step traces bitwise-identically to a
config with no block at all.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from ..ops.quantized_collective import (
    dequantize_int8,
    quantize_int8,
    symmetric_scale,
)

#: buffer-pytree namespace for the error-feedback residuals (dunder
#: prefix like the guard counters — never collides with layer buffers,
#: which are namespaced by layer name)
RESIDUAL_PREFIX = "__gradres__/"


def residual_key(name: str) -> str:
    """Buffer key of param ``name``'s error-feedback residual."""
    return RESIDUAL_PREFIX + name


def is_residual_key(key: str) -> bool:
    return key.startswith(RESIDUAL_PREFIX)


@dataclasses.dataclass(frozen=True)
class GradCommSpec:
    """The trainer-facing slice of the ``grad_comm`` config block (plus
    the ``kernels { grad_allreduce }`` wire-implementation knob)."""

    mode: str = "exact"  # "exact" | "quantized"
    dtype: str = "int8"  # wire dtype for quantized mode: "int8" | "bf16"
    error_feedback: bool = True
    buckets: int = 0  # 0/1 = per-param granularity, no ordering chain
    #: how the quantized reduction crosses the data axis: "reference"
    #: (grad_comm's cast around the GSPMD psum — fp32 on the wire, the
    #: bitwise-pinned oracle), "quantized_ring" (the explicit
    #: int8-on-the-wire ppermute ring, ops/quantized_collective.py),
    #: or "q8_hier" (the hierarchical two-level ring: f32 intra-slice,
    #: int8 inter-slice — geometry from the ``ring {}`` fields below)
    wire_impl: str = "reference"
    #: pure-XLA ppermute form (True, the CPU-CI path) vs the fused
    #: Pallas per-hop quantize+accumulate kernel (False, real hardware)
    interpret: bool = True
    #: ``ring {}`` geometry for q8_hier (hier_ring_geometry resolves
    #: these against the mesh): named axes, or the factored data-axis
    #: group width. All empty/0 for the flat impls.
    intra_axis: str = ""
    inter_axis: str = ""
    intra_degree: int = 0

    @property
    def quantized(self) -> bool:
        return self.mode == "quantized"

    @property
    def overlapped(self) -> bool:
        return self.buckets > 1

    @property
    def ring(self) -> bool:
        """Whether the data-axis reduction is an explicit quantized
        ring (int8 bytes in the ppermutes) — flat or hierarchical —
        rather than the reference dequantize-then-psum seam."""
        return self.wire_impl in ("quantized_ring", "q8_hier")

    @property
    def hier(self) -> bool:
        """Whether the ring is the hierarchical two-level form."""
        return self.wire_impl == "q8_hier"

    @property
    def wants_residuals(self) -> bool:
        """Whether the step carries error-feedback residual buffers."""
        return self.quantized and self.error_feedback

    @staticmethod
    def from_config(cfg, kernels=None, ring=None) -> "GradCommSpec | None":
        """-> GradCommSpec, or None when the block is absent OR
        structurally inert (mode exact, no bucketization). Returning
        None for an inert block is the bitwise-exactness guarantee:
        ``grad_comm { mode: exact }`` must trace the identical program
        a config with no block traces — and ``kernels { grad_allreduce:
        reference }`` (the default) changes nothing about it.

        ``kernels`` is the model conf's ``kernels {}`` block; both ring
        impls (``quantized_ring`` flat, ``q8_hier`` hierarchical)
        require an active quantized ``grad_comm`` block (the ring IS
        the quantized collective's wire implementation — with nothing
        quantized there is no wire value to narrow) and raise
        ConfigError without one. ``ring`` is the model conf's
        ``ring {}`` geometry block, carried verbatim for q8_hier (the
        mesh-aware validation lives in ``hier_ring_geometry``)."""
        impl = (
            kernels.grad_allreduce if kernels is not None else "reference"
        )
        interpret = bool(kernels.interpret) if kernels is not None else True
        if impl in ("quantized_ring", "q8_hier") and (
            cfg is None or cfg.mode != "quantized"
        ):
            from ..config.schema import ConfigError

            raise ConfigError(
                f"kernels {{ grad_allreduce: {impl} }} needs an "
                "active grad_comm { mode: quantized } block: the ring is "
                "the quantized collective's wire implementation"
            )
        if cfg is None:
            return None
        spec = GradCommSpec(
            mode=cfg.mode,
            dtype=cfg.dtype,
            error_feedback=bool(cfg.error_feedback),
            buckets=max(0, int(cfg.buckets)),
            wire_impl=impl,
            interpret=interpret,
            intra_axis=(
                ring.intra_axis if ring is not None else ""
            ),
            inter_axis=(
                ring.inter_axis if ring is not None else ""
            ),
            intra_degree=(
                max(0, int(ring.intra_degree)) if ring is not None else 0
            ),
        )
        if not spec.quantized and not spec.overlapped:
            return None
        return spec


def apply_grad_comm_tag(cfg, tag: str):
    """CLI shorthand -> ``cfg.grad_comm`` (sweep / convergence / bench):
    ``q8`` = quantized int8 + error feedback, ``bf16`` = quantized bf16,
    ``q8wire`` = q8 with the int8-on-the-wire ring collective
    (``kernels { grad_allreduce: quantized_ring }``), ``q8hier`` = q8
    with the hierarchical two-level ring (``q8_hier`` + a factored
    ``ring { intra_degree: 2 }`` when the conf declares no geometry),
    ``exact`` = an explicit (inert) exact block, "" = leave
    untouched."""
    if not tag:
        return cfg
    from ..config.schema import GradCommConfig, KernelsConfig, RingConfig

    gc = GradCommConfig()
    if tag == "exact":
        gc.mode = "exact"
    elif tag in ("q8", "q8wire", "q8hier"):
        gc.mode, gc.dtype = "quantized", "int8"
    elif tag == "bf16":
        gc.mode, gc.dtype = "quantized", "bf16"
    else:
        raise ValueError(
            f"unknown grad_comm tag {tag!r} (choose exact, q8, q8wire, "
            "q8hier, bf16)"
        )
    cfg.grad_comm = gc
    if tag in ("q8wire", "q8hier"):
        kern = cfg.kernels if cfg.kernels is not None else KernelsConfig()
        kern.grad_allreduce = (
            "q8_hier" if tag == "q8hier" else "quantized_ring"
        )
        cfg.kernels = kern
    if tag == "q8hier" and cfg.ring is None:
        ring = RingConfig()
        ring.intra_degree = 2
        cfg.ring = ring
    return cfg


def init_residuals(params: dict, spec: GradCommSpec | None) -> dict:
    """Fresh zero residuals (STORED shapes — grads of padded params are
    padded) for every param, keyed by ``residual_key``. Empty when the
    spec carries none."""
    if spec is None or not spec.wants_residuals:
        return {}
    return {
        residual_key(n): jnp.zeros(v.shape, dtype=jnp.float32)
        for n, v in params.items()
    }


def reverse_topo_buckets(
    net, names: frozenset, nbuckets: int, specs: dict
) -> tuple[tuple[str, ...], ...]:
    """Partition ``names`` into reduction buckets in REVERSE topological
    layer order — the order the backward pass produces their gradients,
    so the bucket chain's issue order matches gradient readiness.

    ``nbuckets <= 1`` yields one bucket per param (per-param
    quantization scale, no ordering chain); otherwise at most
    ``nbuckets`` contiguous groups, greedily balanced by element count
    (``specs`` supplies the shapes). Every name appears exactly once.
    """
    ordered: list[str] = []
    seen: set[str] = set()
    for layer in reversed(net.layers):
        for n in layer.param_specs():
            if n in names and n not in seen:
                seen.add(n)
                ordered.append(n)
    # grads for params no layer declares (defensive): stable tail
    ordered.extend(sorted(names - seen))
    if nbuckets <= 1:
        return tuple((n,) for n in ordered)
    sizes = {
        n: max(1, int(functools.reduce(
            lambda a, b: a * b, specs[n].shape, 1
        ))) if n in specs else 1
        for n in ordered
    }
    total = sum(sizes[n] for n in ordered)
    target = total / nbuckets
    out: list[tuple[str, ...]] = []
    cur: list[str] = []
    acc = 0
    for n in ordered:
        cur.append(n)
        acc += sizes[n]
        # close the bucket once it reaches its share — unless closing
        # would leave more names than remaining buckets can hold
        if acc >= target and len(out) < nbuckets - 1:
            out.append(tuple(cur))
            cur, acc = [], 0
    if cur:
        out.append(tuple(cur))
    return tuple(out)


def _chain(gs: dict, token):
    """Pin this bucket's ops after ``token`` (one reduced array from the
    previous bucket): ``optimization_barrier`` is a value-identity that
    adds a scheduling edge, keeping the lowered collectives in
    reverse-topo issue order — bucket k's reduction can run while bucket
    k+1's backward segment is still computing, instead of every
    collective sinking to the step end."""
    if token is None:
        return gs
    names = list(gs)
    fused = jax.lax.optimization_barrier(
        tuple(gs[n] for n in names) + (token,)
    )
    return dict(zip(names, fused[:-1]))


def _bucket_scale(es: dict) -> jnp.ndarray:
    """One symmetric int8 scale for the bucket — the shared
    ``symmetric_scale`` helper (ops/quantized_collective.py), so the
    reference path and the quantized ring consult ONE formula: max-abs
    over every gradient, floored away from zero (max is exactly
    associative, so the scale is bitwise-independent of layout; NaN/Inf
    gradients poison it, the guard contract)."""
    return symmetric_scale(es.values())


def reduce_gradients(
    grads: dict,
    buffers: dict,
    spec: GradCommSpec,
    buckets: tuple[tuple[str, ...], ...],
    constrain,
) -> tuple[dict, dict]:
    """The grad_comm reduction: -> (update-ready grads, residual-buffer
    updates).

    Per bucket, in reverse-topo order: re-inject the error-feedback
    residuals, cast to the wire dtype (int8 with the bucket's max-abs
    scale, or bf16), apply ``constrain(name, arr)`` — the trainer's
    per-tensor data-axis reduction layout (zero_update's reduce-scatter
    constraint, identity for the replicated update) — ON THE QUANTIZED
    TENSOR, dequantize, and bank the fresh quantization error as the
    next step's residual. A NaN/Inf gradient poisons its bucket's scale
    and survives dequantization as NaN, so the divergence guard's
    verdict over the dequantized grads still fires.

    ``mode: exact`` never reaches here bucketed with buckets <= 1 (the
    spec is inert then); with buckets > 1 the buckets only carry the
    ordering chain — the values are untouched.
    """
    out: dict = {}
    new_res: dict = {}
    token = None
    for bucket in buckets:
        gs = _chain({n: grads[n] for n in bucket}, token)
        if not spec.quantized:
            for n, g in gs.items():
                out[n] = constrain(n, g)
        else:
            es = {}
            for n, g in gs.items():
                r = (
                    buffers.get(residual_key(n))
                    if spec.error_feedback
                    else None
                )
                es[n] = g if r is None else g + r.astype(g.dtype)
            scale = _bucket_scale(es) if spec.dtype == "int8" else None
            for n, e in es.items():
                if spec.dtype == "int8":
                    q = quantize_int8(e, scale)
                    ghat = dequantize_int8(
                        constrain(n, q), scale
                    ).astype(e.dtype)
                else:  # bf16
                    ghat = constrain(
                        n, e.astype(jnp.bfloat16)
                    ).astype(e.dtype)
                if spec.error_feedback:
                    new_res[residual_key(n)] = (
                        e.astype(jnp.float32) - ghat.astype(jnp.float32)
                    )
                out[n] = ghat
        if spec.overlapped:
            # the ordering chain exists only in bucketized mode —
            # buckets <= 1 is per-param granularity with NO chain (the
            # documented contract), leaving the scheduler free
            token = out[bucket[0]]
    return out, new_res
