"""Partition semantics -> GSPMD sharding annotations.

The reference's partitioner rewrites the layer graph: kDataPartition splits
every blob's batch dim 0, kLayerPartition splits the neuron dim 1, and
Slice/Concate/Split/Bridge connectors plus ZeroMQ shuffles move the pieces
(src/worker/neuralnet.cc:198-323, partition_dimension at
base_layer.h:121-128). Here the graph is left untouched; the same semantics
are expressed as shardings on the jitted step's inputs:

  kDataPartition  -> batch arrays sharded over the data axis; params
                     replicated; XLA psums grads (= ParamSync, replacing
                     param_manager.cc:160-231).
  kLayerPartition -> each param sharded over the model axis along its
                     declared ``neuron_axis``; XLA's propagation pass then
                     shards the matching activations and inserts exactly the
                     slice/concat/shuffle collectives the reference built by
                     hand ("the most complex scenario", neuralnet.cc:265-280).

The reference gives the last partition any remainder (neuralnet.cc:160-162);
XLA shards evenly, so an indivisible neuron dim pads its STORED array up to
the next multiple (see _param_layout), and an indivisible expert count falls
back to replication (documented divergence, SURVEY hard-part #3). Both
fallbacks announce themselves via ``warnings.warn`` and are surfaced
statically by netlint as SHD001 (``python -m singa_tpu.tools.lint``).
"""

from __future__ import annotations

import warnings

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graph.builder import Net
from .mesh import DATA_AXIS, MODEL_AXIS


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_shardings(mesh: Mesh, net: Net) -> dict:
    """Sharding pytree for the step's batch input: every array in every
    data layer's feed dict is sharded on dim 0 over the data axis. Token
    feeds additionally shard their sequence dim over the seq axis when
    the mesh has one (sequence parallelism — ring attention then keeps
    K/V sharded end to end)."""
    leaf = NamedSharding(mesh, P(DATA_AXIS))
    nseq = dict(mesh.shape).get("seq", 1)
    out = {}
    for layer in net.datalayers:
        img = leaf
        if nseq > 1 and layer.TYPE == "kSequenceData":
            img = NamedSharding(mesh, P(DATA_AXIS, "seq"))
        out[layer.name] = {"image": img, "label": leaf}
    return out


def _param_layout(mesh: Mesh, net: Net, *, warn: bool = False):
    """-> iterator of (name, spec, sharded_axis | None, pad).

    ``sharded_axis`` is the param dim sharded over a mesh axis (with the
    axis name), ``pad`` the extra length the STORED array needs on that
    dim so jax's even-shard requirement holds. kLayerPartition neuron
    dims honor the reference's uneven-partition contract by
    pad-to-multiple (the reference gives the last partition the
    remainder, neuralnet.cc:160-162; padding the last shard is the GSPMD
    expression of the same split — Net.forward slices the tail back off
    before any layer sees it). Expert axes never pad: a phantom expert
    would need routing masks, so indivisible expert counts replicate.
    """
    nmodel = mesh.shape[MODEL_AXIS]
    nexpert = dict(mesh.shape).get("expert", 1)
    for layer in net.layers:
        for name, spec in layer.param_specs().items():
            if (
                layer.partition_dim == 1
                and spec.neuron_axis is not None
                and nmodel > 1
            ):
                d = spec.shape[spec.neuron_axis]
                pad = -d % nmodel
                if pad and warn:
                    # lint surfaces the same condition statically (SHD001)
                    warnings.warn(
                        f"layer {layer.name!r}: kLayerPartition dim "
                        f"{spec.neuron_axis} of param {name!r} (size {d}) "
                        f"is not divisible by the model axis ({nmodel}); "
                        f"storage pads to {d + pad}",
                        stacklevel=3,
                    )
                yield name, spec, (spec.neuron_axis, MODEL_AXIS), pad
            elif spec.expert_axis is not None and nexpert > 1:
                if spec.shape[spec.expert_axis] % nexpert:
                    if warn:
                        warnings.warn(
                            f"layer {layer.name!r}: expert dim "
                            f"{spec.expert_axis} of param {name!r} (size "
                            f"{spec.shape[spec.expert_axis]}) is not "
                            f"divisible by the expert axis ({nexpert}); "
                            "falling back to replication",
                            stacklevel=3,
                        )
                    yield name, spec, None, 0
                else:
                    # kMoE expert weights split over the expert axis
                    # regardless of partition_type — expert parallelism is
                    # the layer's intrinsic layout, not a net-wide choice
                    yield name, spec, (spec.expert_axis, "expert"), 0
            else:
                yield name, spec, None, 0


def param_shardings(mesh: Mesh, net: Net) -> dict[str, NamedSharding]:
    """Per-param shardings implementing the layer's partition_type.

    Only layers whose partition_dim is 1 (kLayerPartition) shard their
    params, along each param's neuron_axis; everything else replicates
    (data-parallel grads sync via psum, which GSPMD inserts because the
    loss is a mean over the sharded batch dim). Indivisible neuron dims
    are still sharded — the trainer pads their storage (see
    param_paddings / _param_layout).
    """
    out: dict[str, NamedSharding] = {}
    for name, spec, sharded, _pad in _param_layout(mesh, net, warn=True):
        if sharded is None:
            out[name] = replicated(mesh)
        else:
            dim, axis = sharded
            axes: list = [None] * len(spec.shape)
            axes[dim] = axis
            out[name] = NamedSharding(mesh, P(*axes))
    return out


def param_paddings(mesh: Mesh, net: Net) -> dict[str, tuple]:
    """{name: np.pad-style widths} for params whose STORED array must be
    longer than the logical shape (indivisible kLayerPartition dims).
    Only padded params appear. The logical shape stays spec.shape;
    Net.forward slices the stored array back down before layers see it.
    """
    out: dict[str, tuple] = {}
    for name, spec, sharded, pad in _param_layout(mesh, net):
        if pad:
            dim = sharded[0]
            widths = [(0, 0)] * len(spec.shape)
            widths[dim] = (0, pad)
            out[name] = tuple(widths)
    return out


def zero_update_shardings(
    mesh: Mesh,
    net: Net,
    param_sh: dict[str, NamedSharding],
    *,
    warn: bool = False,
) -> dict[str, NamedSharding]:
    """ZeRO-style UPDATE layout (PAPERS.md arxiv 2004.13336): each
    param's forward sharding plus the data axis on the first
    still-replicated dim the data-parallel degree divides evenly.

    Constraining grads to this layout makes GSPMD lower the data-axis
    grad sync to a reduce-scatter (each rank receives only its shard's
    sum); updater slots STORED in it shrink per-device by the data
    width; constraining the fresh params back to their forward
    shardings after the update is the allgather. This composes with
    the existing fallbacks: dims padded for an indivisible model axis
    use their STORED (padded) length, and a param with no evenly
    divisible free dim keeps its forward sharding — its update stays
    replicated, the same replicate fallback as indivisible expert
    counts, announced via ``warnings.warn`` when ``warn``.
    """
    ndata = mesh.shape[DATA_AXIS]
    out: dict[str, NamedSharding] = {}
    for name, spec, sharded, pad in _param_layout(mesh, net):
        shape = list(spec.shape)
        if pad:
            shape[sharded[0]] += pad
        axes = list(tuple(param_sh[name].spec))
        axes += [None] * (len(shape) - len(axes))
        dim = None
        if ndata > 1:
            dim = next(
                (
                    d
                    for d, size in enumerate(shape)
                    if axes[d] is None and size and size % ndata == 0
                ),
                None,
            )
        if dim is None:
            if ndata > 1 and warn:
                warnings.warn(
                    f"zero_update: no free dim of param {name!r} (stored "
                    f"shape {tuple(shape)}) is divisible by the data axis "
                    f"({ndata}); its update stays replicated",
                    stacklevel=3,
                )
            out[name] = param_sh[name]
        else:
            axes[dim] = DATA_AXIS
            out[name] = NamedSharding(mesh, P(*axes))
    return out


def serving_kv_shardings(
    mesh: Mesh, n_heads: int, *, warn: bool = False
) -> tuple[NamedSharding, NamedSharding]:
    """-> (pool_sharding, state_sharding) for the serving engine's paged
    KV state (serve/engine.py).

    The pools are ``(n_blocks, heads, block_len, head_dim)``: the heads
    dim shards over the ``model`` axis when it divides evenly — the
    serving analog of kLayerPartition (each model shard holds its
    heads' K/V, attention contracts locally, GSPMD reassembles the
    output exactly as it does for the TP projections) — else the pool
    replicates, announced like every other indivisible-dim fallback.
    The block dim NEVER shards: block ids are a global namespace the
    host allocator hands out, and a table must be resolvable on every
    shard. Slot-lane state (tokens/pos/live/rng/tables) is tiny and
    always replicates."""
    repl = replicated(mesh)
    nmodel = dict(mesh.shape).get(MODEL_AXIS, 1)
    if nmodel <= 1:
        return repl, repl
    if n_heads % nmodel:
        if warn:
            warnings.warn(
                f"serving: n_heads {n_heads} not divisible by the model "
                f"axis ({nmodel}); KV pools fall back to replication",
                stacklevel=2,
            )
        return repl, repl
    return NamedSharding(mesh, P(None, MODEL_AXIS, None, None)), repl


def state_shardings(
    param_sh: dict[str, NamedSharding],
    slots: tuple[str, ...],
    update_sh: dict[str, NamedSharding] | None = None,
) -> dict[str, dict[str, NamedSharding]]:
    """Updater slots (history/update) mirror their param's sharding, like
    the reference keeps history blobs beside data blobs (param.h:136).
    Under ``zero_update`` the slots follow the UPDATE layout instead
    (``update_sh`` from zero_update_shardings) — each rank holds only
    its shard of the optimizer state, the per-device shrink that is the
    point of ZeRO."""
    src = update_sh if update_sh is not None else param_sh
    return {name: {s: sh for s in slots} for name, sh in src.items()}
