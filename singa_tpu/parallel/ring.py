"""Ring attention: sequence/context parallelism over the device ring.

The reference has no sequence dimension anywhere (SURVEY §5: pre-
transformer system), but its generic partition machinery (kLayerPartition
slicing an arbitrary dim, src/worker/neuralnet.cc:198-323) is the
structural seam SURVEY identifies for sequence-dim sharding. This module
is that seam made real, TPU-native: Q/K/V live sequence-sharded across a
mesh axis; each chip computes attention for its local query block while
K/V shards rotate around the ring via ``lax.ppermute`` (one ICI hop per
step, compute overlapping communication under XLA's scheduler), folding
each visiting block into flash-style online-softmax statistics
(singa_tpu/ops/attention.py). No chip ever holds the full sequence or an
S x S score matrix, so max context length scales linearly with ring size.

Causal masking stays exact under rotation: each shard knows its global
offset from ``lax.axis_index``, so a visiting K block is masked by global
positions, and fully-masked visits contribute nothing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.quantized_collective import shard_map

from ..ops.attention import (
    block_attn_finish,
    block_attn_init,
    block_attn_update,
)

SEQ_AXIS = "seq"


def build_sp_mesh(ndata: int = 1, nseq: int = 1, devices=None) -> Mesh:
    """A (data, seq) mesh: batch shards over data, sequence over seq.

    The seq axis is innermost so the K/V ring rides neighboring devices
    (fastest ICI hops), like the model axis in build_mesh."""
    from .mesh import axis_pair_mesh

    return axis_pair_mesh(ndata, nseq, SEQ_AXIS, devices, "sp mesh")


def _ring_attn_local(q, k, v, *, axis_name: str, causal: bool):
    """Per-shard ring attention body (runs under shard_map).

    q/k/v: (batch_local, heads, seq_local, head_dim)."""
    nshards = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    in_dtype = q.dtype
    # accumulate flash statistics in fp32 (matching the Pallas kernel's
    # upcast) — bf16 exp-sums folded across many ring steps drift; K/V
    # stay in the input dtype so ring traffic is not inflated
    q32 = q.astype(jnp.float32)
    out, m, l = block_attn_init(q32)

    def step(i, carry):
        out, m, l, k, v = carry
        # the K/V block visiting at step i originated on shard (my - i)
        src = (my - i) % nshards
        out, m, l = block_attn_update(
            q32, k.astype(jnp.float32), v.astype(jnp.float32), out, m, l,
            q_offset=my * s_local,
            k_offset=src * s_local,
            causal=causal,
        )
        # rotate K/V one hop around the ring: shard j's block moves to
        # shard j+1, so the next visitor originated one shard earlier
        perm = [(j, (j + 1) % nshards) for j in range(nshards)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return out, m, l, k, v

    out, m, l, k, v = jax.lax.fori_loop(
        0, nshards, step, (out, m, l, k, v)
    )
    return block_attn_finish(out, m, l).astype(in_dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    causal: bool = False,
    axis: str = SEQ_AXIS,
) -> jnp.ndarray:
    """Sequence-parallel attention over ``mesh``'s ``axis``.

    Inputs/outputs are global (batch, heads, seq, head_dim) arrays whose
    seq dim is (or becomes) sharded over ``axis``; batch rides any "data"
    axis the mesh has. Differentiable: autodiff traces back through the
    ppermute rotations, so grads flow with the same ring traffic pattern.
    """
    if dict(mesh.shape).get(axis, 1) == 1:
        from ..ops.attention import attention

        return attention(q, k, v, causal=causal)
    data = "data" if "data" in mesh.shape else None
    spec = P(data, None, axis, None)
    fn = shard_map(
        functools.partial(
            _ring_attn_local, axis_name=axis, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
