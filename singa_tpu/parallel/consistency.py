"""Async consistency protocols (EASGD / RandomSync / hogwild), TPU-native.

The reference trains one model replica per worker group and reconciles the
replicas through a ZeroMQ parameter server running one of two protocols
(src/utils/param.cc:100-256), throttled by a bandwidth-adaptive sample
ratio (src/worker/param_manager.cc:85-93) on the SyncNow cadence
(param_manager.cc:155-159). Here the server tier dissolves: replicas live
on a leading array axis sharded over the mesh's data axis, and each
protocol becomes a pure, jit-compiled transform over that axis. The
server processed worker messages serially under a per-param lock
(src/server/server.cc:110-143), so the faithful equivalent is a
`lax.scan` over replicas with the server ("center") pytree as carry —
order-dependent exactly like the reference, but one XLA program instead
of a message storm.

Protocols (semantics pinned by tests/test_consistency.py):

- **Elastic (EASGD)** — worker ships its full vector w with moving rate
  alpha; the server computes diff = alpha*(w - s), absorbs it (s += diff)
  and returns diff; the worker subtracts it (w -= diff)
  (param.cc:216-256).
- **RandomSync** — the worker samples floor(ratio*n) coordinates without
  replacement (reservoir-style, param.cc:101-110; distributionally
  equivalent sampling here), ships delta = w[idx] - snapshot[idx]; the
  server adds each delta and returns its *pre-update* value old;
  the worker reconciles w[idx] = old + delta and refreshes the snapshot
  (param.cc:112-196).
- **hogwild** (UpdaterProto.hogwild, model.proto:316) was *intra-process*
  lock-free sharing among executor threads. It has no TPU counterpart by
  design: one XLA program already saturates a chip, so the
  `nthreads_per_procs` replicas collapse into the batch dimension (see
  singa_tpu/parallel/mesh.py). The flag is parsed and ignored.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def sync_now(step: int, sync_frequency: int, warmup_steps: int) -> bool:
    """ParamManager::SyncNow (reference: param_manager.cc:155-159): every
    ``sync_frequency`` steps once past warmup. ``step`` is the step just
    completed."""
    return (
        sync_frequency > 0
        and (step + 1) % sync_frequency == 0
        and step > warmup_steps
    )


def sync_ratio(
    compute_time_s: float,
    model_mb: float,
    nworkers: int,
    nservers: int,
    bandwidth_mbps: float,
) -> float:
    """ParamManager::SyncConfig (reference: param_manager.cc:85-93): the
    bandwidth-adaptive RandomSync sample ratio. The cluster can absorb
    ``bandwidth * nservers`` MB/s of sync traffic; the workers produce
    ``model_mb * nworkers / compute_time`` MB/s; the ratio of the two is
    the fraction of coordinates each sync can afford, clamped to 1."""
    if compute_time_s <= 0 or model_mb <= 0:
        return 1.0
    produced = model_mb * nworkers / compute_time_s
    ratio = bandwidth_mbps * max(nservers, 1) / produced
    return float(min(ratio, 1.0))


def elastic_sync(replicas, center, alpha: float):
    """One EASGD round: every replica syncs with the center, serially.

    ``replicas`` is a pytree whose leaves carry a leading replica axis;
    ``center`` the matching server pytree. Returns (replicas, center).
    Matches ElasticParam::{GenSyncMsgFromWorker,HandleSyncMsg,
    ParseSyncMsgFromPS} (reference: src/utils/param.cc:216-256): for each
    replica in turn, diff = alpha*(w - s); s += diff; w -= diff.
    """

    def one(c, w):
        diff = jax.tree.map(lambda wi, ci: alpha * (wi - ci), w, c)
        c = jax.tree.map(jnp.add, c, diff)
        w = jax.tree.map(jnp.subtract, w, diff)
        return c, w

    center, replicas = jax.lax.scan(one, center, replicas)
    return replicas, center


def random_sync(replicas, snapshots, center, indices):
    """One RandomSync round over sampled coordinates, serially per replica.

    ``indices`` maps param name -> int32 (nreplicas, m) of flat coordinate
    indices (unique within each row). Per replica i and param (reference:
    src/utils/param.cc:112-196):

        delta = w[idx] - snapshot[idx]        (GenSyncMsgFromWorker)
        old   = s[idx];  s[idx] += delta      (HandleSyncMsg)
        w[idx] = old + delta;  snapshot[idx] = w[idx]   (ParseSyncMsgFromPS)

    so each replica absorbs exactly the other replicas' deltas that reached
    the server before its own message. Returns (replicas, snapshots, center).
    """

    def one(c, xs):
        w, snap, idx = xs
        new_w, new_snap = {}, {}
        for name in w:
            shape = w[name].shape
            wf = w[name].ravel()
            sf = snap[name].ravel()
            cf = c[name].ravel()
            ix = idx[name]
            delta = wf[ix] - sf[ix]
            old = cf[ix]
            cf = cf.at[ix].add(delta)
            new_vals = old + delta
            wf = wf.at[ix].set(new_vals)
            sf = sf.at[ix].set(new_vals)
            c[name] = cf.reshape(shape)
            new_w[name] = wf.reshape(shape)
            new_snap[name] = sf.reshape(shape)
        return dict(c), (new_w, new_snap)

    center, (replicas, snapshots) = jax.lax.scan(
        one, dict(center), (replicas, snapshots, indices)
    )
    return replicas, snapshots, center


def sample_sync_indices(
    rng: np.random.RandomState,
    shapes: dict[str, tuple],
    nreplicas: int,
    ratio: float,
) -> dict[str, np.ndarray]:
    """Host-side coordinate sampling for one RandomSync round.

    Each replica draws its own coordinates (the reference seeds per-worker
    from the wall clock, param.cc:146; parity is distributional). The
    sample count m = floor(ratio*n) — the reference's float-to-int
    truncation of data_.count()*sample_ratio (param.cc:148) — is static
    per param so the jitted sync retraces only when the ratio changes
    (it is fixed after warmup).
    """
    out: dict[str, np.ndarray] = {}
    for name, shape in shapes.items():
        n = int(np.prod(shape))
        if ratio >= 1.0:
            # every coordinate: the sorted sample IS arange — skip the
            # O(n) reject-sampling draw per replica (measured 5ms/round
            # on the MLP, pure overhead at full ratio)
            out[name] = np.broadcast_to(
                np.arange(n, dtype=np.int32), (nreplicas, n)
            )
            continue
        m = max(1, int(n * ratio))
        rows = [
            np.sort(rng.choice(n, size=m, replace=False))
            for _ in range(nreplicas)
        ]
        out[name] = np.stack(rows).astype(np.int32)
    return out
