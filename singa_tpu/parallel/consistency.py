"""Async consistency protocols (EASGD / RandomSync / hogwild), TPU-native.

The reference trains one model replica per worker group and reconciles the
replicas through a ZeroMQ parameter server running one of two protocols
(src/utils/param.cc:100-256), throttled by a bandwidth-adaptive sample
ratio (src/worker/param_manager.cc:85-93) on the SyncNow cadence
(param_manager.cc:155-159). Here the server tier dissolves: replicas live
on a leading array axis sharded over the mesh's data axis, and each
protocol becomes a pure, jit-compiled transform over that axis. The
server processed worker messages serially under a per-param lock
(src/server/server.cc:110-143), so the faithful equivalent is a
`lax.scan` over replicas with the server ("center") pytree as carry —
order-dependent exactly like the reference, but one XLA program instead
of a message storm.

Protocols (semantics pinned by tests/test_consistency.py):

- **Elastic (EASGD)** — worker ships its full vector w with moving rate
  alpha; the server computes diff = alpha*(w - s), absorbs it (s += diff)
  and returns diff; the worker subtracts it (w -= diff)
  (param.cc:216-256).
- **RandomSync** — the worker samples floor(ratio*n) coordinates without
  replacement (reservoir-style, param.cc:101-110; distributionally
  equivalent sampling here), ships delta = w[idx] - snapshot[idx]; the
  server adds each delta and returns its *pre-update* value old;
  the worker reconciles w[idx] = old + delta and refreshes the snapshot
  (param.cc:112-196).
- **hogwild** (UpdaterProto.hogwild, model.proto:316) was *intra-process*
  lock-free sharing among executor threads. It has no TPU counterpart by
  design: one XLA program already saturates a chip, so the
  `nthreads_per_procs` replicas collapse into the batch dimension (see
  singa_tpu/parallel/mesh.py). The flag is parsed and ignored.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

#: partial-coverage dense-prefix budget, in ELEMENTS of the (R, n) delta
#: field (fp32 => x4 bytes). Above this, random_sync uses the serial-scan
#: formulation whose peak transient is the (R, m) sampled field itself —
#: the dense field is never built. Read ONCE at import (a trace-time env
#: read would leave stale jit caches when the var changes mid-process).
DENSE_PREFIX_MAX_ELEMS = int(
    os.environ.get("SINGA_TPU_RS_DENSE_ELEMS", 64 * 1024 * 1024)
)


def sync_now(step: int, sync_frequency: int, warmup_steps: int) -> bool:
    """ParamManager::SyncNow (reference: param_manager.cc:155-159): every
    ``sync_frequency`` steps once past warmup. ``step`` is the step just
    completed."""
    return (
        sync_frequency > 0
        and (step + 1) % sync_frequency == 0
        and step > warmup_steps
    )


def sync_ratio(
    compute_time_s: float,
    model_mb: float,
    nworkers: int,
    nservers: int,
    bandwidth_mbps: float,
) -> float:
    """ParamManager::SyncConfig (reference: param_manager.cc:85-93): the
    bandwidth-adaptive RandomSync sample ratio. The cluster can absorb
    ``bandwidth * nservers`` MB/s of sync traffic; the workers produce
    ``model_mb * nworkers / compute_time`` MB/s; the ratio of the two is
    the fraction of coordinates each sync can afford, clamped to 1."""
    if compute_time_s <= 0 or model_mb <= 0:
        return 1.0
    produced = model_mb * nworkers / compute_time_s
    ratio = bandwidth_mbps * max(nservers, 1) / produced
    return float(min(ratio, 1.0))


def elastic_sync(replicas, center, alpha: float):
    """One EASGD round: every replica syncs with the center, serially.

    ``replicas`` is a pytree whose leaves carry a leading replica axis;
    ``center`` the matching server pytree. Returns (replicas, center).
    Matches ElasticParam::{GenSyncMsgFromWorker,HandleSyncMsg,
    ParseSyncMsgFromPS} (reference: src/utils/param.cc:216-256): for each
    replica in turn, diff = alpha*(w - s); s += diff; w -= diff.
    """

    def one(c, w):
        diff = jax.tree.map(lambda wi, ci: alpha * (wi - ci), w, c)
        c = jax.tree.map(jnp.add, c, diff)
        w = jax.tree.map(jnp.subtract, w, diff)
        return c, w

    center, replicas = jax.lax.scan(one, center, replicas)
    return replicas, center


def random_sync(replicas, snapshots, center, indices, full_coverage=False):
    """One RandomSync round over sampled coordinates.

    ``indices`` maps param name -> int32 (nreplicas, m) of flat coordinate
    indices (unique within each row). Per replica i and param (reference:
    src/utils/param.cc:112-196):

        delta = w[idx] - snapshot[idx]        (GenSyncMsgFromWorker)
        old   = s[idx];  s[idx] += delta      (HandleSyncMsg)
        w[idx] = old + delta;  snapshot[idx] = w[idx]   (ParseSyncMsgFromPS)

    so each replica absorbs exactly the other replicas' deltas that
    reached the server before its own message.

    **The serial server loop is a prefix sum in disguise** (the r4
    decision VERDICT r3 #8 asked for): at any coordinate x, replica i's
    new value is c0[x] + sum_{j<=i, x in idx_j} delta_j[x] and the final
    center is c0 + the full sum — an associative prefix over the replica
    axis. This computes it with one batched scatter + jnp.cumsum instead
    of the r3 lax.scan whose serial gather/scatter rounds cost 3.1x the
    sync engine at 8 replicas (BASELINE.md r3 replica table). The
    arrival order is fixed at 0..R-1 — the reference's order was
    whatever ZMQ delivered, so this is as valid an execution as any, and
    it matches the previous scan's order exactly (differences vs the
    serial form are only the summation tree's fp rounding).
    Transient memory is O(R * n) per param for the dense delta field.

    ``full_coverage=True`` is the ratio>=1.0 fast path: the CALLER
    asserts every replica syncs every coordinate (sample_sync_indices
    emits arange rows there), so the scatter/gather is skipped entirely
    and ``indices`` may be None. Passing partial indices with this flag
    would silently sync everything — it is a contract, not a checked
    argument (the only caller, trainer/replica.py, derives it from the
    static sample_ratio).

    **Memory bound (r5):** the partial-coverage dense path materializes
    an (R, n) delta field — at the flagship's 18.8M params x 8 replicas
    a ~600 MB fp32 transient. When R*n exceeds DENSE_PREFIX_MAX_ELEMS
    (default 64M elements = 256 MB fp32; SINGA_TPU_RS_DENSE_ELEMS, read
    once at import) the round instead runs the serial-scan formulation
    — the reference's own per-replica server loop — whose peak
    transient is the (R, m) sampled field plus one O(n) carry: the
    dense field is never built. Both compute identical values (scan ==
    prefix by associativity; the oracle test covers each). At the
    protocol's real operating point (small ratio, param.cc:148) the
    scan also does strictly less work: O(R*m) touched coordinates vs
    the prefix's O(R*n) cumsum.

    Returns (replicas, snapshots, center).
    """
    new_r, new_s, new_c = {}, {}, {}
    for name in center:
        shape = replicas[name].shape
        R = shape[0]
        n = center[name].size
        w = replicas[name].reshape(R, n)
        snap = snapshots[name].reshape(R, n)
        c0 = center[name].ravel()
        if full_coverage:
            dense = w - snap  # delta at every coordinate
            prefix = jnp.cumsum(dense, axis=0)
            new_vals = c0[None, :] + prefix
            new_r[name] = new_vals.reshape(shape)
            new_s[name] = new_vals.reshape(shape)
            new_c[name] = (c0 + prefix[-1]).reshape(center[name].shape)
        elif R * n <= DENSE_PREFIX_MAX_ELEMS:
            ix = indices[name]
            delta = (
                jnp.take_along_axis(w, ix, 1)
                - jnp.take_along_axis(snap, ix, 1)
            )
            dense = jax.vmap(
                lambda i, d: jnp.zeros((n,), w.dtype).at[i].add(d)
            )(ix, delta)
            prefix = jnp.cumsum(dense, axis=0)
            new_vals = c0[None, :] + prefix
            upd = jnp.take_along_axis(new_vals, ix, 1)
            new_r[name] = jax.vmap(
                lambda row, i, v: row.at[i].set(v)
            )(w, ix, upd).reshape(shape)
            new_s[name] = jax.vmap(
                lambda row, i, v: row.at[i].set(v)
            )(snap, ix, upd).reshape(shape)
            new_c[name] = (c0 + prefix[-1]).reshape(center[name].shape)
        else:
            wi, si, c = _scan_random_sync(w, snap, c0, indices[name])
            new_r[name] = wi.reshape(shape)
            new_s[name] = si.reshape(shape)
            new_c[name] = c.reshape(center[name].shape)
    return new_r, new_s, new_c


def _scan_random_sync(w, snap, c0, ix):
    """The serial server loop, verbatim: replica i's sampled deltas hit
    the center before replica i+1's message is handled (per-param lock,
    server.cc:110-143). Peak transient memory is the (R, m) gathered
    field — used by random_sync when the dense (R, n) prefix field
    would exceed DENSE_PREFIX_MAX_ELEMS."""

    def step(c, inp):
        wi, si, ixi = inp
        delta = wi[ixi] - si[ixi]
        new = c[ixi] + delta  # server's pre-update value + own delta
        c = c.at[ixi].add(delta)
        wi = wi.at[ixi].set(new)
        si = si.at[ixi].set(new)
        return c, (wi, si)

    c, (w2, s2) = jax.lax.scan(step, c0, (w, snap, ix))
    return w2, s2, c


def sample_sync_indices(
    rng: np.random.RandomState,
    shapes: dict[str, tuple],
    nreplicas: int,
    ratio: float,
) -> dict[str, np.ndarray]:
    """Host-side coordinate sampling for one RandomSync round.

    Each replica draws its own coordinates (the reference seeds per-worker
    from the wall clock, param.cc:146; parity is distributional). The
    sample count m = floor(ratio*n) — the reference's float-to-int
    truncation of data_.count()*sample_ratio (param.cc:148) — is static
    per param so the jitted sync retraces only when the ratio changes
    (it is fixed after warmup).
    """
    out: dict[str, np.ndarray] = {}
    for name, shape in shapes.items():
        n = int(np.prod(shape))
        if ratio >= 1.0:
            # every coordinate: the sorted sample IS arange — skip the
            # O(n) reject-sampling draw per replica (measured 5ms/round
            # on the MLP, pure overhead at full ratio)
            out[name] = np.broadcast_to(
                np.arange(n, dtype=np.int32), (nreplicas, n)
            )
            continue
        m = max(1, int(n * ratio))
        rows = [
            np.sort(rng.choice(n, size=m, replace=False))
            for _ in range(nreplicas)
        ]
        out[name] = np.stack(rows).astype(np.int32)
    return out
