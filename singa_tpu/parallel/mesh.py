"""ClusterConfig -> jax.sharding.Mesh.

The reference partitions its processes into worker *groups*: each group
holds one full model replica (data parallelism across groups) and
``nprocs_per_group`` processes that may split the model inside the group
(include/utils/cluster.h:42-60). The TPU-native mapping is a 2-D device
mesh:

    data axis  = ngroups            (one replica per mesh row)
    model axis = nprocs_per_group   (kLayerPartition splits ride this axis)

Servers (`nservers`) have no mesh footprint: the parameter-server tier
dissolves into GSPMD grad psum over the data axis. ``nthreads_per_procs``
(intra-process hogwild replicas) likewise dissolves — a single XLA program
already saturates a chip.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from ..config.schema import ClusterConfig, ConfigError

DATA_AXIS = "data"
MODEL_AXIS = "model"


def build_mesh(
    ndata: int = 1, nmodel: int = 1, devices=None
) -> Mesh:
    """Build a (data, model) mesh over the first ndata*nmodel devices.

    Axis order is (data, model) so that model-partition collectives ride
    the innermost (fastest, ICI-nearest) device ring, matching how the
    reference keeps intra-group bridges on the LAN while PS sync crosses
    racks.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    need = ndata * nmodel
    if need > len(devices):
        raise ConfigError(
            f"mesh wants {ndata}x{nmodel}={need} devices, "
            f"only {len(devices)} visible"
        )
    grid = np.array(devices[:need]).reshape(ndata, nmodel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def axis_pair_mesh(
    ndata: int, n: int, axis: str, devices=None, kind: str = "mesh"
) -> Mesh:
    """A ('data', axis) mesh over the first ndata*n devices — the shared
    builder behind the sp/ep/pp meshes (the second axis innermost so its
    collectives ride neighboring ICI hops, like MODEL_AXIS here)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    need = ndata * n
    if need > len(devices):
        raise ValueError(
            f"{kind} wants {ndata}x{n}={need} devices, "
            f"only {len(devices)} visible"
        )
    grid = np.array(devices[:need]).reshape(ndata, n)
    return Mesh(grid, ("data", axis))


#: full mesh axis order: model innermost (its collectives are densest),
#: then the seq ring, expert all-to-all, pipe hops, data outermost
FULL_AXES = ("data", "pipe", "expert", "seq", "model")


def build_full_mesh(widths: dict[str, int], devices=None) -> Mesh:
    """Build the 5-axis (data, pipe, expert, seq, model) mesh.

    Unused axes have width 1 and cost nothing; shardings that only name
    data/model behave exactly as on the 2-axis mesh."""
    devices = list(jax.devices()) if devices is None else list(devices)
    shape = tuple(max(1, widths.get(a, 1)) for a in FULL_AXES)
    need = int(np.prod(shape))
    if need > len(devices):
        raise ConfigError(
            f"mesh wants {dict(zip(FULL_AXES, shape))} = {need} devices, "
            f"only {len(devices)} visible"
        )
    grid = np.array(devices[:need]).reshape(shape)
    return Mesh(grid, FULL_AXES)


def mesh_from_cluster(
    cluster: ClusterConfig | None, devices=None
) -> Mesh:
    """Map the reference cluster topology onto a device mesh.

    ngroups -> data axis, nprocs_per_group -> intra-group axes
    (include/utils/cluster.h:49-60): by default all of it is the model
    axis (kLayerPartition); the extension fields nseq_per_group /
    nexperts_per_group / npipes_per_group carve seq/expert/pipe widths
    out of it (ClusterConfig.axis_widths). With no cluster config, every
    visible device joins the data axis — the common pure-DP case.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if cluster is None or not cluster.nworkers:
        return build_mesh(len(devices), 1, devices)
    widths = cluster.axis_widths
    if all(widths[a] == 1 for a in ("pipe", "expert", "seq")):
        return build_mesh(widths["data"], widths["model"], devices)
    return build_full_mesh(widths, devices)
