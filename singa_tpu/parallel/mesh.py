"""ClusterConfig -> jax.sharding.Mesh.

The reference partitions its processes into worker *groups*: each group
holds one full model replica (data parallelism across groups) and
``nprocs_per_group`` processes that may split the model inside the group
(include/utils/cluster.h:42-60). The TPU-native mapping is a 2-D device
mesh:

    data axis  = ngroups            (one replica per mesh row)
    model axis = nprocs_per_group   (kLayerPartition splits ride this axis)

Servers (`nservers`) have no mesh footprint: the parameter-server tier
dissolves into GSPMD grad psum over the data axis. ``nthreads_per_procs``
(intra-process hogwild replicas) likewise dissolves — a single XLA program
already saturates a chip.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from ..config.schema import ClusterConfig, ConfigError

DATA_AXIS = "data"
MODEL_AXIS = "model"


def build_mesh(
    ndata: int = 1, nmodel: int = 1, devices=None
) -> Mesh:
    """Build a (data, model) mesh over the first ndata*nmodel devices.

    Axis order is (data, model) so that model-partition collectives ride
    the innermost (fastest, ICI-nearest) device ring, matching how the
    reference keeps intra-group bridges on the LAN while PS sync crosses
    racks.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    need = ndata * nmodel
    if need > len(devices):
        raise ConfigError(
            f"mesh wants {ndata}x{nmodel}={need} devices, "
            f"only {len(devices)} visible"
        )
    grid = np.array(devices[:need]).reshape(ndata, nmodel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def axis_pair_mesh(
    ndata: int, n: int, axis: str, devices=None, kind: str = "mesh"
) -> Mesh:
    """A ('data', axis) mesh over the first ndata*n devices — the shared
    builder behind the sp/ep/pp meshes (the second axis innermost so its
    collectives ride neighboring ICI hops, like MODEL_AXIS here)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    need = ndata * n
    if need > len(devices):
        raise ValueError(
            f"{kind} wants {ndata}x{n}={need} devices, "
            f"only {len(devices)} visible"
        )
    grid = np.array(devices[:need]).reshape(ndata, n)
    return Mesh(grid, ("data", axis))


def mesh_from_cluster(
    cluster: ClusterConfig | None, devices=None
) -> Mesh:
    """Map the reference cluster topology onto a device mesh.

    ngroups -> data axis, nprocs_per_group -> model axis
    (include/utils/cluster.h:49-60). With no cluster config, every visible
    device joins the data axis — the common pure-DP case.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if cluster is None or not cluster.nworkers:
        return build_mesh(len(devices), 1, devices)
    nmodel = max(1, cluster.nprocs_per_group)
    ndata = cluster.ngroups
    return build_mesh(ndata, nmodel, devices)
