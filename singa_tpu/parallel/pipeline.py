"""Pipeline parallelism: GPipe-style microbatch scheduling over a mesh axis.

The reference's closest precursor is layer placement: ``locationid`` puts
different layers on different processes with blocking bridge handshakes
and NO microbatch interleaving (SURVEY §2.5: "layer placement without
pipelining"). This module supplies the real thing, TPU-native: stages'
params shard over a "pipe" mesh axis, activations hop stage-to-stage via
``lax.ppermute``, and a ``lax.scan`` over nmicro + nstages - 1 ticks
keeps every stage busy once the pipeline fills. Backward is jax autodiff
through the scan — the reverse schedule with reversed hops, for free.

Constraints (documented, enforced): every stage maps activations of one
shared shape to the same shape (the reference's own shape-invariance rule
for partitioned nets, neuralnet.cc:187-193); microbatch count should be
>= the stage count to amortize the fill/drain bubble.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.quantized_collective import shard_map
from .mesh import axis_pair_mesh

PIPE_AXIS = "pipe"


def build_pp_mesh(ndata: int = 1, npipe: int = 1, devices=None) -> Mesh:
    """A (data, pipe) mesh: batch shards over data, stages over pipe."""
    return axis_pair_mesh(ndata, npipe, PIPE_AXIS, devices, "pp mesh")


def stage_param_shardings(mesh: Mesh, params, axis: str = PIPE_AXIS):
    """Shard every (nstages, ...) param leaf over the pipe axis."""
    return jax.tree.map(
        lambda _: NamedSharding(
            mesh, P(axis, *([None] * (np.ndim(_) - 1)))
        ),
        params,
    )


def pipeline_apply(
    stage_fn,
    stage_params,
    x: jnp.ndarray,
    mesh: Mesh,
    *,
    axis: str = PIPE_AXIS,
):
    """Run microbatches through the stage pipeline.

    stage_fn(params_one_stage, act) -> act applies ONE stage; its pytree
    ``stage_params`` has a leading nstages dim on every leaf, sharded
    over ``axis``. x is (nmicro, mb, ...) microbatched input (batch may
    shard over "data"). Returns (nmicro, mb, ...) outputs of the final
    stage. With a 1-wide pipe axis this is just a scan over microbatches.
    """
    nstages = mesh.shape[axis]
    if nstages == 1:
        one = jax.tree.map(lambda p: p[0], stage_params)
        return jax.vmap(lambda m: stage_fn(one, m))(x)
    nmicro = x.shape[0]
    data = "data" if "data" in mesh.shape else None

    def local(params_local, xm):
        params_one = jax.tree.map(lambda p: p[0], params_local)
        stage = jax.lax.axis_index(axis)
        is_first = stage == 0
        is_last = stage == nstages - 1
        mb_shape = xm.shape[1:]
        perm = [(j, (j + 1) % nstages) for j in range(nstages)]

        def tick(carry, t):
            recv = carry
            # stage 0 injects microbatch t (zeros once drained)
            mb = jnp.where(
                t < nmicro,
                jax.lax.dynamic_index_in_dim(
                    xm, jnp.minimum(t, nmicro - 1), keepdims=False
                ),
                jnp.zeros(mb_shape, xm.dtype),
            )
            inp = jnp.where(is_first, mb, recv)
            y = stage_fn(params_one, inp)
            # schedule validity: stage s works on microbatch t - s
            valid = (t >= stage) & (t - stage < nmicro)
            out = jnp.where(valid & is_last, y, jnp.zeros_like(y))
            send = jax.lax.ppermute(y, axis, perm)
            return send, (out, valid & is_last, t - stage)

        # the carry must already wear the vma of its steady state: derive
        # from xm (data axis) and mark pipe-varying (send crosses hops).
        # pcast/vma only exists on newer jax; the experimental shard_map
        # this image ships tracks replication itself, so the bare zero
        # is already correct there
        pcast = getattr(jax.lax, "pcast", None)
        zero = xm[0] * 0.0
        if pcast is not None:
            zero = pcast(zero, (axis,), to="varying")
        _, (outs, valids, idxs) = jax.lax.scan(
            tick, zero, jnp.arange(nmicro + nstages - 1)
        )
        # scatter valid ticks' outputs into microbatch order; on non-last
        # stages everything is zero and the result is discarded via the
        # psum below (each microbatch written by exactly one stage)
        buf = jnp.zeros_like(xm)
        buf = buf.at[jnp.where(valids, idxs, nmicro)].set(
            outs, mode="drop"
        )
        return jax.lax.psum(buf, axis)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(axis), stage_params),
            P(None, data),
        ),
        out_specs=P(None, data),
    )
    return fn(stage_params, x)
