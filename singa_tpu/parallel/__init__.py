"""Distribution layer: device meshes + GSPMD shardings.

Replaces the reference's entire distribution stack — the Cluster topology
singleton (include/utils/cluster.h), the ZeroMQ parameter-server protocol
(src/server/server.cc, src/worker/param_manager.cc), the graph-rewriting
partitioner (src/worker/neuralnet.cc:112-323), and the PUSH/PULL activation
bridges (src/worker/worker.cc:139-155) — with a `jax.sharding.Mesh` plus
sharding annotations. XLA's GSPMD pass inserts the collectives (psum for
grad sync over ICI, all-gather/reduce-scatter for layer partitions) that the
reference implemented by hand over TCP.
"""

from .collectives import (
    GradCommSpec,
    apply_grad_comm_tag,
    init_residuals,
    is_residual_key,
    reduce_gradients,
    residual_key,
    reverse_topo_buckets,
)
from .consistency import (
    elastic_sync,
    random_sync,
    sample_sync_indices,
    sync_now,
    sync_ratio,
)
from .launch import coordinator_address, init_distributed, read_hostfile
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    build_full_mesh,
    build_mesh,
    mesh_from_cluster,
)
from .moe import (
    build_ep_mesh,
    init_moe,
    moe_ffn,
    moe_ffn_dense,
    moe_ffn_a2a,
    moe_param_shardings,
)
from .pipeline import build_pp_mesh, pipeline_apply, stage_param_shardings
from .shardings import (
    batch_shardings,
    param_paddings,
    param_shardings,
    replicated,
    state_shardings,
    zero_update_shardings,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "GradCommSpec",
    "apply_grad_comm_tag",
    "init_residuals",
    "is_residual_key",
    "reduce_gradients",
    "residual_key",
    "reverse_topo_buckets",
    "build_full_mesh",
    "build_mesh",
    "mesh_from_cluster",
    "coordinator_address",
    "init_distributed",
    "read_hostfile",
    "build_ep_mesh",
    "init_moe",
    "moe_ffn",
    "moe_ffn_dense",
    "moe_ffn_a2a",
    "moe_param_shardings",
    "build_pp_mesh",
    "pipeline_apply",
    "stage_param_shardings",
    "batch_shardings",
    "param_paddings",
    "param_shardings",
    "replicated",
    "state_shardings",
    "zero_update_shardings",
    "elastic_sync",
    "random_sync",
    "sample_sync_indices",
    "sync_now",
    "sync_ratio",
]
