"""Mixture-of-experts FFN with expert parallelism.

The reference has no MoE (pre-transformer); this extension completes the
framework's parallelism vocabulary (dp/tp/sp/ep). The design is
GShard/Switch-style top-1 routing with a capacity limit, executed the
TPU way: routing builds a dense dispatch tensor (no ragged scatter — the
MXU sees einsums), experts' weights shard over a mesh axis, and the
combine is one psum over that axis. Under shard_map each device:

  1. computes gating for its (possibly data-sharded) tokens,
  2. dispatches tokens into its LOCAL experts' (capacity, d) buffers,
  3. runs the local experts' FFN,
  4. un-dispatches and psums partial outputs across the expert axis.

Dropped tokens (over capacity) pass through on the residual path, like
Switch Transformer. Routing/combine math stays fp32 under bf16 compute.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.quantized_collective import shard_map
from .mesh import axis_pair_mesh

EXPERT_AXIS = "expert"


def build_ep_mesh(ndata: int = 1, nexpert: int = 1, devices=None) -> Mesh:
    """A (data, expert) mesh: batch shards over data, experts over expert."""
    return axis_pair_mesh(ndata, nexpert, EXPERT_AXIS, devices, "ep mesh")


def init_moe(
    rng: jax.Array, d_model: int, d_ff: int, n_experts: int
) -> dict:
    """Param pytree: gate (D, E), experts' up (E, D, F) / down (E, F, D)."""
    kg, ku, kd = jax.random.split(rng, 3)
    s = 1.0 / np.sqrt(d_model)
    return {
        "gate": s * jax.random.normal(kg, (d_model, n_experts)),
        "up": s * jax.random.normal(ku, (n_experts, d_model, d_ff)),
        "down": (1.0 / np.sqrt(d_ff))
        * jax.random.normal(kd, (n_experts, d_ff, d_model)),
    }


def _route(x2d: jnp.ndarray, gate_w: jnp.ndarray, capacity: int):
    """Top-1 routing -> (dispatch (N, E, C) one-hot, combine weights,
    aux load-balancing loss, per-expert routed fraction, per-expert mean
    prob). All fp32. frac/mean_prob are the aux's ingredients — the
    all-to-all formulation pmeans them across token shards before the
    (nonlinear) product so its aux equals the global-batch value."""
    logits = x2d.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # (N, E)
    expert = jnp.argmax(probs, axis=-1)  # (N,)
    onehot = jax.nn.one_hot(expert, gate_w.shape[1], dtype=jnp.float32)
    # each token's position in its expert's queue (0-based)
    pos = jnp.sum((jnp.cumsum(onehot, axis=0) - 1.0) * onehot, axis=-1)
    kept = pos < capacity  # over-capacity tokens drop to the residual
    slot = jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=jnp.float32
    ) * kept[:, None]
    dispatch = onehot[:, :, None] * slot[:, None, :]  # (N, E, C)
    gate_val = jnp.sum(probs * onehot, axis=-1)  # (N,)
    combine = dispatch * gate_val[:, None, None]
    # Switch load-balancing aux: mean fraction-routed x mean prob per expert
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = gate_w.shape[1] * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux, frac, mean_prob


def moe_ffn_dense(x: jnp.ndarray, params: dict, capacity_factor: float = 1.25):
    """Single-device reference MoE: x (B, S, D) -> (y, aux_loss)."""
    b, s, d = x.shape
    n = b * s
    e = params["gate"].shape[1]
    capacity = max(1, int(capacity_factor * n / e))
    x2d = x.reshape(n, d)
    dispatch, combine, aux, _, _ = _route(x2d, params["gate"], capacity)
    expert_in = jnp.einsum(
        "nec,nd->ecd", dispatch, x2d.astype(jnp.float32)
    )
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["up"]))
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["down"])
    y = jnp.einsum("nec,ecd->nd", combine, expert_out)
    return y.reshape(b, s, d).astype(x.dtype), aux


def moe_ffn(
    x: jnp.ndarray,
    params: dict,
    mesh: Mesh,
    *,
    capacity_factor: float = 1.25,
    axis: str = EXPERT_AXIS,
):
    """Expert-parallel MoE over ``mesh``'s expert axis.

    x (B, S, D) with batch optionally sharded over "data"; expert weights
    (E, ...) sharded over ``axis``. Each shard routes its local tokens,
    computes only its local experts, and the combine psums partial
    outputs across the expert axis. With an unsharded batch (ndata == 1)
    this is numerically identical to moe_ffn_dense; under data sharding,
    capacity and queue order are per data shard, so over-capacity DROP
    decisions can differ from the global dense reference (outputs for
    kept tokens are identical either way).
    """
    nexp = mesh.shape[axis]
    if nexp == 1:
        return moe_ffn_dense(x, params, capacity_factor)
    data = "data" if "data" in mesh.shape else None

    def local(x, gate_w, up, down):
        b, s, d = x.shape
        n = b * s
        e_total = gate_w.shape[1]
        capacity = max(1, int(capacity_factor * n / e_total))
        x2d = x.reshape(n, d)
        dispatch, combine, aux, _, _ = _route(x2d, gate_w, capacity)
        # this shard owns experts [my*e_local, (my+1)*e_local)
        e_local = up.shape[0]
        my = jax.lax.axis_index(axis)
        lo = my * e_local
        dsp = jax.lax.dynamic_slice_in_dim(dispatch, lo, e_local, axis=1)
        cmb = jax.lax.dynamic_slice_in_dim(combine, lo, e_local, axis=1)
        expert_in = jnp.einsum("nec,nd->ecd", dsp, x2d.astype(jnp.float32))
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, up))
        expert_out = jnp.einsum("ecf,efd->ecd", h, down)
        y = jnp.einsum("nec,ecd->nd", cmb, expert_out)
        y = jax.lax.psum(y, axis)  # combine partial expert outputs
        # aux is identical on every expert shard (gating is replicated);
        # shape (1,) so the data axis can stack shards' values
        return y.reshape(b, s, d).astype(x.dtype), aux.reshape(1)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P(data, None, None),      # x: batch over data, replicated on ep
            P(),                       # gate replicated
            P(axis, None, None),       # up sharded over experts
            P(axis, None, None),       # down sharded over experts
        ),
        out_specs=(P(data, None, None), P(data)),
    )
    y, aux = fn(x, params["gate"], params["up"], params["down"])
    return y, jnp.mean(aux)


def moe_ffn_a2a(
    x: jnp.ndarray,
    params: dict,
    mesh: Mesh,
    *,
    capacity_factor: float = 1.25,
    axis: str = EXPERT_AXIS,
):
    """Expert-parallel MoE with GShard-style all-to-all dispatch.

    Tokens shard over BOTH the data and expert axes (the expert axis
    doubles as extra data parallelism outside the MoE); each device
    routes only its n/(ndata*E_shards) local tokens, ships per-expert
    capacity buffers to the experts' owners with one all_to_all, runs
    its local experts, and a second all_to_all returns the outputs.

    **Comm volume per device** (the r4 decision VERDICT r3 #7 asked
    for): 2 x cf * n_local * d — the two all_to_alls move only the
    capacity buffers. The psum formulation (moe_ffn) replicates every
    token over the expert axis, so each device routes/dispatches
    E-fold more tokens and the combine all-reduces a FULL (n, d)
    activation: ~2 * n * d comm per device plus E-fold redundant
    routing/dispatch compute. At E experts the all-to-all form does
    O(1/E) of both. (measured: BASELINE.md r4.)

    **Semantics vs moe_ffn/moe_ffn_dense**: the capacity limit is per
    (source shard, expert) — cf * n_local / E slots — the standard
    GShard/Switch local-capacity semantics. Aggregate capacity matches
    the dense reference, and with ample capacity (no drops anywhere)
    outputs are exactly equal (pinned by tests/test_moe.py); when a
    local queue overflows, DROP decisions differ from the global dense
    queue. The aux loss is exactly the global-batch value in all cases
    (frac/mean_prob pmean across token shards before the product).
    moe_ffn (psum) remains the default for dense-equivalence; select
    this with moe_param.dispatch: "alltoall".
    """
    nexp = mesh.shape[axis]
    if nexp == 1:
        return moe_ffn_dense(x, params, capacity_factor)
    data = "data" if "data" in mesh.shape else None
    token_axes = (data, axis) if data else (axis,)

    def local(x, gate_w, up, down):
        b, s, d = x.shape
        n = b * s
        e_total = gate_w.shape[1]
        e_local = up.shape[0]
        cap = max(1, int(capacity_factor * n / e_total))
        x2d = x.reshape(n, d)
        dispatch, combine, _, frac, mean_prob = _route(x2d, gate_w, cap)
        # send buffers: slot-addressed tokens for EVERY expert
        send = jnp.einsum("nec,nd->ecd", dispatch, x2d.astype(jnp.float32))
        # all_to_all over the expert axis: chunk k of the leading
        # (E_total = E_shards * e_local) dim goes to shard k; received
        # rows [j*e_local + i] are source shard j's buffer for my
        # local expert i
        recv = jax.lax.all_to_all(
            send, axis, split_axis=0, concat_axis=0, tiled=True
        )
        nshards = e_total // e_local
        expert_in = (
            recv.reshape(nshards, e_local, cap, d)
            .transpose(1, 0, 2, 3)
            .reshape(e_local, nshards * cap, d)
        )
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, up))
        out = jnp.einsum("ecf,efd->ecd", h, down)
        # reverse exchange: outputs back to the tokens' source shards
        back = (
            out.reshape(e_local, nshards, cap, d)
            .transpose(1, 0, 2, 3)
            .reshape(e_total, cap, d)
        )
        ret = jax.lax.all_to_all(
            back, axis, split_axis=0, concat_axis=0, tiled=True
        )
        y = jnp.einsum("nec,ecd->nd", combine, ret)
        # aux: exact global-batch value (see _route docstring)
        frac_g = jax.lax.pmean(frac, token_axes)
        mp_g = jax.lax.pmean(mean_prob, token_axes)
        aux = e_total * jnp.sum(frac_g * mp_g)
        return y.reshape(b, s, d).astype(x.dtype), aux.reshape(1)

    token_spec = P(token_axes if data else axis, None, None)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            token_spec,                # x: batch over data AND expert
            P(),                       # gate replicated
            P(axis, None, None),       # up sharded over experts
            P(axis, None, None),       # down sharded over experts
        ),
        # aux is pmean'ed identical everywhere; expose one copy
        out_specs=(token_spec, P(None)),
    )
    y, aux = fn(x, params["gate"], params["up"], params["down"])
    return y, jnp.mean(aux)


def moe_param_shardings(mesh: Mesh, axis: str = EXPERT_AXIS) -> dict:
    """Placement for init_moe params on an ep mesh."""
    return {
        "gate": NamedSharding(mesh, P()),
        "up": NamedSharding(mesh, P(axis, None, None)),
        "down": NamedSharding(mesh, P(axis, None, None)),
    }
