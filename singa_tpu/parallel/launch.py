"""Multi-host bootstrap: the reference's process-identity machinery on JAX.

The reference assigns roles from ``-procsID`` + a hostfile (one address
per line, comments allowed; src/utils/cluster.cc:18-24) and then
hand-shakes every process through Router PING/PONG barriers
(src/utils/router.cc:16-86). On TPU both jobs belong to
``jax.distributed.initialize``: the coordinator (hostfile line 0) runs
the rendezvous service, every process reports its rank, and the runtime
wires the global device mesh — after which cross-host traffic is XLA
collectives over ICI/DCN, not sockets we manage.

On TPU pods (GKE / gcloud-created slices) the runtime injects its own
coordinator environment and ``initialize()`` needs no arguments; the
hostfile path exists for parity with reference launch scripts and for
CPU/GPU clusters.
"""

from __future__ import annotations

import os
import sys

DEFAULT_PORT = 9999  # arbitrary; the reference's start_port plays this role


def read_hostfile(path: str) -> list[str]:
    """Hostfile -> ordered address list (cluster.cc:18-24 semantics:
    one host per line, blank lines and #-comments skipped, order is
    process rank order)."""
    hosts: list[str] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                hosts.append(line)
    return hosts


def coordinator_address(hosts: list[str], port: int = DEFAULT_PORT) -> str:
    """Line 0 hosts the rendezvous, like the reference's server-0 router
    bind (router.cc:46-86). A host may carry its own ``:port``."""
    if not hosts:
        raise ValueError("empty hostfile")
    head = hosts[0]
    return head if ":" in head else f"{head}:{port}"


def init_distributed(
    procs_id: int | None = None,
    hostfile: str | None = None,
    *,
    port: int = DEFAULT_PORT,
) -> bool:
    """Initialize jax.distributed for a multi-host run; returns whether a
    multi-process rendezvous actually started.

    Resolution order matches how jobs launch in practice:
    1. No hostfile and no multi-process env -> single-process, no-op.
    2. TPU pod environment (runtime-injected coordinator) ->
       ``jax.distributed.initialize()`` with no arguments.
    3. Hostfile + procs_id -> explicit coordinator/num_processes/rank,
       the reference's ``-procsID``+hostfile contract (main.cc:13-18).
    """
    import jax

    if hostfile is None:
        explicit = any(
            v in os.environ
            for v in ("COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS")
        )
        workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        multi_worker = len([w for w in workers.split(",") if w]) > 1
        if not explicit and not workers:
            return False
        try:
            jax.distributed.initialize()
            return True
        except (ValueError, RuntimeError):
            if explicit or multi_worker:
                # a pod-shaped environment that fails to rendezvous must
                # not silently degrade to N independent same-seed trainers
                raise
            # single-host tunnels set TPU_WORKER_HOSTNAMES with one entry;
            # falling back to single-process is correct there, but say so
            print(
                "singa_tpu: jax.distributed.initialize() declined "
                "(single-host TPU environment); running single-process",
                file=sys.stderr,
            )
            return False
    hosts = read_hostfile(hostfile)
    if len(hosts) <= 1:
        return False
    if procs_id is None or not 0 <= procs_id < len(hosts):
        raise ValueError(
            f"procs_id {procs_id!r} out of range for {len(hosts)} hosts"
        )
    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address(hosts, port),
        num_processes=len(hosts),
        process_id=procs_id,
    )
    return True


def _enable_cpu_collectives() -> None:
    """Multi-process jobs on the CPU backend need jax's gloo collectives
    implementation — the default ('none') fails every cross-process
    computation with "Multiprocess computations aren't implemented on
    the CPU backend", which would take the whole coordination plane
    (resilience/coord.py preemption barriers, multihost_utils
    broadcasts) down with it. Must run BEFORE the backend initializes;
    a no-op on jax builds without the option (TPU runtimes ignore it)."""
    import jax

    platforms = os.environ.get("JAX_PLATFORMS", "") or str(
        getattr(jax.config, "jax_platforms", "") or ""
    )
    if "cpu" not in platforms:
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass
