"""Decoder-only transformer LM, TPU-first.

No counterpart exists in the reference (pre-transformer system, SURVEY
§5); this family exists to make long-context training first-class. The
design keeps the framework's conventions: params are a flat name-keyed
pytree (like the layer zoo's "<layer>/<param>" naming), the forward is a
pure function traced into one jitted step, and distribution is sharding
metadata, not code:

- attn="flash" routes through the Pallas flash kernel
  (singa_tpu/ops/attention.py) on TPU;
- attn="ring" shards the sequence dim over a mesh axis and streams K/V
  around the ICI ring (singa_tpu/parallel/ring.py) — context length
  scales linearly with ring size;
- the batch dim shards over any "data" mesh axis exactly like the
  proto-driven nets (grad psum = ParamSync).

Weights use bf16-friendly shapes (head_dim, d_ff multiples of 128 map
cleanly onto the MXU); compute dtype is the caller's choice via the
params' dtype.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from ..ops.attention import attention
from ..parallel.ring import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int
    d_model: int = 256
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 1024
    max_len: int = 1024
    attn: str = "dense"  # dense | flash | ring
    #: >0 replaces every block's FFN with a Switch MoE of this many
    #: experts (parallel/moe.py); pair with an "expert" mesh axis for
    #: expert parallelism. The load-balancing aux joins lm_loss.
    moe_experts: int = 0
    moe_aux_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def init_lm(rng: jax.Array, cfg: TransformerConfig) -> dict:
    """Flat name-keyed param pytree; scaled-normal init."""
    params: dict[str, jnp.ndarray] = {}

    def norm(key, shape, scale):
        return scale * jax.random.normal(key, shape, dtype=jnp.float32)

    keys = iter(jax.random.split(rng, 2 + 4 * cfg.n_layers))
    params["embed/tok"] = norm(next(keys), (cfg.vocab, cfg.d_model), 0.02)
    params["embed/pos"] = norm(next(keys), (cfg.max_len, cfg.d_model), 0.02)
    for i in range(cfg.n_layers):
        p = f"blk{i}"
        d, f = cfg.d_model, cfg.d_ff
        params[f"{p}/ln1/scale"] = jnp.ones((d,))
        params[f"{p}/ln1/bias"] = jnp.zeros((d,))
        params[f"{p}/attn/qkv"] = norm(next(keys), (d, 3 * d), 1 / math.sqrt(d))
        params[f"{p}/attn/out"] = norm(
            next(keys), (d, d), 1 / math.sqrt(d * 2 * cfg.n_layers)
        )
        params[f"{p}/ln2/scale"] = jnp.ones((d,))
        params[f"{p}/ln2/bias"] = jnp.zeros((d,))
        if cfg.moe_experts:
            from ..parallel.moe import init_moe

            moe = init_moe(next(keys), d, f, cfg.moe_experts)
            for k, v in moe.items():
                params[f"{p}/moe/{k}"] = v
        else:
            params[f"{p}/mlp/up"] = norm(
                next(keys), (d, f), 1 / math.sqrt(d)
            )
            params[f"{p}/mlp/down"] = norm(
                next(keys), (f, d), 1 / math.sqrt(f * 2 * cfg.n_layers)
            )
    params["ln_f/scale"] = jnp.ones((cfg.d_model,))
    params["ln_f/bias"] = jnp.zeros((cfg.d_model,))
    return params


def lm_param_shardings(mesh, params: dict, axis: str = "model") -> dict:
    """Tensor-parallel specs for the code-API param tree.

    The MLP gets the classic Megatron column/row pair (``up`` shards its
    output dim, ``down`` the matching contraction dim: one psum per
    block, gelu stays local). The attention projections (``qkv``,
    ``out``) shard their CONTRACTION dim instead: the packed ``(d, 3d)``
    qkv layout reshapes to ``(3, heads, head_dim)`` downstream, and a
    contiguous column shard of the 3d dim crosses the q|k|v thirds for
    every practical width (head-parallel attention would need an
    unpacked/interleaved weight layout) — contraction sharding still
    divides the projection FLOPs and weight memory evenly and never
    fights the reshape; only the S^2 attention core itself stays
    replicated. Embeddings / norms / MoE trees stay replicated. A dim
    ``axis`` does not divide — or a mesh without ``axis`` at all —
    falls back to replicated: the annotation is a performance hint,
    never a constraint. Beyond-parity extension: the conf surface gets
    TP from kLayerPartition (parallel/shardings.py); this gives the
    code-API LM (init_lm / lm_apply / generate) the same axis without a
    conf.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    width = dict(mesh.shape).get(axis, 0)

    def spec_for(name: str, v) -> PartitionSpec:
        if not width:  # mesh has no such axis: everything replicated
            return PartitionSpec()
        if name.endswith("/mlp/up"):
            dim = 1
        elif name.endswith(("/attn/qkv", "/attn/out", "/mlp/down")):
            dim = 0
        else:
            return PartitionSpec()
        if v.ndim != 2 or v.shape[dim] % width:
            return PartitionSpec()
        return PartitionSpec(*(axis if d == dim else None for d in range(2)))

    return {k: NamedSharding(mesh, spec_for(k, v)) for k, v in params.items()}


def _layernorm(x, scale, bias, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias


def _attend(q, k, v, cfg: TransformerConfig, mesh):
    if cfg.attn == "ring":
        if mesh is None:
            raise ValueError("attn='ring' requires a mesh with a seq axis")
        return ring_attention(q, k, v, mesh, causal=True)
    if cfg.attn == "flash":
        # dense below the per-device score-footprint threshold, kernel
        # above — "flash" means "don't blow memory", not "always
        # kernel" (ops.attention.auto_attention, BASELINE.md r3)
        from ..ops.attention import auto_attention

        return auto_attention(
            q, k, v, causal=True,
            n_devices=mesh.size if mesh is not None else 1,
        )
    return attention(q, k, v, causal=True)


def _block_apply(params, p, x, attend, cfg, mesh=None,
                 moe_capacity_factor=None):
    """One transformer block with a pluggable attention implementation.

    ``attend(q, k, v) -> (o, extra)`` receives/returns (B, H, S, D);
    ``extra`` passes through (K/V caches for decode, None otherwise).
    The SINGLE definition of block semantics — lm_apply, generate()'s
    prefill, and the KV-cache decode step all run this body, so the
    train->decode bit-exact parity cannot silently diverge.
    ``moe_capacity_factor`` overrides the MoE capacity (decode passes E
    so routing is drop-free; None keeps the training default)."""
    b, s, _ = x.shape
    h = _layernorm(x, params[f"{p}/ln1/scale"], params[f"{p}/ln1/bias"])
    qkv = h @ params[f"{p}/attn/qkv"]
    qkv = qkv.reshape(b, s, 3, cfg.n_heads, cfg.head_dim)
    q, k, v = (jnp.moveaxis(qkv[:, :, j], 2, 1) for j in range(3))
    o, extra = attend(q, k, v)
    o = jnp.moveaxis(o, 1, 2).reshape(b, s, cfg.d_model)
    x = x + o @ params[f"{p}/attn/out"]
    h = _layernorm(x, params[f"{p}/ln2/scale"], params[f"{p}/ln2/bias"])
    aux = jnp.float32(0.0)
    if cfg.moe_experts:
        from ..parallel.moe import moe_ffn, moe_ffn_dense

        moe_params = {
            k2: params[f"{p}/moe/{k2}"] for k2 in ("gate", "up", "down")
        }
        if mesh is not None and "expert" in getattr(mesh, "shape", {}):
            y, aux = moe_ffn(h, moe_params, mesh)
        elif moe_capacity_factor is not None:
            y, aux = moe_ffn_dense(
                h, moe_params, capacity_factor=moe_capacity_factor
            )
        else:
            y, aux = moe_ffn_dense(h, moe_params)
        x = x + y
    else:
        h = jax.nn.gelu(h @ params[f"{p}/mlp/up"])
        x = x + h @ params[f"{p}/mlp/down"]
    return x, aux, extra


def lm_head(params: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Final layernorm + tied-embedding projection — the ONE LM head
    every forward shares (lm_apply, generate()'s prefill and decode
    scan, and the serving engine's decode/prefill/verify programs in
    serve/engine.py). Shared for the same reason ``_block_apply`` is:
    the speculative verify step's per-position logits must be the SAME
    head math as the one-token decode tick, so acceptance decisions
    cannot drift from what sequential decode would have emitted."""
    xf = _layernorm(x, params["ln_f/scale"], params["ln_f/bias"])
    return xf @ params["embed/tok"].T


def lm_apply(
    params: dict,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    mesh=None,
    *,
    return_aux: bool = False,
):
    """tokens (B, S) int32 -> logits (B, S, vocab); causal.

    With ``return_aux`` also returns the summed MoE load-balancing loss
    (0.0 for dense-FFN configs)."""
    b, s = tokens.shape
    x = params["embed/tok"][tokens] + params["embed/pos"][:s]
    aux_total = jnp.float32(0.0)
    attend = lambda q, k, v: (_attend(q, k, v, cfg, mesh), None)  # noqa: E731
    for i in range(cfg.n_layers):
        x, aux, _ = _block_apply(params, f"blk{i}", x, attend, cfg, mesh)
        aux_total = aux_total + aux
    logits = lm_head(params, x)
    if return_aux:
        return logits, aux_total
    return logits


def cache_attend(q, k_cache, v_cache, positions):
    """Masked attention of Q queries against a FULL cache — the single
    attention body every serving path shares (generate()'s prefill and
    decode scan here, the paged-KV engine's gathered blocks in
    serve/engine.py, the conf-net decode in serve/conf_decode.py).

    ``q`` (B, H, Q, D) holds queries whose absolute sequence positions
    are ``positions`` (B, Q); ``k_cache``/``v_cache`` (B, H, C, D) hold
    the whole (zero-padded) cache. Cache entries beyond a query's
    position score -1e30, so their softmax weight underflows to exactly
    0.0 — the cache tail (and any garbage a paged pool gathers there)
    never moves a bit of the output. Because the math is shared, "paged
    KV == dense cache" parity is bitwise by construction, not tested
    luck."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cache) * scale
    mask = (
        jnp.arange(k_cache.shape[2])[None, None, None, :]
        <= positions[:, None, :, None]
    )
    s = jnp.where(mask, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v_cache)


def _block_step(params, p, x, k_cache, v_cache, pos, cfg):
    """One transformer block on Q tokens (B, Q, d) against the
    (B, H, C, D) caches; returns (x', new_k, new_v) where new_k/v are
    the caches with positions [pos, pos+Q) filled. Q == 1 is the decode
    step; Q == prompt length (pos == 0) is prefill — ONE body serves
    both, shared with lm_apply via _block_apply. The MoE capacity is E
    (drop-free, batch-independent)."""

    def attend(q, k, v):
        nk = jax.lax.dynamic_update_slice_in_dim(k_cache, k, pos, axis=2)
        nv = jax.lax.dynamic_update_slice_in_dim(v_cache, v, pos, axis=2)
        positions = jnp.broadcast_to(
            pos + jnp.arange(q.shape[2])[None, :], q.shape[:1] + q.shape[2:3]
        )
        return cache_attend(q, nk, nv, positions), (nk, nv)

    x, _, (nk, nv) = _block_apply(
        params, p, x, attend, cfg,
        moe_capacity_factor=float(max(cfg.moe_experts, 1)),
    )
    return x, nk, nv


def generate(
    params: dict,
    prompt: jnp.ndarray,
    cfg: TransformerConfig,
    n_tokens: int,
    *,
    rng: jax.Array | None = None,
    temperature: float = 0.0,
    prefill_chunk: int | None = None,
) -> jnp.ndarray:
    """Autoregressive decode with a KV cache, TPU-first.

    ``prompt`` (B, P) int32 -> (B, P + n_tokens). Greedy when
    ``temperature`` == 0, else softmax sampling at that temperature
    (``rng`` required). The whole decode is ONE jittable program:
    prefill feeds the prompt through the SAME cached-attention
    ``_block_step`` body the decode scan uses (in chunks of
    ``prefill_chunk`` tokens, default min(P, 512), so a long-context
    prompt never materializes more than a chunk x max_len score
    tensor), then a ``lax.scan`` over ``n_tokens`` steps feeds each
    sampled token back through single-token block steps against the
    (B, H, max_len, D) caches — static shapes throughout, position
    handled by masking, no dynamic Python control flow. Chunking is
    bitwise split-invariant, so ``prefill_chunk`` is a memory knob,
    never a semantics knob.

    Beyond-parity extension: the reference is a pre-transformer system
    with no inference path at all (SURVEY §5); this completes the LM
    family's train -> sample loop.

    MoE semantics at decode: prefill and every decode step route with
    capacity_factor = E, which makes GShard capacity vacuous (capacity
    >= token count), so NO token is ever dropped at inference — and a
    row's output never depends on what else shares the batch. That is
    the standard deployment behavior; it also means exact parity with a
    recompute-the-whole-prefix oracle (which uses the TRAINING
    capacity) is only defined for dense-FFN configs
    (tests/test_generate.py pins dense parity bit-exactly, MoE
    batch-independence explicitly).
    """
    b, plen = prompt.shape
    if plen < 1:
        raise ValueError("generate: prompt must hold at least one token")
    total = plen + n_tokens
    if total > cfg.max_len:
        raise ValueError(
            f"generate: prompt {plen} + n_tokens {n_tokens} exceeds "
            f"max_len {cfg.max_len}"
        )
    if temperature > 0.0 and rng is None:
        raise ValueError("generate: sampling (temperature > 0) needs rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if prefill_chunk is None:
        prefill_chunk = max(1, min(plen, 512))

    # ---- prefill: the SAME _block_step body the decode scan (and the
    # serving engine, serve/engine.py) runs, at Q = chunk length against
    # zero-initialized caches. Chunking bounds the (B, H, Q, max_len)
    # score footprint for long prompts — the serving tier's chunked
    # prefill — and is bitwise chunk-split-invariant: each query attends
    # the full masked cache regardless of which chunk computed it.
    shape = (b, cfg.n_heads, cfg.max_len, cfg.head_dim)
    k_caches = [jnp.zeros(shape) for _ in range(cfg.n_layers)]
    v_caches = [jnp.zeros(shape) for _ in range(cfg.n_layers)]
    x_last = None
    for c0 in range(0, plen, prefill_chunk):
        n = min(prefill_chunk, plen - c0)
        x = (
            params["embed/tok"][prompt[:, c0:c0 + n]]
            + params["embed/pos"][c0:c0 + n]
        )
        for i in range(cfg.n_layers):
            x, k_caches[i], v_caches[i] = _block_step(
                params, f"blk{i}", x, k_caches[i], v_caches[i],
                jnp.int32(c0), cfg,
            )
        x_last = x
    last_logits = lm_head(params, x_last)[:, -1]

    def sample(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)
        return jax.random.categorical(
            key, logits / temperature, axis=-1
        ).astype(prompt.dtype)

    k0, rng = jax.random.split(rng)
    first = sample(last_logits, k0)

    # ---- decode: scan over single-token steps ----
    def step(carry, key):
        token, pos, ks, vs = carry
        x = (
            params["embed/tok"][token][:, None, :]
            + params["embed/pos"][pos][None, None, :]
        )
        new_ks, new_vs = [], []
        for i in range(cfg.n_layers):
            x, nk, nv = _block_step(
                params, f"blk{i}", x, ks[i], vs[i], pos, cfg
            )
            new_ks.append(nk)
            new_vs.append(nv)
        logits = lm_head(params, x)[:, 0]
        nxt = sample(logits, key)
        return (nxt, pos + 1, new_ks, new_vs), token

    keys = jax.random.split(rng, n_tokens)
    (last, _, _, _), out = jax.lax.scan(
        step, (first, jnp.int32(plen), k_caches, v_caches), keys
    )
    # out is (n_tokens, B): the token EMITTED at each step, i.e. the
    # sequence [first, ...]; drop nothing — `last` is the (unemitted)
    # n_tokens+1-th sample
    gen = jnp.moveaxis(out, 0, 1)
    return jnp.concatenate([prompt, gen], axis=1)


def lm_loss(
    params: dict,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    mesh=None,
) -> jnp.ndarray:
    """Next-token cross entropy, mean over all predicting positions.

    The forward runs on the full (ring-divisible) sequence; the loss
    drops the last position's prediction instead of trimming the input,
    so ring sharding never sees an odd S-1 length. MoE configs add the
    weighted load-balancing aux."""
    logits, aux = lm_apply(params, tokens, cfg, mesh, return_aux=True)
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    targets = tokens[:, 1:]
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll) + cfg.moe_aux_weight * aux
