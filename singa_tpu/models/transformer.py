"""Decoder-only transformer LM, TPU-first.

No counterpart exists in the reference (pre-transformer system, SURVEY
§5); this family exists to make long-context training first-class. The
design keeps the framework's conventions: params are a flat name-keyed
pytree (like the layer zoo's "<layer>/<param>" naming), the forward is a
pure function traced into one jitted step, and distribution is sharding
metadata, not code:

- attn="flash" routes through the Pallas flash kernel
  (singa_tpu/ops/attention.py) on TPU;
- attn="ring" shards the sequence dim over a mesh axis and streams K/V
  around the ICI ring (singa_tpu/parallel/ring.py) — context length
  scales linearly with ring size;
- the batch dim shards over any "data" mesh axis exactly like the
  proto-driven nets (grad psum = ParamSync).

Weights use bf16-friendly shapes (head_dim, d_ff multiples of 128 map
cleanly onto the MXU); compute dtype is the caller's choice via the
params' dtype.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from ..ops.attention import attention
from ..parallel.ring import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int
    d_model: int = 256
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 1024
    max_len: int = 1024
    attn: str = "dense"  # dense | flash | ring
    #: >0 replaces every block's FFN with a Switch MoE of this many
    #: experts (parallel/moe.py); pair with an "expert" mesh axis for
    #: expert parallelism. The load-balancing aux joins lm_loss.
    moe_experts: int = 0
    moe_aux_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def init_lm(rng: jax.Array, cfg: TransformerConfig) -> dict:
    """Flat name-keyed param pytree; scaled-normal init."""
    params: dict[str, jnp.ndarray] = {}

    def norm(key, shape, scale):
        return scale * jax.random.normal(key, shape, dtype=jnp.float32)

    keys = iter(jax.random.split(rng, 2 + 4 * cfg.n_layers))
    params["embed/tok"] = norm(next(keys), (cfg.vocab, cfg.d_model), 0.02)
    params["embed/pos"] = norm(next(keys), (cfg.max_len, cfg.d_model), 0.02)
    for i in range(cfg.n_layers):
        p = f"blk{i}"
        d, f = cfg.d_model, cfg.d_ff
        params[f"{p}/ln1/scale"] = jnp.ones((d,))
        params[f"{p}/ln1/bias"] = jnp.zeros((d,))
        params[f"{p}/attn/qkv"] = norm(next(keys), (d, 3 * d), 1 / math.sqrt(d))
        params[f"{p}/attn/out"] = norm(
            next(keys), (d, d), 1 / math.sqrt(d * 2 * cfg.n_layers)
        )
        params[f"{p}/ln2/scale"] = jnp.ones((d,))
        params[f"{p}/ln2/bias"] = jnp.zeros((d,))
        if cfg.moe_experts:
            from ..parallel.moe import init_moe

            moe = init_moe(next(keys), d, f, cfg.moe_experts)
            for k, v in moe.items():
                params[f"{p}/moe/{k}"] = v
        else:
            params[f"{p}/mlp/up"] = norm(
                next(keys), (d, f), 1 / math.sqrt(d)
            )
            params[f"{p}/mlp/down"] = norm(
                next(keys), (f, d), 1 / math.sqrt(f * 2 * cfg.n_layers)
            )
    params["ln_f/scale"] = jnp.ones((cfg.d_model,))
    params["ln_f/bias"] = jnp.zeros((cfg.d_model,))
    return params


def _layernorm(x, scale, bias, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias


def _attend(q, k, v, cfg: TransformerConfig, mesh):
    if cfg.attn == "ring":
        if mesh is None:
            raise ValueError("attn='ring' requires a mesh with a seq axis")
        return ring_attention(q, k, v, mesh, causal=True)
    if cfg.attn == "flash":
        # dense below the per-device score-footprint threshold, kernel
        # above — "flash" means "don't blow memory", not "always
        # kernel" (ops.attention.auto_attention, BASELINE.md r3)
        from ..ops.attention import auto_attention

        return auto_attention(
            q, k, v, causal=True,
            n_devices=mesh.size if mesh is not None else 1,
        )
    return attention(q, k, v, causal=True)


def lm_apply(
    params: dict,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    mesh=None,
    *,
    return_aux: bool = False,
):
    """tokens (B, S) int32 -> logits (B, S, vocab); causal.

    With ``return_aux`` also returns the summed MoE load-balancing loss
    (0.0 for dense-FFN configs)."""
    b, s = tokens.shape
    x = params["embed/tok"][tokens] + params["embed/pos"][:s]
    aux_total = jnp.float32(0.0)
    for i in range(cfg.n_layers):
        p = f"blk{i}"
        h = _layernorm(x, params[f"{p}/ln1/scale"], params[f"{p}/ln1/bias"])
        qkv = h @ params[f"{p}/attn/qkv"]
        qkv = qkv.reshape(b, s, 3, cfg.n_heads, cfg.head_dim)
        # (B, H, S, D)
        q, k, v = (
            jnp.moveaxis(qkv[:, :, j], 2, 1) for j in range(3)
        )
        o = _attend(q, k, v, cfg, mesh)
        o = jnp.moveaxis(o, 1, 2).reshape(b, s, cfg.d_model)
        x = x + o @ params[f"{p}/attn/out"]
        h = _layernorm(x, params[f"{p}/ln2/scale"], params[f"{p}/ln2/bias"])
        if cfg.moe_experts:
            from ..parallel.moe import moe_ffn, moe_ffn_dense

            moe_params = {
                k: params[f"{p}/moe/{k}"] for k in ("gate", "up", "down")
            }
            if mesh is not None and "expert" in getattr(mesh, "shape", {}):
                y, aux = moe_ffn(h, moe_params, mesh)
            else:
                y, aux = moe_ffn_dense(h, moe_params)
            x = x + y
            aux_total = aux_total + aux
        else:
            h = jax.nn.gelu(h @ params[f"{p}/mlp/up"])
            x = x + h @ params[f"{p}/mlp/down"]
    x = _layernorm(x, params["ln_f/scale"], params["ln_f/bias"])
    logits = x @ params["embed/tok"].T
    if return_aux:
        return logits, aux_total
    return logits


def lm_loss(
    params: dict,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    mesh=None,
) -> jnp.ndarray:
    """Next-token cross entropy, mean over all predicting positions.

    The forward runs on the full (ring-divisible) sequence; the loss
    drops the last position's prediction instead of trimming the input,
    so ring sharding never sees an odd S-1 length. MoE configs add the
    weighted load-balancing aux."""
    logits, aux = lm_apply(params, tokens, cfg, mesh, return_aux=True)
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    targets = tokens[:, 1:]
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll) + cfg.moe_aux_weight * aux
