"""Programmatic model families.

The reference declares (but leaves empty) programmatic net construction —
NeuralNet::AddLayer, include/worker/neuralnet.h:61-65 — alongside its
proto-driven builder. This package is that surface made real: models
built directly against the op vocabulary, for families beyond the
config schema's layer types:

  transformer  decoder-only LM (dense/flash/ring attention, optional
               Switch-MoE FFN with expert parallelism)
  resnet       ResNet-18/34/50/101/152 *job-config generator* — emits
               text-proto files for the standard engine
"""

from .resnet import resnet_conf
from .transformer import (
    TransformerConfig,
    init_lm,
    lm_apply,
    lm_loss,
)

__all__ = [
    "TransformerConfig",
    "init_lm",
    "lm_apply",
    "lm_loss",
    "resnet_conf",
]
