"""Programmatic model families.

The reference declares (but leaves empty) programmatic net construction —
NeuralNet::AddLayer, include/worker/neuralnet.h:61-65 — alongside its
proto-driven builder. This package is that surface made real: models
built directly against the op vocabulary, for families beyond the
config schema's layer types (currently the transformer LM that makes
long-context/sequence-parallel training first-class).
"""

from .transformer import (
    TransformerConfig,
    init_lm,
    lm_apply,
    lm_loss,
)

__all__ = ["TransformerConfig", "init_lm", "lm_apply", "lm_loss"]
