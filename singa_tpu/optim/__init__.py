"""Updaters (optimizers) and learning-rate schedules.

Exact re-implementations of the reference's updater math
(src/utils/updater.cc:11-182) as pure, jit-traceable pytree transforms:
5 updaters (SGD/Nesterov/AdaGrad/RMSProp/AdaDelta) x 6 LR schedules
(kFixed/kLinear/kExponential/kInverse_t/kInverse/kStep). The reference
mutates Param blobs in place per step; here ``apply`` maps
(step, params, grads, state) -> (params, state) so the whole update lives
inside the jitted train step.

Faithfulness notes (all pinned by tests/test_optim.py):
- weight decay is *folded into the gradient* (grad += wd*data) before the
  momentum/adaptive logic, with one per-updater quirk: AdaGrad and RMSProp
  accumulate the *pre-decay* gradient into history, AdaDelta the post-decay
  one (updater.cc:117-128 vs :168-181).
- the reference zeroes history at step==0; we initialize slots to zero in
  ``init_state``, which is equivalent because step 0 is the first apply.
- AdaDelta ignores the learning rate entirely (updater.cc:164-182).
- NesterovUpdater::Init never reads proto.momentum (reference bug: the
  member is uninitialized C++); we read cfg.momentum — the only sane
  interpretation — and document the divergence here.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..config.schema import ConfigError, UpdaterConfig
from ..params import ParamSpec

Params = dict[str, jnp.ndarray]
State = dict[str, dict[str, jnp.ndarray]]


def learning_rate(cfg: UpdaterConfig, step) -> jnp.ndarray:
    """GetLearningRate (reference: src/utils/updater.cc:11-51).

    ``step`` may be a traced jnp scalar; all branches lower to jnp ops.
    """
    base = cfg.base_learning_rate
    method = cfg.learning_rate_change_method
    step = jnp.asarray(step, dtype=jnp.float32)
    if method == "kFixed":
        return jnp.float32(base)
    if method == "kLinear":
        r = step / cfg.learning_rate_change_frequency
        return (1.0 - r) * base + r * cfg.final_learning_rate
    if method == "kExponential":
        # reference CHECKs base == 2*final; honor the contract
        if base != 2 * cfg.final_learning_rate:
            raise ConfigError("kExponential: base_learning_rate must be 2*final")
        return base / jnp.power(2.0, step / cfg.learning_rate_change_frequency)
    if method == "kInverse_t":
        if base != 2 * cfg.final_learning_rate:
            raise ConfigError("kInverse_t: base_learning_rate must be 2*final")
        return base / (1.0 + step / cfg.final_learning_rate)
    if method == "kInverse":
        return base * jnp.power(1.0 + cfg.gamma * step, -cfg.pow)
    if method == "kStep":
        # integer division step/freq, per the reference's explicit comment
        freq = cfg.learning_rate_change_frequency
        return base * jnp.power(cfg.gamma, (step // freq).astype(jnp.float32))
    raise ConfigError(f"unknown LR schedule {method!r}")


class Updater:
    """Base: selects slots + math per UpdaterConfig.type."""

    SLOTS: tuple[str, ...] = ()

    def __init__(self, cfg: UpdaterConfig):
        if cfg.base_learning_rate is None or cfg.base_learning_rate <= 0:
            if type(self) is not AdaDeltaUpdater:
                raise ConfigError("updater requires base_learning_rate > 0")
        self.cfg = cfg

    def init_state(self, params: Params) -> State:
        return {
            name: {slot: jnp.zeros_like(p) for slot in self.SLOTS}
            for name, p in params.items()
        }

    def apply(
        self,
        step,
        params: Params,
        grads: Params,
        state: State,
        specs: dict[str, ParamSpec],
        grad_scale: float = 1.0,
    ) -> tuple[Params, State]:
        new_p: Params = {}
        new_s: State = {}
        for name, p in params.items():
            spec = specs.get(name)
            lr_mult = spec.lr_mult if spec else 1.0
            wd_mult = spec.wd_mult if spec else 1.0
            np_, ns_ = self._update(
                step, p, grads[name], state[name], lr_mult, wd_mult, grad_scale
            )
            new_p[name] = np_
            new_s[name] = ns_
        return new_p, new_s

    def _lr(self, step, lr_mult: float) -> jnp.ndarray:
        return learning_rate(self.cfg, step) * lr_mult

    def _wd(self, wd_mult: float) -> float:
        return self.cfg.weight_decay * wd_mult

    def _update(self, step, data, grad, slots, lr_mult, wd_mult, gscale):
        raise NotImplementedError


class SGDUpdater(Updater):
    """SGD with momentum + L2 (reference: updater.cc:54-79)."""

    SLOTS = ("history",)

    def _update(self, step, data, grad, slots, lr_mult, wd_mult, gscale):
        lr = self._lr(step, lr_mult)
        wd = self._wd(wd_mult)
        if wd > 0:
            grad = grad + data * wd
        if self.cfg.momentum > 0:
            history = slots["history"] * self.cfg.momentum + lr * grad
            return data - history, {"history": history}
        return data - lr * grad, {"history": slots["history"]}


class NesterovUpdater(Updater):
    """Nesterov momentum (reference: updater.cc:82-105)."""

    SLOTS = ("history",)

    def _update(self, step, data, grad, slots, lr_mult, wd_mult, gscale):
        lr = self._lr(step, lr_mult)
        wd = self._wd(wd_mult)
        m = self.cfg.momentum
        if wd > 0:
            grad = grad + data * wd
        tmp = slots["history"]
        history = tmp * m + lr * grad
        update = history * (1.0 + m) - tmp * m
        return data - update, {"history": history}


class AdaGradUpdater(Updater):
    """AdaGrad (reference: updater.cc:107-128). History accumulates the
    *pre-weight-decay* gradient; the applied gradient includes decay."""

    SLOTS = ("history",)

    def _update(self, step, data, grad, slots, lr_mult, wd_mult, gscale):
        history = slots["history"] + jnp.square(grad * gscale)
        lr = self._lr(step, lr_mult)
        wd = self._wd(wd_mult)
        if wd > 0:
            grad = grad + data * wd
        data = data - lr * grad / jnp.sqrt(history + self.cfg.delta)
        return data, {"history": history}


class RMSPropUpdater(Updater):
    """RMSProp (reference: updater.cc:131-153); same decay quirk as AdaGrad."""

    SLOTS = ("history",)

    def _update(self, step, data, grad, slots, lr_mult, wd_mult, gscale):
        rho = self.cfg.rho
        history = slots["history"] * rho + (1.0 - rho) * jnp.square(grad * gscale)
        lr = self._lr(step, lr_mult)
        wd = self._wd(wd_mult)
        if wd > 0:
            grad = grad + data * wd
        data = data - lr * grad / jnp.sqrt(history + self.cfg.delta)
        return data, {"history": history}


class AdaDeltaUpdater(Updater):
    """AdaDelta (reference: updater.cc:156-182). No learning rate; decay is
    applied to the gradient *before* the history accumulation."""

    SLOTS = ("history", "update")

    def _update(self, step, data, grad, slots, lr_mult, wd_mult, gscale):
        rho = self.cfg.rho
        delta = self.cfg.delta
        wd = self._wd(wd_mult)
        if wd > 0:
            grad = grad + data * wd
        history = slots["history"] * rho + (1.0 - rho) * jnp.square(grad * gscale)
        tmp = grad * jnp.sqrt(slots["update"] + delta) / jnp.sqrt(history + delta)
        update = rho * slots["update"] + (1.0 - rho) * jnp.square(tmp)
        return data - tmp, {"history": history, "update": update}


_UPDATERS = {
    "kSGD": SGDUpdater,
    "kNesterov": NesterovUpdater,
    "kAdaGrad": AdaGradUpdater,
    "kRMSProp": RMSPropUpdater,
    "kAdaDelta": AdaDeltaUpdater,
}


def make_updater(cfg: UpdaterConfig) -> Updater:
    """Select the updater by UpdaterProto.type (reference: model.proto:308-315)."""
    try:
        cls = _UPDATERS[cfg.type]
    except KeyError:
        raise ConfigError(f"unknown updater type {cfg.type!r}") from None
    return cls(cfg)
