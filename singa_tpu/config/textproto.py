"""Protobuf text-format parser (no protobuf runtime dependency).

The reference system's public API is a pair of text-format protobuf files
(`model.conf`, `cluster.conf`) read by ``ReadProtoFromTextFile``
(reference: src/utils/common.cc:56-64). This module parses that syntax into
plain nested Python structures; ``singa_tpu.config.schema`` then applies
typed field definitions and defaults.

Supported syntax (everything the reference configs use, plus the common
text-format extras):

  key: value            # scalar field (int/float/bool/enum-ident/"string")
  key { ... }           # sub-message
  key: { ... }          # sub-message, colon form
  repeated fields       # same key occurring multiple times accumulates
  # line comments       # anywhere, including inside messages

Values are returned as Python ints/floats/bools/strings; enum identifiers
(e.g. ``kSGD``, ``MAX``) are returned as strings and resolved by the schema
layer.
"""

from __future__ import annotations

import re
from typing import Any

_TOKEN_RE = re.compile(
    r"""
    \s+
  | \#[^\n]*                          # comment
  | (?P<brace>[{}])
  | (?P<colon>:)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<number>[-+]?(?:\.\d+|\d+\.?\d*)(?:[eE][-+]?\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.]*)
    """,
    re.VERBOSE,
)

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\", "'": "'"}


class TextProtoError(ValueError):
    """Raised on malformed text-format input."""


def _unquote(tok: str) -> str:
    body = tok[1:-1]
    out = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            nxt = body[i + 1]
            if nxt in _ESCAPES:
                out.append(_ESCAPES[nxt])
                i += 2
                continue
            if nxt in "01234567":  # octal escape (max 3 octal digits)
                j = i + 1
                while j < len(body) and j < i + 4 and body[j] in "01234567":
                    j += 1
                # protobuf truncates a 3-digit octal escape to one byte
                out.append(chr(int(body[i + 1 : j], 8) & 0xFF))
                i = j
                continue
        out.append(c)
        i += 1
    return "".join(out)


def tokenize(text: str) -> list[tuple[str, Any]]:
    """Lex text-format input into (kind, value) tokens."""
    tokens: list[tuple[str, Any]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            line = text.count("\n", 0, pos) + 1
            raise TextProtoError(
                f"unexpected character {text[pos]!r} at line {line}"
            )
        pos = m.end()
        if m.lastgroup is None:
            continue  # whitespace / comment
        val = m.group(m.lastgroup)
        if m.lastgroup == "string":
            tokens.append(("string", _unquote(val)))
        elif m.lastgroup == "number":
            if re.search(r"[.eE]", val):
                tokens.append(("number", float(val)))
            else:
                tokens.append(("number", int(val)))
        elif m.lastgroup == "ident":
            if val == "true":
                tokens.append(("bool", True))
            elif val == "false":
                tokens.append(("bool", False))
            else:
                tokens.append(("ident", val))
        else:
            tokens.append((m.lastgroup, val))
    return tokens


#: message-nesting bound: real confs are ~4 deep; the recursive-descent
#: parser must fail with TextProtoError, not RecursionError, on
#: pathological input (tests/test_textproto_fuzz.py)
_MAX_DEPTH = 100


class _Parser:
    def __init__(self, tokens: list[tuple[str, Any]]):
        self.tokens = tokens
        self.pos = 0
        self.depth = 0

    def peek(self) -> tuple[str, Any] | None:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> tuple[str, Any]:
        tok = self.peek()
        if tok is None:
            raise TextProtoError("unexpected end of input")
        self.pos += 1
        return tok

    def parse_message(self, *, toplevel: bool = False) -> dict[str, list[Any]]:
        """Parse fields until '}' (or EOF at top level).

        Every field maps to a *list* of occurrences; the schema layer decides
        whether a field is repeated (keep the list), a scalar (take the last
        occurrence), or a non-repeated message (merge occurrences field-wise,
        matching protobuf text-format merge semantics).
        """
        self.depth += 1
        if self.depth > _MAX_DEPTH:
            raise TextProtoError(
                f"message nesting deeper than {_MAX_DEPTH} levels"
            )
        try:
            return self._parse_fields(toplevel=toplevel)
        finally:
            self.depth -= 1

    def _parse_fields(self, *, toplevel: bool) -> dict[str, list[Any]]:
        fields: dict[str, list[Any]] = {}
        while True:
            tok = self.peek()
            if tok is None:
                if toplevel:
                    return fields
                raise TextProtoError("unexpected end of input: missing '}'")
            if tok == ("brace", "}"):
                if toplevel:
                    raise TextProtoError("unbalanced '}' at top level")
                self.next()
                return fields
            kind, name = self.next()
            if kind != "ident":
                raise TextProtoError(f"expected field name, got {name!r}")
            tok = self.peek()
            if tok == ("colon", ":"):
                self.next()
                tok = self.peek()
                if tok == ("brace", "{"):
                    self.next()
                    value: Any = self.parse_message()
                else:
                    vkind, value = self.next()
                    if vkind not in ("string", "number", "bool", "ident"):
                        raise TextProtoError(
                            f"bad value for field {name!r}: {value!r}"
                        )
            elif tok == ("brace", "{"):
                self.next()
                value = self.parse_message()
            else:
                raise TextProtoError(
                    f"expected ':' or '{{' after field {name!r}"
                )
            fields.setdefault(name, []).append(value)


def parse(text: str) -> dict[str, list[Any]]:
    """Parse text-format protobuf into {field: [occurrences...]}."""
    return _Parser(tokenize(text)).parse_message(toplevel=True)


def parse_file(path: str) -> dict[str, list[Any]]:
    with open(path, "r", encoding="utf-8") as f:
        return parse(f.read())
