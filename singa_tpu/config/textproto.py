"""Protobuf text-format parser (no protobuf runtime dependency).

The reference system's public API is a pair of text-format protobuf files
(`model.conf`, `cluster.conf`) read by ``ReadProtoFromTextFile``
(reference: src/utils/common.cc:56-64). This module parses that syntax into
plain nested Python structures; ``singa_tpu.config.schema`` then applies
typed field definitions and defaults.

Supported syntax (everything the reference configs use, plus the common
text-format extras):

  key: value            # scalar field (int/float/bool/enum-ident/"string")
  key { ... }           # sub-message
  key: { ... }          # sub-message, colon form
  repeated fields       # same key occurring multiple times accumulates
  # line comments       # anywhere, including inside messages

Values are returned as Python ints/floats/bools/strings; enum identifiers
(e.g. ``kSGD``, ``MAX``) are returned as strings and resolved by the schema
layer.
"""

from __future__ import annotations

import re
from typing import Any

_TOKEN_RE = re.compile(
    r"""
    \s+
  | \#[^\n]*                          # comment
  | (?P<brace>[{}])
  | (?P<colon>:)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<number>[-+]?(?:\.\d+|\d+\.?\d*)(?:[eE][-+]?\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.]*)
    """,
    re.VERBOSE,
)

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\", "'": "'"}


class TextProtoError(ValueError):
    """Raised on malformed text-format input."""


def _unquote(tok: str) -> str:
    body = tok[1:-1]
    out = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            nxt = body[i + 1]
            if nxt in _ESCAPES:
                out.append(_ESCAPES[nxt])
                i += 2
                continue
            if nxt in "01234567":  # octal escape (max 3 octal digits)
                j = i + 1
                while j < len(body) and j < i + 4 and body[j] in "01234567":
                    j += 1
                # protobuf truncates a 3-digit octal escape to one byte
                out.append(chr(int(body[i + 1 : j], 8) & 0xFF))
                i = j
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _tokenize_spans(text: str) -> list[tuple[str, Any, int, int]]:
    """Lex text-format input into (kind, value, line, col) tokens.

    ``line`` is 1-based, ``col`` 1-based (editor convention; diagnostics
    render them as ``path:LINE:COL``). The span points at the token's
    first character in the ORIGINAL text — for strings that is the
    opening quote, before unescaping."""
    tokens: list[tuple[str, Any, int, int]] = []
    pos = 0
    line = 1
    bol = 0  # offset of the current line's first character
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            line = text.count("\n", 0, pos) + 1
            raise TextProtoError(
                f"unexpected character {text[pos]!r} at line {line}"
            )
        start = m.start()  # == pos: the regex alternation is anchored
        col = start - bol + 1
        pos = m.end()
        if m.lastgroup is None:
            # whitespace / comment: advance the line counter through it
            nl = text.count("\n", start, pos)
            if nl:
                line += nl
                bol = text.rfind("\n", start, pos) + 1
            continue
        val = m.group(m.lastgroup)
        if m.lastgroup == "string":
            tokens.append(("string", _unquote(val), line, col))
        elif m.lastgroup == "number":
            if re.search(r"[.eE]", val):
                tokens.append(("number", float(val), line, col))
            else:
                tokens.append(("number", int(val), line, col))
        elif m.lastgroup == "ident":
            if val == "true":
                tokens.append(("bool", True, line, col))
            elif val == "false":
                tokens.append(("bool", False, line, col))
            else:
                tokens.append(("ident", val, line, col))
        else:
            tokens.append((m.lastgroup, val, line, col))
    return tokens


def tokenize(text: str) -> list[tuple[str, Any]]:
    """Lex text-format input into (kind, value) tokens."""
    return [(kind, val) for kind, val, _, _ in _tokenize_spans(text)]


#: message-nesting bound: real confs are ~4 deep; the recursive-descent
#: parser must fail with TextProtoError, not RecursionError, on
#: pathological input (tests/test_textproto_fuzz.py)
_MAX_DEPTH = 100


class FieldLoc:
    """(line, col) spans for one field occurrence: where the key token
    sits, where the value token sits (None for message blocks), and —
    for message values — the sub-message's own {field: [FieldLoc]} tree,
    parallel to the parse tree. Spans are 1-based."""

    __slots__ = ("key", "value", "sub")

    def __init__(self, key, value=None, sub=None):
        self.key = key        # (line, col) of the field-name token
        self.value = value    # (line, col) of the scalar value token
        self.sub = sub        # {fname: [FieldLoc]} for message values

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"FieldLoc(key={self.key}, value={self.value})"


class _Parser:
    def __init__(self, tokens: list[tuple[str, Any, int, int]]):
        self.tokens = tokens
        self.pos = 0
        self.depth = 0
        #: parallel loc tree for the most recent parse_message call
        self.locs: dict[str, list[FieldLoc]] = {}

    def peek(self) -> tuple[str, Any] | None:
        if self.pos < len(self.tokens):
            return self.tokens[self.pos][:2]
        return None

    def peek_span(self) -> tuple[int, int]:
        kind, val, line, col = self.tokens[self.pos]
        return line, col

    def next(self) -> tuple[str, Any]:
        tok = self.peek()
        if tok is None:
            raise TextProtoError("unexpected end of input")
        self.pos += 1
        return tok

    def parse_message(self, *, toplevel: bool = False) -> dict[str, list[Any]]:
        """Parse fields until '}' (or EOF at top level).

        Every field maps to a *list* of occurrences; the schema layer decides
        whether a field is repeated (keep the list), a scalar (take the last
        occurrence), or a non-repeated message (merge occurrences field-wise,
        matching protobuf text-format merge semantics). After the call,
        ``self.locs`` holds the parallel {field: [FieldLoc]} span tree.
        """
        self.depth += 1
        if self.depth > _MAX_DEPTH:
            raise TextProtoError(
                f"message nesting deeper than {_MAX_DEPTH} levels"
            )
        try:
            fields, locs = self._parse_fields(toplevel=toplevel)
            self.locs = locs
            return fields
        finally:
            self.depth -= 1

    def _parse_fields(
        self, *, toplevel: bool
    ) -> tuple[dict[str, list[Any]], dict[str, list[FieldLoc]]]:
        fields: dict[str, list[Any]] = {}
        locs: dict[str, list[FieldLoc]] = {}
        while True:
            tok = self.peek()
            if tok is None:
                if toplevel:
                    return fields, locs
                raise TextProtoError("unexpected end of input: missing '}'")
            if tok == ("brace", "}"):
                if toplevel:
                    raise TextProtoError("unbalanced '}' at top level")
                self.next()
                return fields, locs
            key_span = self.peek_span()
            kind, name = self.next()
            if kind != "ident":
                raise TextProtoError(f"expected field name, got {name!r}")
            floc = FieldLoc(key_span)
            tok = self.peek()
            if tok == ("colon", ":"):
                self.next()
                tok = self.peek()
                if tok == ("brace", "{"):
                    self.next()
                    value: Any = self.parse_message()
                    floc.sub = self.locs
                else:
                    if tok is not None:
                        floc.value = self.peek_span()
                    vkind, value = self.next()
                    if vkind not in ("string", "number", "bool", "ident"):
                        raise TextProtoError(
                            f"bad value for field {name!r}: {value!r}"
                        )
            elif tok == ("brace", "{"):
                self.next()
                value = self.parse_message()
                floc.sub = self.locs
            else:
                raise TextProtoError(
                    f"expected ':' or '{{' after field {name!r}"
                )
            fields.setdefault(name, []).append(value)
            locs.setdefault(name, []).append(floc)


def parse(text: str) -> dict[str, list[Any]]:
    """Parse text-format protobuf into {field: [occurrences...]}."""
    return _Parser(_tokenize_spans(text)).parse_message(toplevel=True)


def parse_with_locs(
    text: str,
) -> tuple[dict[str, list[Any]], dict[str, list[FieldLoc]]]:
    """Parse like :func:`parse`, additionally returning the parallel
    {field: [FieldLoc]} span tree: one FieldLoc per occurrence, in the
    same order as the parse tree's occurrence lists, with ``sub`` trees
    for message values. netlint threads these spans into Diagnostic
    locations (``path:LINE:COL``) so findings point at the offending
    token instead of a grep'd needle."""
    p = _Parser(_tokenize_spans(text))
    tree = p.parse_message(toplevel=True)
    return tree, p.locs


def parse_file(path: str) -> dict[str, list[Any]]:
    with open(path, "r", encoding="utf-8") as f:
        return parse(f.read())
