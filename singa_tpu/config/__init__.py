"""Job configuration: protobuf text-format parsing + typed schema.

Drop-in for the reference's proto config surface (src/proto/model.proto,
src/proto/cluster.proto, read via src/utils/common.cc:56-64) so existing
job files launch unchanged.
"""

from .schema import (
    ClusterConfig,
    ConfigError,
    LayerConfig,
    ModelConfig,
    NetConfig,
    ParamConfig,
    UpdaterConfig,
    load_cluster_config,
    load_model_config,
    parse_cluster_config,
    parse_model_config,
)
from .textproto import TextProtoError, parse, parse_file

__all__ = [
    "ClusterConfig",
    "ConfigError",
    "LayerConfig",
    "ModelConfig",
    "NetConfig",
    "ParamConfig",
    "UpdaterConfig",
    "TextProtoError",
    "load_cluster_config",
    "load_model_config",
    "parse_cluster_config",
    "parse_model_config",
    "parse",
    "parse_file",
]
