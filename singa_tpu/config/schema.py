"""Typed job-config schema mirroring the reference proto surface.

Field names, defaults, and enum vocabularies reproduce the reference's
`src/proto/model.proto` and `src/proto/cluster.proto` so that existing job
files (e.g. reference examples/mnist/mlp.conf, conv.conf) parse unchanged.
The schema is implemented as lightweight Python message classes rather than
generated protobuf code: the text-format front end lives in
``singa_tpu.config.textproto`` and this module applies typing + defaults.

Enums are represented as strings (the text-format identifiers, e.g.
``"kSGD"``, ``"MAX"``); constants are provided for comparison.
"""

from __future__ import annotations

from typing import Any

from . import textproto


# --------------------------------------------------------------------------
# enum vocabularies (model.proto:40-44,72-92,108-122,251-254,308-335)
# --------------------------------------------------------------------------

GRAD_CALC_ALGS = ("kBackPropagation", "kContrastiveDivergence")
INIT_METHODS = (
    "kConstant",
    "kGaussain",  # [sic] reference spelling, model.proto:75
    "kUniform",
    "kPretrained",
    "kGaussainSqrtFanIn",
    "kUniformSqrtFanIn",
    "kUniformSqrtFanInOut",
)
PHASES = ("kTrain", "kValidation", "kTest")
PARTITION_TYPES = ("kDataPartition", "kLayerPartition", "kNone")
CONNECTION_TYPES = ("kOneToOne", "kOneToAll")
POOL_METHODS = ("MAX", "AVE")
NORM_REGIONS = ("ACROSS_CHANNELS", "WITHIN_CHANNEL")
UPDATER_TYPES = ("kAdaGrad", "kAdaDelta", "kNesterov", "kSGD", "kRMSProp")
LR_CHANGE_METHODS = (
    "kFixed",
    "kInverse_t",
    "kInverse",
    "kExponential",
    "kLinear",
    "kStep",
)

#: Accepted alternate spellings, normalized to the reference token before
#: enum membership is checked. The reference's model.proto misspells
#: Gaussian ("kGaussain", model.proto:75); hand-written configs using the
#: corrected spelling parse fine and normalize to the [sic] token so the
#: rest of the system (param init, checkpoints) sees one vocabulary.
#: netlint's CFG003 points authors at this table.
ENUM_ALIASES = {
    "kGaussian": "kGaussain",
    "kGaussianSqrtFanIn": "kGaussainSqrtFanIn",
}


class ConfigError(ValueError):
    pass


# --------------------------------------------------------------------------
# message machinery
# --------------------------------------------------------------------------


class Field:
    """One schema field: type, default, repeated-ness, enum/message binding."""

    def __init__(
        self,
        kind: str,
        default: Any = None,
        *,
        repeated: bool = False,
        enum: tuple[str, ...] | None = None,
        message: type | None = None,
        required: bool = False,
    ):
        assert kind in ("int", "float", "bool", "string", "enum", "message")
        self.kind = kind
        self.default = default
        self.repeated = repeated
        self.enum = enum
        self.message = message
        self.required = required

    def convert(self, raw: Any, name: str) -> Any:
        k = self.kind
        if k == "message":
            if not isinstance(raw, dict):
                raise ConfigError(f"field {name!r} expects a message block")
            return self.message.from_fields(raw)
        if k == "int":
            if isinstance(raw, bool) or not isinstance(raw, (int, float)):
                raise ConfigError(f"field {name!r} expects an int, got {raw!r}")
            if isinstance(raw, float):
                # protobuf's text parser rejects any float literal for an
                # int32 field ("Expected integer, got: 2.0")
                raise ConfigError(
                    f"field {name!r} expects an int, got float {raw!r}"
                )
            return raw
        if k == "float":
            if isinstance(raw, bool) or not isinstance(raw, (int, float)):
                raise ConfigError(f"field {name!r} expects a number, got {raw!r}")
            return float(raw)
        if k == "bool":
            if isinstance(raw, bool):
                return raw
            if raw in (0, 1):
                return bool(raw)
            raise ConfigError(f"field {name!r} expects a bool, got {raw!r}")
        if k == "string":
            if not isinstance(raw, str):
                raise ConfigError(f"field {name!r} expects a string, got {raw!r}")
            return raw
        if k == "enum":
            if isinstance(raw, str) and raw in self.enum:
                # exact members always win; aliasing only rescues
                # spellings the vocabulary does not contain
                return raw
            canon = ENUM_ALIASES.get(raw, raw) if isinstance(raw, str) else raw
            if not isinstance(raw, str) or canon not in self.enum:
                # report what the user wrote, not the normalized token
                raise ConfigError(
                    f"field {name!r}: {raw!r} not in enum {self.enum}"
                )
            return canon
        raise AssertionError(k)


class Message:
    """Base for schema messages; subclasses declare FIELDS."""

    FIELDS: dict[str, Field] = {}

    def __init__(self, **kwargs: Any):
        for fname, spec in self.FIELDS.items():
            if fname in kwargs:
                val = kwargs.pop(fname)
            elif spec.repeated:
                val = []
            else:
                val = spec.default
            setattr(self, fname, val)
        if kwargs:
            raise ConfigError(
                f"{type(self).__name__}: unknown fields {sorted(kwargs)}"
            )

    @classmethod
    def from_fields(cls, raw: dict[str, list[Any]]) -> "Message":
        out: dict[str, Any] = {}
        for fname, occurrences in raw.items():
            spec = cls.FIELDS.get(fname)
            if spec is None:
                raise ConfigError(
                    f"{cls.__name__}: unknown field {fname!r} "
                    f"(known: {sorted(cls.FIELDS)})"
                )
            if spec.repeated:
                out[fname] = [spec.convert(v, fname) for v in occurrences]
            elif spec.kind == "message" and len(occurrences) > 1:
                # protobuf text-format merge: duplicate occurrences of a
                # non-repeated message field merge field-wise (recursively);
                # concatenating the occurrence lists reproduces that exactly.
                merged: dict[str, list[Any]] = {}
                for occ in occurrences:
                    if not isinstance(occ, dict):
                        raise ConfigError(
                            f"field {fname!r} expects a message block"
                        )
                    for sub, subvals in occ.items():
                        merged.setdefault(sub, []).extend(subvals)
                out[fname] = spec.convert(merged, fname)
            else:
                out[fname] = spec.convert(occurrences[-1], fname)
        msg = cls(**out)
        for fname, spec in cls.FIELDS.items():
            if spec.required and getattr(msg, fname) is None:
                raise ConfigError(f"{cls.__name__}: missing required {fname!r}")
        return msg

    @classmethod
    def from_text(cls, text: str) -> "Message":
        return cls.from_fields(textproto.parse(text))

    @classmethod
    def from_file(cls, path: str) -> "Message":
        return cls.from_fields(textproto.parse_file(path))

    def to_dict(self) -> dict[str, Any]:
        out = {}
        for fname, spec in self.FIELDS.items():
            v = getattr(self, fname)
            if spec.kind == "message":
                if spec.repeated:
                    v = [m.to_dict() for m in v]
                elif v is not None:
                    v = v.to_dict()
            out[fname] = v
        return out

    def __repr__(self) -> str:
        inner = ", ".join(
            f"{k}={getattr(self, k)!r}"
            for k in self.FIELDS
            if getattr(self, k) not in (None, [])
        )
        return f"{type(self).__name__}({inner})"


# --------------------------------------------------------------------------
# per-layer hyper-parameter messages (model.proto:160-275)
# --------------------------------------------------------------------------


class RGBImageConfig(Message):
    FIELDS = {
        "scale": Field("float", 1.0),
        "cropsize": Field("int", 0),
        "mirror": Field("bool", False),
        # singa-tpu extension (matches the successor SINGA's
        # rgbimage_param.meanfile): path to a mean.npy to subtract on
        # device. This snapshot's reference subtracts the mean at loader
        # time instead (tools/data_loader/data_source.cc:158-173); doing
        # it in the parser keeps shards uint8 and lets XLA fuse the
        # subtraction into the first conv.
        "meanfile": Field("string"),
    }


class SplitConfig(Message):
    FIELDS = {"num_splits": Field("int")}


class EmbeddingConfig(Message):
    """singa-tpu extension: token + learned positional embedding
    (layers/sequence.py). The reference predates sequence models."""

    FIELDS = {
        "vocab_size": Field("int", required=True),
        "embedding_dim": Field("int", required=True),
        "max_len": Field("int", 0),  # 0 = the data layer's seq length
    }


class LayerNormConfig(Message):
    FIELDS = {"eps": Field("float", 1e-5)}


class AttentionConfig(Message):
    """Causal multi-head self-attention over (B, S, D) activations.
    mode "flash" runs the Pallas kernel on TPU (dense fallback where the
    kernel can't serve the geometry)."""

    FIELDS = {
        "num_heads": Field("int", required=True),
        # "flash": Pallas kernel; "ring": sequence-parallel ring attention
        # over the cluster's seq mesh axis (nseq_per_group), falling back
        # to flash/dense when the mesh has no seq axis
        "mode": Field("enum", "dense", enum=("dense", "flash", "ring")),
    }


class DenseConfig(Message):
    """Per-position (last-dim) linear map — unlike kInnerProduct, which
    flattens to (batch, -1). Optional fused activation."""

    FIELDS = {
        "num_output": Field("int", required=True),
        "activation": Field("enum", "", enum=("", "gelu", "relu")),
        "bias_term": Field("bool", True),
    }


class MoEConfig(Message):
    """singa-tpu extension: Switch-style top-1 mixture-of-experts FFN
    (kMoE). Expert weights shard over the cluster's expert mesh axis
    (nexperts_per_group); the load-balancing aux loss joins the total
    loss with weight aux_loss_weight. num_experts must be a multiple of
    the expert axis width."""

    FIELDS = {
        "num_experts": Field("int", required=True),
        "d_ff": Field("int", required=True),
        "capacity_factor": Field("float", 1.25),
        "aux_loss_weight": Field("float", 0.01),
        # "psum" replicates tokens over the expert axis and all-reduces
        # the combine (exactly dense-equivalent); "alltoall" shards
        # tokens over the expert axis too and moves only capacity
        # buffers (GShard semantics: per-shard capacity) —
        # parallel/moe.py moe_ffn_a2a's comm-volume docstring
        "dispatch": Field("string", "psum"),
    }


class GlobalPoolingConfig(Message):
    """singa-tpu extension: kGlobalPooling has no kernel/stride — only the
    method (AVE default, the ResNet convention)."""

    FIELDS = {"pool": Field("enum", "AVE", enum=POOL_METHODS)}


class BatchNormConfig(Message):
    """singa-tpu extension (no counterpart in model.proto — the reference
    predates batch norm); configures layers/norm.py BatchNormLayer."""

    FIELDS = {
        "momentum": Field("float", 0.9),
        "eps": Field("float", 1e-5),
        # OPT-IN different math (r5): batch moments from the first
        # batch/N sample rows with a straight-through (detached-stats)
        # backward — see ops/norm.py batch_norm_train_sampled. 1 = exact.
        "stats_sample_stride": Field("int", 1),
    }


class TanhConfig(Message):
    # scaled tanh: outer_scale * tanh(inner_scale * x); defaults are 1.0 but
    # the reference kTanh layer always uses the LeCun constants (stanh,
    # cxxnet_op.h:77-87) regardless — see layers/neuron.py.
    FIELDS = {
        "outer_scale": Field("float", 1.0),
        "inner_scale": Field("float", 1.0),
    }


class SoftmaxLossConfig(Message):
    FIELDS = {
        "topk": Field("int", 1),
        "scale": Field("float", 1.0),
    }


class ConvolutionConfig(Message):
    FIELDS = {
        "num_filters": Field("int"),
        "bias_term": Field("bool", True),
        "pad": Field("int", 0),
        "stride": Field("int", 1),
        "kernel": Field("int", required=True),
    }


class ConcateConfig(Message):
    FIELDS = {
        "concate_dimension": Field("int"),
        "concate_num": Field("int"),
    }


class DataConfig(Message):
    FIELDS = {
        "source": Field("string"),
        "path": Field("string"),
        "batchsize": Field("int"),
        "random_skip": Field("int", 0),
    }


class MnistConfig(Message):
    FIELDS = {
        "kernel": Field("int", 0),
        "sigma": Field("float", 0.0),
        "alpha": Field("float", 0.0),
        "beta": Field("float", 0.0),
        "gamma": Field("float", 0.0),
        "resize": Field("int", 0),
        "elastic_freq": Field("int", 0),
        "norm_a": Field("float", 1.0),
        "norm_b": Field("float", 0.0),
    }


class DropoutConfig(Message):
    FIELDS = {"dropout_ratio": Field("float", 0.5)}


class InnerProductConfig(Message):
    FIELDS = {
        "num_output": Field("int"),
        "bias_term": Field("bool", True),
    }


class RBMConfig(Message):
    """singa-tpu extension: restricted Boltzmann machine hyperparams.

    The reference declares the contrastive-divergence algorithm
    (GradCalcAlg.kContrastiveDivergence, model.proto:40-44) but ships no CD
    worker or RBM layer; this message parameterizes the greenfield kRBM
    layer that fills that hole (BASELINE config 4)."""

    FIELDS = {
        "num_hidden": Field("int"),
        "cd_k": Field("int", 1),
        # sample (vs. use mean-field probabilities for) the visible units
        # during Gibbs steps
        "sample_visible": Field("bool", False),
    }


class LRNConfig(Message):
    FIELDS = {
        "local_size": Field("int", 5),
        "alpha": Field("float", 1.0),
        "beta": Field("float", 0.75),
        "norm_region": Field("enum", "ACROSS_CHANNELS", enum=NORM_REGIONS),
        "knorm": Field("float", 1.0),
    }


class PoolingConfig(Message):
    FIELDS = {
        "pool": Field("enum", "MAX", enum=POOL_METHODS),
        "kernel": Field("int", required=True),
        "pad": Field("int", 0),
        "stride": Field("int", 1),
    }


class SliceConfig(Message):
    FIELDS = {
        "slice_dimension": Field("int"),
        "slice_num": Field("int"),
    }


class ReLUConfig(Message):
    FIELDS = {"negative_slope": Field("float", 0.0)}


class ParamConfig(Message):
    FIELDS = {
        "name": Field("string"),
        "id": Field("int"),
        "shape": Field("int", repeated=True),
        "split_threshold": Field("int", 5000000),
        "partition_dim": Field("int", -1),
        "init_method": Field("enum", "kConstant", enum=INIT_METHODS),
        "value": Field("float", 1.0),
        "low": Field("float", -1.0),
        "high": Field("float", 1.0),
        "mean": Field("float", 0.0),
        "std": Field("float", 1.0),
        "learning_rate_multiplier": Field("float", 1.0),
        "weight_decay_multiplier": Field("float", 1.0),
    }


class LayerConfig(Message):
    FIELDS = {
        "name": Field("string"),
        "type": Field("string"),
        "srclayers": Field("string", repeated=True),
        # locationid is the reference's layer-placement field
        # (base_layer.h:151-165: which thread/process hosts the layer).
        # Here an explicitly-set locationid assigns the layer to a
        # PIPELINE STAGE (graph/pipeline_plan.py) when the cluster conf
        # declares npipes_per_group > 1. Default None = unplaced
        # (prologue/epilogue, replicated); the reference's default is 0,
        # which a conf may still write explicitly.
        "locationid": Field("int", None),
        "partitionid": Field("int", 0),
        "partition_type": Field("enum", None, enum=PARTITION_TYPES),
        "share_ary": Field("string", repeated=True),
        "param": Field("message", repeated=True, message=ParamConfig),
        "share_param": Field("string", repeated=True),
        "exclude": Field("enum", repeated=True, enum=PHASES),
        "batchnorm_param": Field("message", message=BatchNormConfig),
        "globalpooling_param": Field("message", message=GlobalPoolingConfig),
        "embedding_param": Field("message", message=EmbeddingConfig),
        "layernorm_param": Field("message", message=LayerNormConfig),
        "attention_param": Field("message", message=AttentionConfig),
        "dense_param": Field("message", message=DenseConfig),
        "moe_param": Field("message", message=MoEConfig),
        "convolution_param": Field("message", message=ConvolutionConfig),
        "concate_param": Field("message", message=ConcateConfig),
        "data_param": Field("message", message=DataConfig),
        "dropout_param": Field("message", message=DropoutConfig),
        "inner_product_param": Field("message", message=InnerProductConfig),
        "lrn_param": Field("message", message=LRNConfig),
        "mnist_param": Field("message", message=MnistConfig),
        "pooling_param": Field("message", message=PoolingConfig),
        "rbm_param": Field("message", message=RBMConfig),
        "slice_param": Field("message", message=SliceConfig),
        "split_param": Field("message", message=SplitConfig),
        "relu_param": Field("message", message=ReLUConfig),
        "rgbimage_param": Field("message", message=RGBImageConfig),
        "softmaxloss_param": Field("message", message=SoftmaxLossConfig),
        "tanh_param": Field("message", message=TanhConfig),
    }


# --------------------------------------------------------------------------
# data record messages (model.proto:279-305,342-349)
# --------------------------------------------------------------------------

RECORD_TYPES = ("kSingleLabelImage",)


class SingleLabelImageRecord(Message):
    """One labelled image sample (model.proto:300-305).

    ``pixel`` holds raw uint8 bytes (decoded from the protobuf bytes field);
    ``data`` holds float pixels. Exactly one of the two is normally set.
    """

    FIELDS = {
        "shape": Field("int", repeated=True),
        "label": Field("int", 0),
        "pixel": Field("string", ""),
        "data": Field("float", repeated=True),
    }


class RecordConfig(Message):
    """Top-level dataset record (model.proto:279-285)."""

    FIELDS = {
        "type": Field("enum", "kSingleLabelImage", enum=RECORD_TYPES),
        "image": Field("message", message=SingleLabelImageRecord),
    }


class DatumConfig(Message):
    """Caffe LMDB record for import (model.proto:288-299)."""

    FIELDS = {
        "channels": Field("int", 0),
        "height": Field("int", 0),
        "width": Field("int", 0),
        "data": Field("string", ""),
        "label": Field("int", 0),
        "float_data": Field("float", repeated=True),
        "encoded": Field("bool", False),
    }


class BlobConfig(Message):
    """Tensor snapshot message (model.proto:342-349); used by checkpoints."""

    FIELDS = {
        "num": Field("int", 0),
        "channels": Field("int", 0),
        "height": Field("int", 0),
        "width": Field("int", 0),
        "data": Field("float", repeated=True),
        "diff": Field("float", repeated=True),
    }


class NetConfig(Message):
    FIELDS = {
        "layer": Field("message", repeated=True, message=LayerConfig),
        "partition_type": Field("enum", "kNone", enum=PARTITION_TYPES),
    }


class UpdaterConfig(Message):
    FIELDS = {
        "type": Field("enum", "kAdaGrad", enum=UPDATER_TYPES),
        "hogwild": Field("bool", True),
        "momentum": Field("float", 0.0),
        "weight_decay": Field("float", 0.0),
        "gamma": Field("float", 1.0),
        "pow": Field("float", 0.0),
        "delta": Field("float", 1e-7),
        "rho": Field("float", 0.9),
        "base_learning_rate": Field("float"),
        "final_learning_rate": Field("float"),
        "learning_rate_change_frequency": Field("int"),
        "learning_rate_change_method": Field(
            "enum", "kFixed", enum=LR_CHANGE_METHODS
        ),
        "sync_frequency": Field("int", 1),
        "warmup_steps": Field("int", 10),
        "moving_rate": Field("float", 0.0),
        "param_type": Field("string", "Elastic"),
    }


GUARD_POLICIES = ("kNone", "kSkip", "kRollback")


class ResilienceConfig(Message):
    """singa-tpu extension: fault-tolerance runtime knobs (resilience/).

    Presence of this block opts the job into the supervised train loop:
    ``resilience.supervisor.run`` catches crashes, restores the newest
    complete checkpoint, and retries with bounded exponential backoff; a
    crash-loop circuit breaker gives up loudly after ``max_restarts``
    failures that each made less than ``restart_window_steps`` steps of
    progress. SIGTERM/SIGINT drain the current step, write a final
    checkpoint, and exit resumable (TPU maintenance-event discipline).
    The reference's availability story was the parameter-server tier a
    restarted worker group rejoined (src/main.cc:49-55) plus the
    never-implemented Worker::Resume (src/worker/worker.cc:65-67); with
    no server tier, this block is the trainer-side replacement.
    """

    FIELDS = {
        # --- supervisor: crash-loop circuit breaker + backoff ---
        # give up after this many restarts that each progressed fewer
        # than restart_window_steps steps (a restart that gets past the
        # window resets the breaker); 0 = never restart
        "max_restarts": Field("int", 3),
        "restart_window_steps": Field("int", 1),
        # --- launcher-side restart budget (resilience/launcher.py) ---
        # distinct from the in-process breaker above: the breaker bounds
        # crash loops WITHIN one process lifetime, while exit-75
        # (resumable) statuses deliberately bypass it — a launcher that
        # blindly relaunches them can loop forever on a deterministic
        # drain/death cycle. The elastic launcher relaunches a gang at
        # most max_restarts_per_window times per rolling
        # restart_window_s seconds, then gives up loudly.
        # 0 = unbudgeted (relaunch forever; today's behavior).
        "max_restarts_per_window": Field("int", 0),
        "restart_window_s": Field("float", 3600.0),
        # exponential backoff between restarts: base * 2^k seconds,
        # capped at backoff_max (tests set base 0 for instant retries)
        "backoff_base": Field("float", 1.0),
        "backoff_max": Field("float", 60.0),
        # --- retention: keep-last-N complete checkpoints + LATEST ---
        "keep_last": Field("int", 3),
        # --- zero-stall checkpointing (resilience/async_ckpt.py): the
        # save becomes a non-blocking device snapshot at the step
        # boundary + a background writer thread (double-buffered; a full
        # buffer applies backpressure). SIGTERM drain flushes the
        # in-flight write before exiting resumable; a crash mid-write
        # never corrupts LATEST. false = the synchronous save path. ---
        "async_checkpoint": Field("bool", False),
        # --- divergence guard (on-device; no per-step host sync) ---
        # kSkip: drop a non-finite step's update and count it;
        # kRollback: additionally restore the last checkpoint with an LR
        # backoff after guard_rollback_after consecutive bad steps
        "guard_policy": Field("enum", "kNone", enum=GUARD_POLICIES),
        "guard_rollback_after": Field("int", 3),
        # effective-LR multiplier applied at each rollback (grads are
        # scaled by the accumulated factor inside the jitted step)
        "guard_lr_backoff": Field("float", 0.5),
        # --- hung-step watchdog: dump diagnostics when a step exceeds
        # this many seconds without reaching a boundary; 0 = disabled ---
        "watchdog_timeout": Field("float", 0.0),
        # write a final checkpoint when draining on SIGTERM/SIGINT
        "preemption_checkpoint": Field("bool", True),
        # --- cluster coordination (resilience/coord.py) ---
        # fold every host's preemption flag into a cross-host OR at
        # step/chunk boundaries so ANY host's SIGTERM drains EVERY host
        # at the SAME step (all ranks checkpoint + exit 75 together);
        # no-op on single-process jobs
        "coordinate_preemption": Field("bool", True),
        # peer-liveness watchdog: each rank touches a heartbeat file
        # while its process lives; a peer file stale past this many
        # seconds while OUR step is stalled means the peer died
        # mid-collective -> loud resumable exit (75) instead of a
        # silent forever-hang. 0 = disabled.
        "heartbeat_timeout_s": Field("float", 0.0),
        # two-phase sharded-save commit: process 0 promotes LATEST only
        # after every rank's CRC'd commit_k marker lands and verifies;
        # past this deadline the save is judged torn (LATEST keeps the
        # previous complete checkpoint)
        "commit_timeout_s": Field("float", 60.0),
    }


GRAD_COMM_MODES = ("exact", "quantized")
GRAD_COMM_DTYPES = ("int8", "bf16")


class GradCommConfig(Message):
    """singa-tpu extension: quantized + overlapped gradient collectives
    (parallel/collectives.py; PAPERS.md arxiv 2506.17615 EQuARX).

    ``mode: quantized`` casts each bucket's gradients to a scaled
    low-precision wire format (``dtype``) before the data-axis
    reduction — composing with ``zero_update``'s reduce-scatter layout —
    and dequantizes after; with ``error_feedback`` (default on) the
    compression error persists as per-param residual buffers re-injected
    next step, so convergence matches fp32 (validated end to end by
    tools/convergence.py ``--grad_comm q8``). ``buckets: N`` partitions
    the params into N reverse-topo groups whose reductions are chained
    in gradient-readiness order, so bucket k's collective overlaps
    bucket k+1's backward segment instead of one barrier at step end
    (N also sets the quantization-scale granularity; 0 = per-param
    scales, no ordering chain). ``mode: exact`` (default, = no block)
    keeps today's bitwise-identical fp32 path. Rejected by the replica
    engine, whose EASGD protocol owns its own sync math."""

    FIELDS = {
        "mode": Field("enum", "exact", enum=GRAD_COMM_MODES),
        "dtype": Field("enum", "int8", enum=GRAD_COMM_DTYPES),
        "error_feedback": Field("bool", True),
        "buckets": Field("int", 0),
    }


SPEC_DRAFTERS = ("ngram", "null")


class SpeculateConfig(Message):
    """singa-tpu extension: speculative multi-token decode for the
    serving tier (serve/speculate.py). ``k`` draft tokens per live slot
    per tick are proposed by a model-free ``drafter`` (``ngram`` =
    longest-suffix prompt lookup against the sequence's own
    prompt+emitted tokens; ``null`` = never proposes — the machinery
    probe) and scored in ONE fixed-shape batched verify pass; greedy
    acceptance takes the longest matching prefix plus the bonus token,
    and a masked KV rewind keeps the paged cache bitwise what
    sequential one-token decode would have written. Token streams are
    identical to non-speculative greedy by construction — speculation
    changes *when* tokens appear, never *which*. ``k: 0`` (default)
    disables speculation (the one-token decode tick). Speculation is
    greedy-only per slot: a temperature > 0 slot rides the verify tick
    with zero drafts (one sampled token per tick)."""

    FIELDS = {
        # draft tokens proposed per live greedy slot per tick; the
        # verify program scores (slots, k+1) positions in one forward
        "k": Field("int", 0),
        # draft source: "ngram" prompt-lookup, "null" (machinery probe)
        "drafter": Field("enum", "ngram", enum=SPEC_DRAFTERS),
    }


class PrefixCacheConfig(Message):
    """singa-tpu extension: prefix caching for the paged KV pool
    (serve/kv_pool.py). ``enabled`` turns the block allocator into a
    content-addressed, refcounted cache: FULL prompt-prefilled blocks
    are hashed by (prefix-so-far, block token ids), admissions share
    the incoming prompt's longest cached block-prefix instead of
    re-prefilling it (copy-on-write where a write into a shared block
    is unavoidable), and token streams plus the paged cache stay
    BITWISE identical to cache-disabled admission. ``lru`` keeps
    refcount-0 cached blocks on an LRU list — reclaimed lazily only
    when an allocation would otherwise exhaust the pool — so hits
    survive the cached sequence's retirement; false shares only among
    concurrently-live sequences."""

    FIELDS = {
        # content-addressed block sharing at admission (default off:
        # the PR 9 free-list allocator, no hashing, no refcount > 1)
        "enabled": Field("bool", False),
        # park refcount-0 cached blocks on an LRU list instead of
        # freeing eagerly (reclaimed lazily at pool exhaustion)
        "lru": Field("bool", True),
        # > 0: PARTIAL-TAIL sharing — sub-block digests at this token
        # stride index a prompt's last partial block, so a prompt whose
        # shared prefix ends mid-block copy-on-write-EXTENDS the deepest
        # registered partial match instead of re-prefilling the whole
        # block. Must divide kv_block_len (netlint SRV001 checks this
        # statically). 0 = full-block granularity only.
        "tail_stride": Field("int", 0),
        # register FULL decode-written blocks under the same chained
        # digest at retirement, so multi-turn conversations hit their
        # own history. Decode-written bytes ride a different compiled
        # shape than prefill (the PR 9 cross-shape caveat), so warm
        # streams over these blocks are TOKEN-LEVEL identical to cold
        # admission, not bitwise — default off preserves the bitwise
        # guarantee.
        "decode_blocks": Field("bool", False),
        # fleet cross-host block shipping: how long a host holds a
        # request awaiting a peer's cache_ship reply before degrading
        # to plain prefill (serve/fleet/host.py; never a hang)
        "fetch_timeout_s": Field("float", 2.0),
    }


class ServingConfig(Message):
    """singa-tpu extension: the serving tier (singa_tpu/serve/) — the
    capability analog of the reference's Server tier (one process
    answering every worker's kGet/kPut, src/server/server.cc), here one
    engine answering every client's generation request. ``slots`` is
    the decode batch width (one donated fixed-shape step advances every
    live slot per tick; admit/retire never recompiles); the KV cache is
    paged — ``kv_blocks`` fixed-size blocks of ``kv_block_len``
    positions each, allocated per request at admission and freed at
    retirement, so concurrent streams share device memory instead of
    each reserving max_len (admission backpressure when the pool is
    exhausted). ``max_prefill_chunk`` bounds how much prompt one tick
    prefills, so long prompts never stall live decode."""

    FIELDS = {
        # concurrent decode lanes in the single compiled step
        "slots": Field("int", 8),
        # positions per KV block; must divide the model's max_len
        "kv_block_len": Field("int", 16),
        # total pool blocks (incl. the reserved trash block);
        # 0 = dense-equivalent sizing (every slot can hold max_len)
        "kv_blocks": Field("int", 0),
        # max prompt tokens prefilled per request per tick
        "max_prefill_chunk": Field("int", 64),
        # speculative multi-token decode (absent = one-token ticks)
        "speculate": Field("message", message=SpeculateConfig),
        # refcounted copy-on-write block sharing at admission (absent =
        # the plain free-list allocator, every prompt fully prefilled)
        "prefix_cache": Field("message", message=PrefixCacheConfig),
    }


FLEET_ROLES = ("unified", "prefill", "decode", "auto")
FLEET_PEER_ROLES = ("unified", "prefill", "decode")


class FleetLoadConfig(Message):
    """singa-tpu extension: the offered-load model for the cost-aware
    shardlint's fleet sizing rule (lint/cost_model.py FLT002). Declares
    the traffic the fleet is sized for; netlint checks each role's
    aggregate capacity against it — decode capacity is
    ``decode_hosts * serving.slots * ticks_per_s`` tokens/s (every live
    slot emits one token per tick), prefill capacity is
    ``prefill_hosts * serving.max_prefill_chunk * ticks_per_s``
    prompt tokens/s (one chunk per host per tick). The rule only runs
    when ``requests_per_s`` and ``ticks_per_s`` are both positive —
    an absent or zeroed block declares no load model and is skipped."""

    FIELDS = {
        # steady-state request arrival rate the fleet must absorb
        "requests_per_s": Field("float", 0.0),
        # mean prompt length per request (prefill token demand)
        "prompt_tokens": Field("int", 0),
        # mean generated tokens per request (decode token demand)
        "decode_tokens": Field("int", 0),
        # engine step rate per host (decode ticks == prefill ticks)
        "ticks_per_s": Field("float", 0.0),
        # steady-state fraction [0, 1] of each prompt's tokens served
        # from the warm (fleet-wide) prefix cache: discounts FLT002's
        # prefill demand and SRV002's per-sequence block pressure so
        # capacity planning matches a warm fleet instead of pricing
        # every admission as a full prefill. Honored only when
        # serving { prefix_cache { enabled } } — a declared hit rate
        # with the cache off is wishful and is ignored.
        "prefix_hit_rate": Field("float", 0.0),
    }


class FleetPeerConfig(Message):
    """One host of a disaggregated serving fleet (serve/fleet/): its
    mailbox name and concrete role. Listed in RANK ORDER — entry k is
    the host ``-procsID k`` launches as, the reference's hostfile
    pattern (src/utils/cluster.cc:18-24)."""

    FIELDS = {
        "name": Field("string", required=True),
        "role": Field("enum", "unified", enum=FLEET_PEER_ROLES),
        # the host's "host:port" endpoint under `transport: socket`
        # (comm/wire.py; required there — netlint WIR001); the mailbox
        # transport needs only the shared root and ignores it
        "address": Field("string", ""),
    }


FLEET_TRANSPORTS = ("mailbox", "socket")


class WireConfig(Message):
    """singa-tpu extension: the socket transport's wire discipline
    (comm/wire.py) — send/connect deadlines, the bounded exponential
    reconnect backoff, and the peer-liveness window the host watchdog
    tombstones on. Only read under ``fleet { transport: socket }``;
    every field has a serving-safe default, so an empty block works."""

    FIELDS = {
        # TCP connect deadline per attempt
        "connect_timeout_s": Field("float", 2.0),
        # one attempt's transmit+ack deadline; a max-size migration
        # message must fit in it (retries re-send from scratch —
        # netlint WIR001 checks this against link_bandwidth)
        "send_timeout_s": Field("float", 5.0),
        # redelivery attempts after the first (0 = single attempt)
        "max_retries": Field("int", 4),
        # exponential backoff base between attempts ...
        "backoff_s": Field("float", 0.05),
        # ... capped here (no hot reconnect loop)
        "backoff_cap_s": Field("float", 2.0),
        # > 0: a peer we HAVE heard from that goes silent this long is
        # reported dead (peer_death tombstone); 0 = only exhausted
        # sends tombstone
        "liveness_timeout_s": Field("float", 0.0),
        # the front door's "host:port" endpoint — finished streams
        # report there (host.py results_to), so socket fleets need it
        "frontdoor_address": Field("string", ""),
        # modeled link bandwidth for WIR001's can-one-attempt-ever-
        # deliver check; 0 disables the check
        "link_bandwidth_bytes_per_s": Field("float", 1e9),
    }


class RolloutConfig(Message):
    """singa-tpu extension: live weight rollout into a RUNNING fleet
    (serve/rollout.py) — the controller stages next-version params
    alongside the live ones on every host (dual-resident until the
    flip; netlint ROL001 prices the extra HBM), canaries ONE
    decode-capable host, verifies stream parity on replayed probe
    traffic, then promotes host-by-host; a parity mismatch rolls the
    whole fleet back to the pinned current version."""

    FIELDS = {
        # next-version weights: an npz save, a sharded checkpoint dir,
        # or a retention folder (its newest complete save wins) —
        # restored through resilience/reshard.load_serving_params, so
        # ANY saved topology stages onto ANY serving host
        "checkpoint": Field("string", ""),
        # version tag the flip installs; 0 = derive from the save's
        # step (a rollout must always move to a NEW, nonzero version)
        "version": Field("int", 0),
        # the decode-capable host canaried first ("" = the first
        # decode-capable peer in rank order)
        "canary": Field("string", ""),
        # replayed probe streams the canary parity check verifies
        # against a reference engine on the staged weights
        "parity_probes": Field("int", 4),
        # tokens each probe stream decodes
        "probe_tokens": Field("int", 8),
        # per-host deadline for a stage/flip/probe acknowledgment
        # before the rollout declares the host dead and PAUSES
        "stage_timeout_s": Field("float", 30.0),
        # CRC-rejected weight ships retried this many times before the
        # version is quarantined (serving stays on current throughout)
        "ship_retries": Field("int", 2),
    }


class FleetConfig(Message):
    """singa-tpu extension: the disaggregated serving fleet
    (singa_tpu/serve/fleet/) — the serving-scale analog of the
    reference's rank-picks-role Worker/Server split (src/main.cc:49-55).
    Presence of this block routes ``singa_tpu.main`` to a fleet host
    instead of the trainer: ``role`` pins this host's role, or
    ``auto`` (default) assigns it by rank — ranks below
    ``prefill_hosts`` run admission + chunked prefill only and hand
    filled sequences to decode ranks over the paged-KV block-migration
    path; decode ranks run the fixed-shape decode tick only. Explicit
    ``peers`` entries name the whole fleet in rank order (else
    ``nworkers`` synthetic hosts). ``mailbox`` roots the filesystem
    transport (default ``<workspace>/fleet``)."""

    FIELDS = {
        # this host's role; "auto" = the rank-picks-role dispatch
        "role": Field("enum", "auto", enum=FLEET_ROLES),
        # the fleet topology in rank order (absent = synthetic names
        # with auto roles over the cluster's nworkers)
        "peers": Field("message", repeated=True, message=FleetPeerConfig),
        # with role auto: ranks [0, prefill_hosts) prefill, the rest
        # decode
        "prefill_hosts": Field("int", 1),
        # shared mailbox-transport root ("" = <workspace>/fleet)
        "mailbox": Field("string", ""),
        # the cross-process wiring: "mailbox" (filesystem, the
        # deterministic CI drill transport) or "socket" (comm/wire.py
        # TCP — the production path; peers need address fields and the
        # wire block's frontdoor_address, netlint WIR001)
        "transport": Field("enum", "mailbox", enum=FLEET_TRANSPORTS),
        # socket-transport deadlines/backoff/liveness (absent = the
        # WireConfig defaults)
        "wire": Field("message", message=WireConfig),
        # --- elastic fleet sizing (serve/fleet/host.py): the topology
        # (peers / nworkers) declares up to max_hosts ranks, but only
        # ranks [0, min_hosts) must be live at launch — the rest are
        # LATENT: declared, excluded from every placement decision
        # until they JOIN by publishing a serving status through the
        # transport (at which point prefill hosts start exporting to
        # them and the router sees their occupancy). Scale-down is the
        # drain-to-peer path (tombstone). 0 = the whole topology is
        # live at launch (the fixed fleet; today's behavior). ---
        "min_hosts": Field("int", 0),
        "max_hosts": Field("int", 0),
        # offered-load model for the cost-aware shardlint's per-role
        # fleet sizing (FLT002); absent = no declared load, rule skipped
        "load": Field("message", message=FleetLoadConfig),
        # live weight rollout: canaried, health-gated hot-swap of a
        # next-version checkpoint into the running fleet
        # (serve/rollout.py; netlint ROL001 checks feasibility)
        "rollout": Field("message", message=RolloutConfig),
    }


KERNEL_IMPLS = ("reference", "fused")
GRAD_ALLREDUCE_IMPLS = ("reference", "quantized_ring", "q8_hier")


class RingConfig(Message):
    """singa-tpu extension: two-level ring geometry for
    ``kernels { grad_allreduce: q8_hier }`` (the EQuARX deployment
    topology, arxiv 2506.17615 — fast intra-slice ICI feeding one
    scarce inter-slice DCN hop). Two mutually exclusive forms:

    - factored data axis: ``intra_degree: K`` splits the single
      ``data`` axis of width n into n/K groups of K adjacent ranks —
      the intra rings run over each K-block, the quantized inter ring
      over same-position ranks across blocks.
    - named axes: ``intra_axis`` / ``inter_axis`` name two real mesh
      axes (e.g. ``data`` × ``model``) and the reduction runs over
      their product, int8 only on the inter_axis hops.
    """

    FIELDS = {
        # mesh axis for the fast (full-precision) intra-slice rings
        "intra_axis": Field("string", ""),
        # mesh axis for the scarce (quantized) inter-slice ring
        "inter_axis": Field("string", ""),
        # factored form: group width K carved out of the data axis
        # (must divide it); 0 = use the named-axes form above
        "intra_degree": Field("int", 0),
    }


class KernelsConfig(Message):
    """singa-tpu extension: per-site kernel implementation selection
    (the Pallas hot-path seam, singa_tpu/ops/paged_attention.py +
    singa_tpu/ops/quantized_collective.py).

    ``paged_attention: fused`` swaps the serving engine's attention —
    every decode tick, prefill chunk, and speculative verify pass —
    from the reference gather -> ``cache_attend`` path onto a Pallas
    kernel that reads K/V blocks IN PLACE through the block table
    (flash-attention online-softmax tiling over block-granular K/V, no
    dense ``(slots, heads, cache_len, head_dim)`` materialization per
    layer). Output is allclose to the reference (online softmax
    reorders the reduction); greedy token streams are identical.
    ``reference`` (default, = no block) keeps the bitwise-pinned
    oracle path untouched. ``interpret`` (default true) runs the
    kernel through the Pallas interpreter (or, for the ring, the
    pure-ppermute XLA form) — plain XLA ops, CPU-safe and
    GSPMD-shardable, what CI exercises — set false on a real TPU
    to compile through Mosaic, which constrains the geometry
    (kv_block_len a multiple of 8, head_dim a multiple of 128; the
    engine rejects violations at construction, netlint KRN001 flags
    them statically).

    ``grad_allreduce: quantized_ring`` swaps the trainer's data-axis
    gradient collective — PR 8's ``grad_comm { mode: quantized }``
    numerics, whose cast sits AROUND the GSPMD psum so the wire stays
    fp32 — onto an explicit ring reduce-scatter + allgather whose
    ppermute'd wire value is genuinely int8 (per-bucket f32 scale
    riding alongside; ops/quantized_collective.py). Requires an active
    quantized ``grad_comm`` block, composes with ``zero_update`` (the
    ring's scatter output IS the update layout — the allgather phase
    is skipped) and ``error_feedback``; the replica engine rejects it
    (netlint KRN002 flags both statically, plus un-chunkable data-axis
    geometry). ``reference`` keeps the dequantize-then-psum oracle —
    jaxpr-identical to a config with no knob.

    ``grad_allreduce: q8_hier`` is the hierarchical two-level form
    (EQuARX's deployment topology): full-precision intra-slice ring
    reduce-scatter over the fast axis, ONE int8 inter-slice ring over
    group leaders (the quantization lands where bandwidth is
    scarcest), then the intra-slice allgather. Geometry comes from the
    model conf's ``ring {}`` block (``intra_degree`` to factor the
    data axis, or ``intra_axis``/``inter_axis`` to name two mesh
    axes); unlike the flat ring it accepts composed meshes whose
    non-data axes the factorization covers."""

    FIELDS = {
        # serving-tier attention: "reference" gather + cache_attend
        # oracle, "fused" Pallas paged-attention kernel
        "paged_attention": Field("enum", "reference", enum=KERNEL_IMPLS),
        # training-tier gradient collective: "reference" = grad_comm's
        # quantize-around-the-psum oracle (fp32 on the wire),
        # "quantized_ring" = int8-on-the-wire ppermute ring
        # "q8_hier" = hierarchical two-level ring (f32 intra-slice,
        # int8 inter-slice; geometry from the model's ring {} block)
        "grad_allreduce": Field(
            "enum", "reference", enum=GRAD_ALLREDUCE_IMPLS
        ),
        # run the fused kernel in the Pallas interpreter / the ring in
        # its pure-XLA ppermute form (CPU-safe); false = compile the
        # inner kernels through Mosaic (TPU, geometry-gated)
        "interpret": Field("bool", True),
    }


class TelemetryConfig(Message):
    """singa-tpu extension: the flight-recorder telemetry plane
    (singa_tpu/obs/). Always-on by default — a job with a workspace
    writes per-rank JSONL event logs to ``<workspace>/events/`` with
    zero added per-step device syncs (events buffer in memory and flush
    at display-cadence boundaries). ``tools/trace.py`` merges the
    per-rank logs into one Perfetto-loadable trace.json. The reference
    had only the Worker display line (src/worker/worker.cc:350-386);
    this block is its post-mortem-grade replacement."""

    FIELDS = {
        # master switch: false silences the event log, span recording,
        # and the profile@K trigger (the display line is unaffected)
        "enabled": Field("bool", True),
        # record every timed phase occurrence (train/data/eval/ckpt,
        # feeder/stager threads, async-ckpt writer, coord barriers) as a
        # span — the Chrome-trace tracks. false = lifecycle events only.
        "trace_spans": Field("bool", True),
        # per-rank event logs land in <workspace>/<events_subfolder>/
        "events_subfolder": Field("string", "events"),
        # jax.profiler traces from profile@K triggers land in
        # <workspace>/<profile_subfolder>/
        "profile_subfolder": Field("string", "xprof"),
    }


class ModelConfig(Message):
    FIELDS = {
        "name": Field("string"),
        "train_folder": Field("string", "train"),
        "test_folder": Field("string", "test"),
        "validation_folder": Field("string", "validation"),
        "display_after_steps": Field("int", 0),
        "display_frequency": Field("int", 0),
        "validation_after_steps": Field("int", 0),
        "validation_frequency": Field("int", 0),
        "test_after_steps": Field("int", 0),
        "test_frequency": Field("int", 0),
        "prefetch": Field("bool", True),
        "train_steps": Field("int"),
        "validation_steps": Field("int"),
        "test_steps": Field("int"),
        "step": Field("int", 0),
        "updater": Field("message", message=UpdaterConfig),
        "alg": Field("enum", "kBackPropagation", enum=GRAD_CALC_ALGS),
        "neuralnet": Field("message", message=NetConfig),
        "debug": Field("bool", False),
        # --- singa-tpu extensions: checkpoint restore path + save cadence
        # (fills the reference's unimplemented Worker::Resume,
        # worker.cc:65-67; the reference has no snapshot cadence at all) ---
        "checkpoint": Field("string"),
        "checkpoint_frequency": Field("int", 0),
        "checkpoint_after_steps": Field("int", 0),
        # "npz": one gathered file (small models); "sharded": per-process
        # shard files, arrays stay device-sharded end to end (pods) —
        # restore auto-detects the format from the path
        "checkpoint_format": Field("enum", "npz", enum=("npz", "sharded")),
        # --- singa-tpu extension: ZeRO-style cross-replica update
        # sharding (arxiv 2004.13336; parallel/shardings.py
        # zero_update_shardings). true = reduce-scatter grads to
        # per-rank shards over the data axis, run the optimizer on each
        # rank's shard only (updater slots live sharded, shrinking
        # per-device opt-state bytes by the data-parallel degree), and
        # allgather fresh params for the next forward. Loss-identical
        # to the replicated update (the math between the collectives is
        # elementwise); false = the reference's replicated update. ---
        "zero_update": Field("bool", False),
        # --- singa-tpu extension: quantized + overlapped gradient
        # collectives (parallel/collectives.py; see GradCommConfig).
        # Absent = the exact fp32 gradient collective. ---
        "grad_comm": Field("message", message=GradCommConfig),
        # --- singa-tpu extension: mixed-precision compute. Params stay
        # fp32 (master copies, updater math in fp32); forward/backward
        # matmuls run in this dtype so the MXU sees bf16. "" = fp32. ---
        "compute_dtype": Field("string", ""),
        # --- singa-tpu extension: microbatches per step for pipeline
        # parallelism (layers staged by locationid over the cluster's
        # pipe axis). 0 = the pipe width (the GPipe minimum); more
        # microbatches shrink the fill/drain bubble. ---
        "pipeline_microbatches": Field("int", 0),
        # --- singa-tpu extension: fault-tolerance runtime (supervised
        # auto-resume, preemption drain, divergence guard, watchdog) ---
        "resilience": Field("message", message=ResilienceConfig),
        # --- singa-tpu extension: flight-recorder telemetry plane
        # (singa_tpu/obs/). Absent = enabled with defaults ---
        "telemetry": Field("message", message=TelemetryConfig),
        # --- singa-tpu extension: serving tier (singa_tpu/serve/) —
        # continuous-batching inference with a paged KV cache. Absent =
        # serving defaults (tools/serve_bench.py, tools/generate.py) ---
        "serving": Field("message", message=ServingConfig),
        # --- singa-tpu extension: per-site kernel selection (Pallas
        # hot paths, singa_tpu/ops/paged_attention.py). Absent = every
        # site runs its reference oracle path ---
        "kernels": Field("message", message=KernelsConfig),
        # --- singa-tpu extension: disaggregated serving fleet
        # (singa_tpu/serve/fleet/) — presence dispatches main.py to a
        # fleet host (role by rank) instead of the trainer ---
        "fleet": Field("message", message=FleetConfig),
        # --- singa-tpu extension: two-level ring geometry for
        # kernels { grad_allreduce: q8_hier } (see RingConfig). Absent
        # with q8_hier = ConfigError at trainer construction. ---
        "ring": Field("message", message=RingConfig),
    }


class ClusterConfig(Message):
    FIELDS = {
        "nworkers": Field("int"),
        "nservers": Field("int", 0),
        "start_port": Field("int", 6723),
        "nprocs_per_group": Field("int", 1),
        "nthreads_per_procs": Field("int", 1),
        "nthreads_per_server": Field("int", 1),
        "workspace": Field("string", required=True),
        "vis_subfolder": Field("string", "vis"),
        "log_subfolder": Field("string", "log"),
        "synchronous": Field("bool", False),
        "largest_message": Field("int", 1048576),
        "bandwidth": Field("float", 100.0),
        # ---- singa-tpu extensions: how nprocs_per_group splits across
        # the intra-group parallelism axes. The reference's only
        # intra-group axis is kLayerPartition (tensor/model); sequence
        # (ring attention), expert (kMoE), and pipeline (locationid
        # stages) are new. model width = nprocs_per_group /
        # (nseq * nexperts * npipes); must divide evenly.
        "nseq_per_group": Field("int", 1),
        "nexperts_per_group": Field("int", 1),
        "npipes_per_group": Field("int", 1),
        # ---- singa-tpu extension: persistent XLA compilation cache.
        # main.py wires jax's compile cache to this directory so repeat
        # runs skip recompilation (BENCH_r05 measured 60-135 ms of fixed
        # per-run startup, mostly XLA compiles). "" = default
        # <workspace>/compile_cache; "off" disables; the
        # SINGA_TPU_COMPILE_CACHE env var overrides either.
        "compile_cache_dir": Field("string", ""),
        # ---- singa-tpu extension: per-device HBM budget in bytes for
        # the cost-aware shardlint (lint/cost_model.py). When > 0,
        # netlint's MEM001 errors on any model conf whose predicted
        # per-device footprint (params + optimizer slots + residuals +
        # activation working set + serving KV pool) exceeds it — the
        # static mirror of the OOM the pod would hit. 0 (default) =
        # no declared budget, MEM001 stays silent.
        "device_hbm_bytes": Field("int", 0),
        # ---- singa-tpu extension: inter-slice (DCN) bandwidth in
        # bytes/sec for the cost-aware shardlint. When > 0 and the
        # model runs the hierarchical ring (q8_hier), --explain-cost
        # prices the scarce inter-slice hop's transfer time from the
        # per-level wire model. 0 (default) = no declared bandwidth.
        "inter_slice_bandwidth": Field("int", 0),
    }

    @property
    def axis_widths(self) -> dict[str, int]:
        """Mesh axis widths {data, pipe, expert, seq, model} implied by
        the topology fields. See parallel.mesh.mesh_from_cluster."""
        npg = max(1, self.nprocs_per_group)
        nseq = max(1, self.nseq_per_group)
        nexp = max(1, self.nexperts_per_group)
        npipe = max(1, self.npipes_per_group)
        inner = nseq * nexp * npipe
        if npg % inner:
            raise ConfigError(
                f"nprocs_per_group ({npg}) not divisible by nseq*nexperts*"
                f"npipes ({nseq}*{nexp}*{npipe}={inner})"
            )
        return {
            "data": self.ngroups,
            "pipe": npipe,
            "expert": nexp,
            "seq": nseq,
            "model": npg // inner,
        }

    @property
    def ngroups(self) -> int:
        """Number of worker groups = data-parallel replicas.

        Reference: include/utils/cluster.h:49-50 — workers are partitioned
        into groups of ``nprocs_per_group`` (plain integer division). A
        config with nworkers < nprocs_per_group would yield zero groups in
        the reference and silently do nothing; we reject it explicitly.
        """
        if not self.nworkers:
            return 1
        npg = max(1, self.nprocs_per_group)
        if self.nworkers < npg:
            raise ConfigError(
                f"nworkers ({self.nworkers}) < nprocs_per_group ({npg}): "
                "yields zero worker groups"
            )
        return self.nworkers // npg


def load_model_config(path: str) -> ModelConfig:
    return ModelConfig.from_file(path)


def load_cluster_config(path: str) -> ClusterConfig:
    return ClusterConfig.from_file(path)


def parse_model_config(text: str) -> ModelConfig:
    return ModelConfig.from_text(text)


def parse_cluster_config(text: str) -> ClusterConfig:
    return ClusterConfig.from_text(text)
