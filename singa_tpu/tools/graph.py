"""Render a net-structure JSON dump as graphviz dot.

The reference renders NeuralNet::ToString's node-link JSON with pydot
(script/graph.py reading the vis_folder dumps, src/utils/graph.cc:8-59).
This emits the .dot source directly — no graphviz python binding needed;
`dot -Tpdf` or any viewer takes it from there.

Usage:
  python -m singa_tpu.tools.graph --input ws/visualization/kTrain.json \
      [--output net.dot]
"""

from __future__ import annotations

import argparse
import json
import sys


_SHAPES = {
    "kShardData": "cylinder",
    "kLMDBData": "cylinder",
    "kMnistImage": "parallelogram",
    "kRGBImage": "parallelogram",
    "kLabel": "parallelogram",
    "kSoftmaxLoss": "doubleoctagon",
    "kEuclideanLoss": "doubleoctagon",
}


def net_json_to_dot(doc: dict) -> str:
    """Node-link JSON ({nodes: [{id, ...}], links: [{source, target}]})
    -> dot source. Node attributes beyond ``id`` become label lines."""
    lines = [
        "digraph net {",
        "  rankdir=BT;",  # data at the bottom, loss on top, like a net
        '  node [shape=box, fontname="monospace"];',
    ]
    for node in doc.get("nodes", []):
        nid = node["id"]
        extra = [
            f"{k}: {v}"
            for k, v in node.items()
            if k not in ("id",) and v not in (None, "", [])
        ]
        label = "\\n".join([str(nid)] + extra)
        shape = _SHAPES.get(node.get("type"))
        attr = f' [label="{label}"' + (f", shape={shape}" if shape else "") + "]"
        lines.append(f'  "{nid}"{attr};')
    for link in doc.get("links", []):
        lines.append(f'  "{link["source"]}" -> "{link["target"]}";')
    lines.append("}")
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="singa_tpu.tools.graph")
    ap.add_argument("--input", required=True, help="net JSON dump")
    ap.add_argument("--output", default=None, help="dot file (default stdout)")
    args = ap.parse_args(argv)
    with open(args.input) as f:
        dot = net_json_to_dot(json.load(f))
    if args.output:
        with open(args.output, "w") as f:
            f.write(dot)
        print(f"wrote {args.output}")
    else:
        sys.stdout.write(dot)
    return 0


if __name__ == "__main__":
    sys.exit(main())
