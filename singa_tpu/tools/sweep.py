"""Scaling sweep: the reference's batch.sh as a harness.

batch.sh reruns a job over nworkers in {1,2,4,8,16}, rewriting
cluster.conf each time and logging to log1k/NwMsTt
(examples/mnist/batch.sh:3-17). Here each sweep point runs the job for a
fixed step count on an nworkers-device mesh and reports samples/sec plus
scaling efficiency vs the smallest point — the BASELINE.md ">=70% from 8
to 64 chips" bar, measurable ahead of hardware on a virtual CPU mesh.

Each point runs in a fresh subprocess because the XLA device-count flag
must be set before jax import (and real multi-host runs are one process
per host anyway, like run.sh's ssh fan-out).

Usage:
  python -m singa_tpu.tools.sweep --model_conf job.conf \
      [--workers 1 2 4 8] [--steps 30] [--virtual] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _child(model_conf: str, nworkers: int, steps: int,
           zero_update: bool = False, grad_comm: str = "") -> None:
    """Run `steps` training steps on an nworkers-wide data mesh; print one
    JSON line. Runs inside the sweep's subprocess (env already set)."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # images whose sitecustomize pre-registers a real accelerator
        # need the config re-pin on top of the env var (same dance as
        # __graft_entry__.dryrun_multichip)
        jax.config.update("jax_platforms", "cpu")

    from ..config import load_model_config
    from ..parallel import build_mesh
    from ..trainer import make_trainer

    cfg = load_model_config(model_conf)
    cfg.train_steps = steps
    cfg.test_steps = cfg.validation_steps = 0
    cfg.display_frequency = 0
    cfg.checkpoint_frequency = 0
    if zero_update:
        cfg.zero_update = True
    if grad_comm:
        from ..parallel import apply_grad_comm_tag

        apply_grad_comm_tag(cfg, grad_comm)
    mesh = build_mesh(nworkers, 1, jax.devices()[:nworkers])
    trainer = make_trainer(cfg, None, mesh=mesh, log=lambda s: None)
    warmup = min(3, steps - 1)
    for step in range(warmup):
        trainer.train_one_batch(step)
    jax.block_until_ready(trainer.params)
    t0 = time.perf_counter()
    for step in range(warmup, steps):
        trainer.train_one_batch(step)
    jax.block_until_ready(trainer.params)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "nworkers": nworkers,
        "batch": trainer.train_net.batchsize,
        "samples_per_sec": (steps - warmup) * trainer.train_net.batchsize / dt,
        # which input path and update layout fed the point (bench.py's
        # feeder/update_mode row fields) — a scaling knee stays
        # attributable to the data path or the update sharding
        "feeder": trainer.feeder_mode,
        "update_mode": trainer.update_mode,
        "opt_state_bytes_per_device": trainer.opt_state_bytes_per_device(),
        # how gradients crossed the data axis at this point (exact /
        # quantized + wire dtype) and the machinery's isolated marginal
        # ms — a scaling knee stays attributable to the collective
        "comm_mode": trainer.comm_mode,
        "comm_dtype": trainer.comm_dtype,
        "comm_ms": round(_comm_ms(trainer), 3),
    }))


def _comm_ms(trainer) -> float:
    from .collective_stall import measure_comm_ms

    return measure_comm_ms(trainer, i1=2, i2=6, trials=1)


def run_sweep(
    model_conf: str,
    workers: list[int],
    steps: int,
    virtual: bool,
    zero_update: bool = False,
    grad_comm: str = "",
) -> list[dict]:
    results = []
    for nw in workers:
        env = dict(os.environ)
        if virtual:
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={nw}"
            ).strip()
        proc = subprocess.run(
            [sys.executable, "-m", "singa_tpu.tools.sweep", "--_child",
             "--model_conf", model_conf, "--nworkers", str(nw),
             "--steps", str(steps)]
            + (["--zero_update"] if zero_update else [])
            + (["--grad_comm", grad_comm] if grad_comm else []),
            env=env, capture_output=True, text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"sweep point nworkers={nw} failed:\n{proc.stderr[-2000:]}"
            )
        line = proc.stdout.strip().splitlines()[-1]
        results.append(json.loads(line))
    base = results[0]
    for r in results:
        ideal = base["samples_per_sec"] * r["nworkers"] / base["nworkers"]
        r["efficiency"] = r["samples_per_sec"] / ideal
    return results


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="singa_tpu.tools.sweep")
    ap.add_argument("--model_conf", required=True)
    ap.add_argument("--workers", type=int, nargs="+", default=[1, 2, 4, 8])
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--virtual", action="store_true",
                    help="CPU-hosted virtual devices (set automatically "
                    "when the host has no accelerator plurality)")
    ap.add_argument("--zero_update", action="store_true",
                    help="sweep with the ZeRO update sharding "
                    "(zero_update: true) — opt-state bytes per device "
                    "should FALL as nworkers grows")
    ap.add_argument("--grad_comm", default="",
                    choices=("", "exact", "q8", "q8wire", "bf16"),
                    help="sweep with a grad_comm block (q8 = quantized "
                    "int8 + error feedback; bf16 = quantized bf16) — "
                    "the quantized wire format should HOLD efficiency "
                    "as the data axis widens")
    ap.add_argument("--json", default=None, help="also write results here")
    ap.add_argument("--_child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--nworkers", type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args._child:
        _child(args.model_conf, args.nworkers, args.steps,
               zero_update=args.zero_update, grad_comm=args.grad_comm)
        return 0

    results = run_sweep(args.model_conf, args.workers, args.steps,
                        args.virtual, zero_update=args.zero_update,
                        grad_comm=args.grad_comm)
    print(
        f"{'nworkers':>8} {'batch':>6} {'samples/s':>12} {'efficiency':>10} "
        f"{'update':>10} {'opt-B/dev':>10} {'comm':>14} {'comm-ms':>8}"
    )
    for r in results:
        comm = r["comm_mode"] + (f":{r['comm_dtype']}" if r["comm_dtype"]
                                 else "")
        print(
            f"{r['nworkers']:>8} {r['batch']:>6} "
            f"{r['samples_per_sec']:>12.0f} {r['efficiency']:>10.2f} "
            f"{r['update_mode']:>10} {r['opt_state_bytes_per_device']:>10} "
            f"{comm:>14} {r['comm_ms']:>8.3f}"
        )
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
