"""Partition dataset records across worker groups.

The reference's script/load_data.py slices a record-id list into
per-group shares (integer division, remainder dropped), then within a
group either *replicates* the share to every worker (data-parallel
groups read the same records) or splits it per worker
(load_data.py:partition). The ssh/scp distribution plumbing becomes
plain local directory writes here — on TPU the "workers" are per-host
input pipelines reading their own shard directory.

Usage:
  python -m singa_tpu.tools.partition --input SHARD --output-prefix P \
      --nworkers 8 --group-size 2 [--replicate]
produces P-w0 .. P-w7 shard dirs (or rid.txt lists with --rid-list).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Sequence, TypeVar

T = TypeVar("T")


def partition_records(
    records: Sequence[T],
    nworkers: int,
    group_size: int,
    replicate: bool = False,
) -> list[list[T]]:
    """Return per-worker record lists with the reference's slicing.

    load_data.py semantics: ngroups = nworkers // group_size; each group
    gets records[g*n : (g+1)*n] with n = len(records) // ngroups; within a
    group the share is replicated to all members or split evenly
    (remainders truncate, exactly like the reference's integer division).
    """
    if group_size <= 0 or nworkers <= 0 or nworkers % group_size:
        raise ValueError(
            f"nworkers ({nworkers}) must be a positive multiple of "
            f"group_size ({group_size})"
        )
    ngroups = nworkers // group_size
    per_group = len(records) // ngroups
    out: list[list[T]] = []
    for g in range(ngroups):
        share = list(records[g * per_group : (g + 1) * per_group])
        if replicate:
            out.extend([share] * group_size)
        else:
            per_worker = per_group // group_size
            out.extend(
                share[k * per_worker : (k + 1) * per_worker]
                for k in range(group_size)
            )
    return out


def main(argv: list[str] | None = None) -> int:
    from ..data.shard import ShardReader, ShardWriter

    ap = argparse.ArgumentParser(prog="singa_tpu.tools.partition")
    ap.add_argument("--input", required=True,
                    help="shard dir, or a rid.txt with --rid-list")
    ap.add_argument("--output-prefix", required=True)
    ap.add_argument("--nworkers", type=int, required=True)
    ap.add_argument("--group-size", type=int, default=1)
    ap.add_argument("--replicate", action="store_true",
                    help="every worker in a group gets the group's share")
    ap.add_argument("--rid-list", action="store_true",
                    help="partition a text record list instead of a shard")
    args = ap.parse_args(argv)

    if args.rid_list:
        with open(args.input) as f:
            records = [ln for ln in f.read().splitlines() if ln.strip()]
        shares = partition_records(
            records, args.nworkers, args.group_size, args.replicate
        )
        for w, share in enumerate(shares):
            path = f"{args.output_prefix}-w{w}.txt"
            with open(path, "w") as f:
                f.write("\n".join(share) + ("\n" if share else ""))
            print(f"worker {w}: {len(share)} records -> {path}")
        return 0

    with ShardReader(args.input) as reader:
        records = list(reader)
    shares = partition_records(
        records, args.nworkers, args.group_size, args.replicate
    )
    for w, share in enumerate(shares):
        folder = f"{args.output_prefix}-w{w}"
        os.makedirs(folder, exist_ok=True)
        with ShardWriter(folder, append=True) as wr:
            for k, v in share:
                wr.insert(k, v)
            wr.flush()
        print(f"worker {w}: {len(share)} records -> {folder}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
