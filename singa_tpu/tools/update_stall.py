"""Measure the update-phase stall: replicated vs ZeRO-sharded update.

The zero_update claim (parallel/shardings.py zero_update_shardings) is
that reduce-scattering gradients, updating each rank's shard only, and
allgathering fresh params shrinks per-device optimizer state by the
data-parallel degree WITHOUT slowing the step down: the collectives
move the same bytes as the replicated update's all-reduce, and the
update math itself shrinks per device. This tool — the sibling of
ckpt_stall / input_stall — measures it by timing the same small MLP job
on an ``ndata``-wide virtual data mesh both ways:

  replicated  every rank applies the full update (the reference's
              ParamSync semantics)
  zero        reduce-scatter grads -> shard-local optimizer ->
              allgather params (update_mode "zero")

and printing one JSON line::

  {"replicated_step_ms": .., "zero_step_ms": .., "ratio": ..,
   "replicated_update_ms": .., "zero_update_ms": ..,
   "opt_bytes_replicated": .., "opt_bytes_zero": .., "opt_bytes_ratio": ..,
   "threshold": .., "pass": ..}

Exit status 0 iff zero/replicated step time <= ``threshold`` (default
1.05: the sharded update may cost at most 5% on the CPU host, where
emulated collectives are memcpys and the shard-local math win cannot
show) AND per-device opt-state bytes actually shrank. On a real
accelerator the zero update should win outright once optimizer state
stops fitting replicated.

``measure_update_ms`` is importable (bench.py and the MULTICHIP dryrun
reuse it): it slope-fits the update phase in isolation — one jitted
program running N chained updater applications — so the reported ms is
the marginal per-update cost, free of dispatch latency.

Usage::

  python -m singa_tpu.tools.update_stall [--steps N] [--warmup N]
      [--trials N] [--batch N] [--hidden N] [--ndata N] [--threshold R]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def measure_update_ms(trainer, i1: int = 4, i2: int = 20,
                      trials: int = 3) -> float:
    """Slope-fit the update phase in isolation: jit a program running N
    chained ``_constrain_grads`` + ``_apply_update`` rounds (zeros
    grads — the same dense elementwise math) on non-donated copies of
    the live state, time two window sizes, and return the marginal
    per-update cost in ms (bench.py's two-window methodology)."""
    import jax
    import jax.numpy as jnp

    grads = jax.tree.map(jnp.zeros_like, trainer.params)

    def make(n):
        def prog(params, state, grads):
            def body(carry, i):
                p, s = carry
                g = trainer._constrain_grads(grads)
                return trainer._apply_update(i, p, g, s), jnp.float32(0)

            (p, s), _ = jax.lax.scan(
                body, (params, state), jnp.arange(n)
            )
            return p, s

        # inputs are the LIVE params/state — never donate them
        return jax.jit(prog)  # netlint: disable=JAX003

    fns = {n: make(n) for n in (i1, i2)}

    def run(n) -> float:
        t0 = time.perf_counter()
        p, _ = fns[n](trainer.params, trainer.state, grads)
        # value materialization, not block_until_ready (the tunnel can
        # let block_until_ready return early — bench.py's methodology)
        float(jnp.sum(jnp.abs(next(iter(p.values())))))
        return time.perf_counter() - t0

    for n in fns:  # compile
        run(n)
    best = {n: float("inf") for n in fns}
    for _ in range(trials):
        for n in fns:
            best[n] = min(best[n], run(n))
    # floor at 0: on a contended host a tiny update's window delta can
    # sink under dispatch jitter — a negative marginal ms must never
    # poison bench rows or the stall JSON
    return max(0.0, (best[i2] - best[i1]) / (i2 - i1) * 1e3)


def _make_runner(shard: str, batch: int, hidden: int, warmup: int,
                 zero: bool, ndata: int):
    """-> (trainer, window(steps) -> (seconds, steps)) for one mode.

    Both modes run the identical per-step sync loop on the same
    ndata-wide data mesh (device_cache off so the step is the honest
    assemble + step path, like input_stall's sync baseline); only the
    update layout differs."""
    import jax
    import jax.numpy as jnp

    from ..config import parse_model_config
    from ..parallel import build_mesh
    from ..trainer import Trainer
    from .input_stall import _CONF

    cfg = parse_model_config(_CONF.format(shard=shard, batch=batch,
                                          hidden=hidden, head=10))
    cfg.zero_update = zero
    mesh = build_mesh(ndata, 1, jax.devices()[:ndata])
    trainer = Trainer(
        cfg, seed=0, log=lambda s: None, mesh=mesh,
        prefetch=False, device_cache=False,
    )
    assert trainer.update_mode == ("zero" if zero else "replicated")

    def sync() -> float:
        return float(jnp.sum(jnp.abs(next(iter(trainer.params.values())))))

    state = {"step": 0}

    def run(steps: int) -> None:
        step0 = state["step"]
        for s in range(step0, step0 + steps):
            trainer.train_one_batch(s)
        state["step"] = step0 + steps

    run(warmup)  # compile
    sync()

    def window(steps: int) -> float:
        t0 = time.perf_counter()
        run(steps)
        sync()
        return time.perf_counter() - t0

    return trainer, window


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="update_stall", description=__doc__)
    ap.add_argument("--steps", type=int, default=12, help="timed steps")
    ap.add_argument("--warmup", type=int, default=4, help="untimed steps")
    ap.add_argument(
        "--trials", type=int, default=3,
        help="windows per mode; the best (least-contended) one counts",
    )
    # the probe regime: a compute-representative step (~85 ms at batch
    # 8192 on the 2-core host) against which the zero update's fixed
    # per-step collective cost (an emulated reduce-scatter + param
    # allgather, ~1 ms of memcpys here) is the honest small share it is
    # on real models — measured ratio 0.92-1.01. A tiny-step probe
    # (batch 512, ~8 ms steps) measures the emulation overhead instead
    # of the update sharding (~1.12 there), the same host-steals-from-
    # itself artifact input_stall documents for its per-step feeder.
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--records", type=int, default=8192,
                    help="synthetic dataset size")
    ap.add_argument("--ndata", type=int, default=2,
                    help="data-axis width (virtual CPU devices)")
    ap.add_argument(
        "--threshold", type=float, default=1.05,
        help="max allowed zero/replicated step-time ratio",
    )
    args = ap.parse_args(argv)

    # the device-count flag must land before the first backend query
    # (__graft_entry__.dryrun_multichip's dance)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.ndata}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..data.loader import synthetic_arrays, write_records

    root = tempfile.mkdtemp(prefix="singa_tpu_update_stall_")
    shard = os.path.join(root, "shard")
    write_records(shard, *synthetic_arrays(args.records, seed=0))
    runners = {
        mode: _make_runner(shard, args.batch, args.hidden, args.warmup,
                           mode == "zero", args.ndata)
        for mode in ("replicated", "zero")
    }
    # INTERLEAVED best-of-trials (ckpt/input_stall's methodology): one
    # window per mode per round so host-load bursts land on both modes
    best = {mode: float("inf") for mode in runners}
    for _ in range(args.trials):
        for mode, (_, window) in runners.items():
            best[mode] = min(best[mode], window(args.steps) / args.steps)
    repl_ms = best["replicated"] * 1e3
    zero_ms = best["zero"] * 1e3
    t_repl, _ = runners["replicated"]
    t_zero, _ = runners["zero"]
    ob_repl = t_repl.opt_state_bytes_per_device()
    ob_zero = t_zero.opt_state_bytes_per_device()
    shrank = args.ndata == 1 or ob_zero < ob_repl
    ok = zero_ms <= repl_ms * args.threshold and shrank
    out = {
        "replicated_step_ms": round(repl_ms, 3),
        "zero_step_ms": round(zero_ms, 3),
        "ratio": round(zero_ms / repl_ms, 3),
        "replicated_update_ms": round(measure_update_ms(t_repl), 3),
        "zero_update_ms": round(measure_update_ms(t_zero), 3),
        "opt_bytes_replicated": ob_repl,
        "opt_bytes_zero": ob_zero,
        "opt_bytes_ratio": round(ob_zero / ob_repl, 3) if ob_repl else None,
        "ndata": args.ndata,
        "threshold": args.threshold,
        "pass": ok,
    }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
