"""Sample from a trained config-surface LM checkpoint.

Beyond-parity extension (the reference has no inference path at all —
SURVEY §5, pre-transformer system): completes the conf-driven train ->
sample loop for the byte-level LM jobs (examples/lm/tinylm*.conf).

    python -m singa_tpu.tools.generate \
        -model_conf examples/lm/tinylm.conf \
        -checkpoint ws/checkpoints/step_2000.npz \
        -prompt "hello " -n 64 [-temperature 0.8] [-seed 0]

Design: decode rides the serving tier's KV-cache path
(serve/conf_decode.NetDecoder) whenever the net's graph supports
incremental apply and the requested length fits the positional table:
chunked prefill writes the prompt's K/V once, then every emitted token
is one (1, 1) cached step instead of a full (1, S) forward — O(1)
recompute per token where the old rolling-buffer decode paid O(S).
Unsupported graphs (convs, kMoE, staged pipelines) and
beyond-the-window generations fall back to that rolling decode: a
(1, S) buffer, prompt left-aligned, logits read at the last live
position via return_acts — slower, never wrong.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="singa_tpu.tools.generate", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("-model_conf", required=True)
    ap.add_argument("-checkpoint", required=True)
    ap.add_argument("-prompt", default="")
    ap.add_argument("-n", type=int, default=64)
    ap.add_argument("-temperature", type=float, default=0.0)
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument(
        "-raw", action="store_true",
        help="emit token ids (one line) instead of decoding bytes",
    )
    return ap


def _ensure_shard(cfg, vocab: int) -> None:
    """build_net reads the data shard (it infers vocab from the token
    stream); when the training shard is gone, synthesize a stub whose
    max token pins the same vocab."""
    import tempfile

    from ..data.loader import write_records

    for layer in cfg.neuralnet.layer:
        p = layer.data_param
        if layer.type == "kSequenceData" and p is not None:
            if not os.path.exists(p.path):
                stub = np.zeros((2, 16), dtype=np.uint8)
                stub[0, 0] = vocab - 1
                tmp = tempfile.mkdtemp(prefix="singa_gen_stub_")
                path = os.path.join(tmp, "stub_shard")
                write_records(path, stub, np.zeros((2,), np.uint8))
                p.path = path


def generate_from_net(net, params, prompt_tokens, n: int,
                      temperature: float, seed: int,
                      log=lambda s: None, serving=None) -> list[int]:
    """Decode over the conf net: KV-cache path when the graph supports
    it (serve/conf_decode.py), rolling-buffer recompute otherwise.
    ``serving`` is the job's parsed ``serving { }`` config block (None =
    defaults); its ``max_prefill_chunk`` sizes the prefill chunks here —
    the slot/kv-pool knobs configure the slot-batched Engine, which a
    single-stream CLI sample does not build."""
    from ..serve.conf_decode import NetDecoder, UnsupportedNet
    from ..serve.engine import EngineConfig

    try:
        dec = NetDecoder(
            net,
            max_prefill_chunk=EngineConfig.from_conf(
                serving
            ).max_prefill_chunk,
        )
        return dec.generate(params, prompt_tokens, n, temperature, seed)
    except UnsupportedNet as e:
        log(f"generate: KV-cache decode unavailable ({e}); "
            "falling back to rolling-buffer recompute")
    return rolling_generate_from_net(
        net, params, prompt_tokens, n, temperature, seed
    )


def rolling_generate_from_net(net, params, prompt_tokens, n: int,
                              temperature: float, seed: int) -> list[int]:
    """Rolling-buffer greedy/temperature decode over the conf net (the
    pre-serving-tier path; kept as the universal fallback and as the
    reference oracle the KV-cache path is tested against)."""
    import jax
    import jax.numpy as jnp

    (dl,) = net.datalayers
    # sequence length = the data layer's declared window
    s = dl.out_shape[1]
    # the logits layer is whatever feeds the LM loss
    (loss_layer,) = net.losslayers
    head = next(
        src for src in loss_layer.srclayers if src != dl.name
    )

    @jax.jit
    def logits_at(params, tokens, pos):
        batch = {dl.name: {"image": tokens, "label": jnp.zeros((1,), jnp.int32)}}
        _, _, acts = net.forward(
            params, batch, training=False, rng=None, return_acts=True
        )
        return acts[head][0, pos]

    toks = list(prompt_tokens)
    if not toks:
        toks = [0]
    if len(toks) >= s:
        toks = toks[-(s - 1):]
    rng = jax.random.PRNGKey(seed)
    out = list(toks)
    for _ in range(n):
        window = out[-(s - 1):] if len(out) >= s else out
        buf = np.zeros((1, s), np.int32)
        buf[0, : len(window)] = window
        lg = logits_at(params, jnp.asarray(buf), len(window) - 1)
        if temperature <= 0.0:
            nxt = int(jnp.argmax(lg))
        else:
            rng, k = jax.random.split(rng)
            nxt = int(jax.random.categorical(k, lg / temperature))
        out.append(nxt)
    return out


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    from ..config import load_model_config
    from ..graph.builder import build_net
    from ..trainer.checkpoint import load_checkpoint

    step, params, _state, _buffers = load_checkpoint(args.checkpoint)
    embed = next(
        (v for k, v in params.items() if k.endswith("/tok")), None
    )
    if embed is None:
        print("checkpoint has no token embedding (not an LM job?)",
              file=sys.stderr)
        return 2
    vocab = embed.shape[0]
    cfg = load_model_config(args.model_conf)
    _ensure_shard(cfg, vocab)
    net = build_net(cfg, "kTest")

    import jax.numpy as jnp

    params = {k: jnp.asarray(v) for k, v in params.items()}
    prompt = [b % vocab for b in args.prompt.encode()]
    toks = generate_from_net(
        net, params, prompt, args.n, args.temperature, args.seed,
        log=lambda s: print(s, file=sys.stderr), serving=cfg.serving,
    )
    if args.raw:
        print(" ".join(str(t) for t in toks))
    else:
        sys.stdout.buffer.write(bytes(t % 256 for t in toks))
        sys.stdout.buffer.write(b"\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
