"""Elastic gang launcher: relaunch resumable gangs under a restart
budget, optionally at a different process count.

Usage:
  python -m singa_tpu.tools.elastic_launch \\
      -model_conf job.conf -cluster_conf cluster.conf -nprocs 2

Spawns ``-nprocs`` ranks of ``python -m singa_tpu.main`` (a generated
localhost hostfile carries the rendezvous, the reference's run.sh
fan-out shape), waits for the gang, and:

  - every rank 0            -> done (exit 0)
  - every non-zero rank 75  -> the gang drained (preemption) or a rank
                               died and its peers' watchdogs followed —
                               RELAUNCH the whole gang from the newest
                               committed checkpoint, while the
                               ``resilience { max_restarts_per_window,
                               restart_window_s }`` budget grants
                               (resilience/launcher.py); the in-process
                               circuit breaker never sees these exits,
                               which is exactly why the launcher needs
                               its own budget
  - any other status        -> fatal; surface it, never replay it

``-resize_after N`` relaunches at a different nprocs once N resumable
exits have happened — the elastic drill: the reshard-on-restore path
(resilience/reshard.py) re-slices the drained checkpoint onto the new
world size, so shrinking a preempted 8-host gang to whatever capacity
is left is one flag, not a migration project.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

from ..config import load_model_config
from ..resilience.launcher import RestartBudget, supervise_gang


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _write_hostfile(workdir: str, nprocs: int) -> str:
    path = os.path.join(workdir, f"hostfile_{os.getpid()}")
    with open(path, "w") as f:
        f.write(f"127.0.0.1:{_free_port()}\n")
        f.write("127.0.0.1\n" * (nprocs - 1))
    return path


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        prog="singa_tpu.tools.elastic_launch", description=__doc__
    )
    ap.add_argument("-model_conf", required=True)
    ap.add_argument("-cluster_conf", default=None)
    ap.add_argument("-nprocs", type=int, default=1)
    ap.add_argument(
        "-resize_to", type=int, default=0,
        help="relaunch at this nprocs instead (0 = keep -nprocs)",
    )
    ap.add_argument(
        "-resize_after", type=int, default=1,
        help="resumable exits before -resize_to takes effect",
    )
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument("-faults", default=None,
                    help="fault plan forwarded to EVERY rank")
    return ap.parse_args(argv)


def run_gang_once(args, nprocs: int, *, log=print) -> list[int]:
    """One gang attempt: spawn nprocs ranks, wait, return exit codes."""
    workdir = os.path.dirname(os.path.abspath(args.model_conf)) or "."
    hostfile = _write_hostfile(workdir, nprocs) if nprocs > 1 else None
    procs = []
    for rank in range(nprocs):
        argv = [
            sys.executable, "-m", "singa_tpu.main",
            "-model_conf", args.model_conf,
            "-procsID", str(rank),
            "-seed", str(args.seed),
        ]
        if args.cluster_conf:
            argv += ["-cluster_conf", args.cluster_conf]
        if hostfile:
            argv += ["-hostfile", hostfile]
        if args.faults:
            argv += ["-faults", args.faults]
        procs.append(subprocess.Popen(argv))
    codes = [p.wait() for p in procs]
    if hostfile:
        try:
            os.unlink(hostfile)
        except OSError:
            pass
    log(f"launcher: gang of {nprocs} exited {codes}")
    return codes


def main(argv=None) -> int:
    args = parse_args(argv)
    model_cfg = load_model_config(args.model_conf)
    budget = RestartBudget.from_config(
        getattr(model_cfg, "resilience", None)
    )
    state = {"nprocs": max(1, args.nprocs), "resumes": 0}

    def run_gang():
        return run_gang_once(args, state["nprocs"])

    def on_relaunch(attempt):
        del attempt
        state["resumes"] += 1
        if args.resize_to and state["resumes"] >= args.resize_after:
            if state["nprocs"] != args.resize_to:
                print(
                    f"launcher: resizing gang {state['nprocs']} -> "
                    f"{args.resize_to} ranks (elastic restore reshards "
                    "the drained checkpoint)"
                )
            state["nprocs"] = max(1, args.resize_to)

    return supervise_gang(
        run_gang, budget, log=print, on_relaunch=on_relaunch
    )


if __name__ == "__main__":
    sys.exit(main())
