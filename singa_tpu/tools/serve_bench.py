"""Serving-tier load harness: continuous batching vs one-at-a-time.

Drives a synthetic open-loop request workload (deterministic prompt
lengths / budgets from ``--seed``) through the serving tier
(serve/engine.py + serve/scheduler.py) and reports one JSON line::

  {"tokens_per_s": .., "seq_tokens_per_s": .., "speedup": ..,
   "p50_ms": .., "p99_ms": .., "slot_occupancy": ..,
   "kv_blocks_peak": .., "backpressure_ticks": .., "pass": ..}

The baseline reproduces the pre-serving behavior — one stream at a
time through ``models.transformer.generate`` (its whole decode is one
compiled scan, so this is a STRONG baseline: no per-token dispatch) —
and the gate demands continuous batching beat it by ``--threshold``
(default 2.0) at the configured concurrency. The win is physics, not
scheduling luck: decode is weight-streaming-bound, so S slots sharing
one weight read per tick emit S tokens for the bandwidth one stream
pays for one token. Both paths are compile-warmed before timing.

With ``--workspace`` the run records serving lifecycle events +
request/decode spans into the PR 6 flight recorder, so
``tools/trace.py <ws> --summarize`` reports serving p50/p99 out of the
box. ``--sigterm_at_tick K`` is the drain drill (the fault grammar's
synthetic-signal discipline): the serve loop installs the resilience
plane's PreemptionHandler, triggers it at tick K (a REAL SIGTERM works
identically), drains — every in-flight sequence handed back with its
partial output, accounted in the final JSON — and exits
EXIT_RESUMABLE (75). CI asserts the exit code and reconstructs
admit -> decode ticks -> drain -> exit from the merged trace.

Usage::

  python -m singa_tpu.tools.serve_bench [--concurrency 8] [--requests 16]
      [--threshold 2.0] [--d_model 256] [--n_layers 2] [--n_heads 4]
      [--vocab 256] [--max_len 128] [--prompt_len 8] [--max_new 32]
      [--block_len 16] [--kv_blocks 0] [--prefill_chunk 16]
      [--workspace DIR] [--sigterm_at_tick K] [--no_gate]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .trace import _percentile  # one percentile definition per package


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="serve_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--concurrency", type=int, default=8,
                    help="serving slots (decode batch width)")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--threshold", type=float, default=2.0,
                    help="min tokens/sec speedup over sequential generate")
    ap.add_argument("--d_model", type=int, default=256)
    ap.add_argument("--n_layers", type=int, default=2)
    ap.add_argument("--n_heads", type=int, default=4)
    ap.add_argument("--d_ff", type=int, default=1024)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--max_len", type=int, default=128)
    ap.add_argument("--prompt_len", type=int, default=8)
    ap.add_argument("--max_new", type=int, default=48)
    ap.add_argument("--block_len", type=int, default=16)
    ap.add_argument("--kv_blocks", type=int, default=0)
    ap.add_argument("--prefill_chunk", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workspace", default=None,
                    help="record serving telemetry under this workspace")
    ap.add_argument("--sigterm_at_tick", type=int, default=0,
                    help="drain drill: trigger the preemption plane at "
                    "this tick and exit 75 (0 = off)")
    ap.add_argument("--no_gate", action="store_true",
                    help="report only; never fail on the threshold")
    return ap


def _workload(args):
    """Deterministic request set: equal prompt/budget shapes so the
    sequential baseline compiles ONE program (anything else would
    charge the old path compile time the serving path does not pay)."""
    import numpy as np

    rs = np.random.RandomState(args.seed)
    return [
        rs.randint(0, args.vocab, size=(args.prompt_len,)).astype(np.int32)
        for _ in range(args.requests)
    ]


def run_scan_reference(params, cfg, prompts, max_new):
    """models.transformer.generate, one fused compiled scan per stream:
    the strongest possible single-stream number (zero per-token
    dispatch, impossible for a real server that must stream tokens back
    as they land). Reported for transparency, not gated. -> (tokens,
    elapsed_s, outputs)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.transformer import generate

    gen = jax.jit(lambda p, t: generate(p, t, cfg, max_new))
    # warm: one full compile outside the timed region
    np.asarray(gen(params, jnp.asarray(prompts[0][None])))
    outs = []
    t0 = time.perf_counter()
    for pr in prompts:
        outs.append(
            [int(t) for t in
             np.asarray(gen(params, jnp.asarray(pr[None])))[0, len(pr):]]
        )
    elapsed = time.perf_counter() - t0
    return sum(len(o) for o in outs), elapsed, outs


def run_continuous(params, cfg, prompts, args, slots, recorder=None,
                   preemption=None, sigterm_at_tick=0):
    """The serving stack at ``slots`` concurrency (slots=1 IS the
    one-at-a-time baseline: the same engine, streaming each request's
    tokens per tick, nothing batched). -> (scheduler, elapsed_s,
    drain accounting | None)."""
    import numpy as np

    from ..serve import Engine, EngineConfig, Request, Scheduler

    engine = Engine(
        params, cfg,
        EngineConfig(
            slots=slots,
            kv_block_len=args.block_len,
            kv_blocks=args.kv_blocks,
            max_prefill_chunk=args.prefill_chunk,
        ),
    )
    sched = Scheduler(engine, recorder=None, preemption=preemption)
    # warm THIS engine's two compiled programs (prefill + decode) with a
    # throwaway request, then zero the counters — jit caches live per
    # engine instance, so warming a twin engine would warm nothing (and
    # the recorder attaches only AFTER the warm, so compile time never
    # pollutes the serving percentiles)
    sched.submit(Request(rid=-1, prompt=np.asarray(prompts[0]),
                         max_new_tokens=2))
    sched.serve()
    sched.recorder = recorder
    sched.finished.clear()
    sched.ticks = sched.tokens_emitted = sched._live_ticks = 0
    sched.backpressure_ticks = 0
    sched.full_tick_s, sched.full_tick_tokens = 0.0, 0
    engine.allocator.peak_used = engine.allocator.used_blocks
    for i, pr in enumerate(prompts):
        sched.submit(Request(rid=i, prompt=pr, max_new_tokens=args.max_new,
                             seed=args.seed + i))
    if sigterm_at_tick:
        # deterministic drill: run to the tick, trigger the plane
        # (identical flag path to a real SIGTERM), then serve() drains
        t0 = time.perf_counter()
        sched.serve(max_ticks=sigterm_at_tick)
        preemption.trigger(f"sigterm_at_tick {sigterm_at_tick}")
        acct = sched.serve()
        return sched, time.perf_counter() - t0, acct
    t0 = time.perf_counter()
    acct = sched.serve()
    return sched, time.perf_counter() - t0, acct


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    import jax

    from ..models.transformer import TransformerConfig, init_lm
    from ..resilience.preemption import EXIT_RESUMABLE, PreemptionHandler

    cfg = TransformerConfig(
        vocab=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff, max_len=args.max_len,
    )
    params = init_lm(jax.random.PRNGKey(args.seed), cfg)
    prompts = _workload(args)

    recorder = None
    if args.workspace:
        import os

        from ..obs.recorder import FlightRecorder

        recorder = FlightRecorder(
            os.path.join(args.workspace, "events"), rank=0,
            run_id="serve_bench",
        )
        recorder.event("run_start", step=0, mode="serve_bench")
    handler = PreemptionHandler()
    handler.install()

    drill = bool(args.sigterm_at_tick)
    if not drill:
        # the gated baseline: the SAME serving stack, one stream at a
        # time (slots=1) — what tools/generate.py-style single-stream
        # serving pays per token. The fused-scan reference rides along
        # un-gated (see run_scan_reference).
        seq_sched, seq_s, _ = run_continuous(
            params, cfg, prompts, args, slots=1
        )
        seq_tokens = seq_sched.tokens_emitted + len(seq_sched.finished)
        scan_tokens, scan_s, scan_outs = run_scan_reference(
            params, cfg, prompts, args.max_new
        )
    sched, serve_s, acct = run_continuous(
        params, cfg, prompts, args, slots=args.concurrency,
        recorder=recorder, preemption=handler,
        sigterm_at_tick=args.sigterm_at_tick,
    )
    if acct is not None and not drill:
        # a REAL preemption arrived mid-benchmark: the serve loop
        # drained — report the accounting and exit resumable like every
        # other drained host, never fall through to the gate math over
        # a half-finished request set
        drill = True

    lat = sorted(r.latency_s * 1e3 for r in sched.finished)
    out = {
        "concurrency": args.concurrency,
        "requests": args.requests,
        "finished": len(sched.finished),
        "tokens": sched.tokens_emitted
        + sum(1 for r in sched.finished),  # + first tokens from prefill
        "serve_s": round(serve_s, 4),
        "tokens_per_s": round(
            (sched.tokens_emitted + len(sched.finished)) / serve_s, 1
        )
        if serve_s > 0
        else 0.0,
        "p50_ms": round(_percentile(lat, 0.50), 2),
        "p99_ms": round(_percentile(lat, 0.99), 2),
        **sched.occupancy(),
    }
    if not drill:
        out["seq_tokens_per_s"] = round(seq_tokens / seq_s, 1)
        out["scan_tokens_per_s"] = round(scan_tokens / scan_s, 1)
        out["speedup"] = round(
            out["tokens_per_s"] / out["seq_tokens_per_s"], 3
        ) if out["seq_tokens_per_s"] else None
        # steady-state capacity ratio: full-occupancy decode ticks only,
        # both sides (admission work is a per-request constant that a
        # long-running server amortizes to nothing; this is the number
        # the batched decode is responsible for)
        steady = steady_seq = 0.0
        if sched.full_tick_s > 0:
            steady = sched.full_tick_tokens / sched.full_tick_s
        if seq_sched.full_tick_s > 0:
            steady_seq = seq_sched.full_tick_tokens / seq_sched.full_tick_s
        out["steady_tokens_per_s"] = round(steady, 1)
        out["steady_seq_tokens_per_s"] = round(steady_seq, 1)
        out["steady_speedup"] = (
            round(steady / steady_seq, 3) if steady_seq else None
        )
        # tokens must MATCH the single-stream paths stream-for-stream —
        # throughput from wrong tokens is no throughput at all. Both
        # baselines vote: scan reference AND slots=1 serving.
        mismatches = sum(
            1
            for i, o in enumerate(scan_outs)
            if o != next(r for r in sched.finished if r.rid == i).tokens
            or o != next(
                r for r in seq_sched.finished if r.rid == i
            ).tokens
        )
        out["token_mismatches"] = mismatches
        out["threshold"] = args.threshold
        # or-gate (ckpt/input/collective_stall's pattern): the END-TO-END
        # speedup carries where the workload is long enough to amortize
        # admission; the STEADY-STATE ratio is the honest capacity
        # measurement on short CI workloads and noisy shared runners.
        # Either way the tokens must match the single-stream paths.
        out["pass_mode"] = (
            "end_to_end"
            if (out["speedup"] or 0) >= args.threshold
            else "steady_state"
            if (out["steady_speedup"] or 0) >= args.threshold
            else None
        )
        out["pass"] = mismatches == 0 and out["pass_mode"] is not None
    if drill:
        out["drained"] = acct is not None
        if acct is not None:
            out["drain"] = acct
    if recorder is not None:
        recorder.event(
            "run_stop", step=sched.ticks,
            exit_code=EXIT_RESUMABLE if (drill and acct) else 0,
        )
        recorder.close()
    print(json.dumps(out))
    if drill:
        return EXIT_RESUMABLE if acct is not None else 1
    if args.no_gate:
        return 0
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
