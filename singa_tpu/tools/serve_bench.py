"""Serving-tier load harness: continuous batching vs one-at-a-time,
speculative vs one-token ticks, batch-submit or Poisson open loop.

Drives a synthetic request workload (deterministic prompt lengths /
budgets from ``--seed``) through the serving tier (serve/engine.py +
serve/scheduler.py) and reports one JSON line::

  {"tokens_per_s": .., "seq_tokens_per_s": .., "speedup": ..,
   "p50_ms": .., "p99_ms": .., "slot_occupancy": ..,
   "kv_blocks_peak": .., "backpressure_ticks": .., "pass": ..}

The baseline reproduces the pre-serving behavior — one stream at a
time through ``models.transformer.generate`` (its whole decode is one
compiled scan, so this is a STRONG baseline: no per-token dispatch) —
and the gate demands continuous batching beat it by ``--threshold``
(default 2.0) at the configured concurrency. The win is physics, not
scheduling luck: decode is weight-streaming-bound, so S slots sharing
one weight read per tick emit S tokens for the bandwidth one stream
pays for one token. Both paths are compile-warmed before timing.

``--speculate_k K`` (> 0) benchmarks SPECULATIVE decode instead: the
same engine/scheduler at the same concurrency, one-token ticks vs
n-gram-drafted verify ticks (serve/speculate.py) emitting up to K+1
tokens per weight stream. The gate (``--spec_threshold``, default
1.3) demands speculative tokens/sec >= 1.3x the one-token tick on the
drafting-friendly ``--workload repeat`` workload, with the repo's
standing or-gate fallback for CPU-host timing variance: the ISOLATED
speculation machinery — the verify program at zero draft width, i.e.
the one-token tick plus draft lanes, acceptance cumprod, and the KV
rewind's save/restore, acceptance forced to zero by having nothing to
accept — must cost <= 5% over the plain decode tick (interleaved
best-of-trials, the collective_stall pattern). Token streams must be
IDENTICAL to the one-token run either way — speculation may only
change *when* tokens appear, never *which*.

``--workload shared_prefix`` benchmarks PREFIX CACHING instead: every
request shares one long common prefix (a system prompt) plus a short
unique tail, and the gate compares warm-cache admission (prefix cache
on, pre-seeded by the compile-warm request) against cold admission
(cache disabled, every prompt fully re-prefilled) on the same engine
shape. Or-gate (``--prefix_threshold``, default 1.5): warm end-to-end
tokens/sec >= 1.5x cold, OR prefill-chunks-EXECUTED drops >= 2x — the
deterministic, host-independent arm (a counter, not a clock). Token
streams must be IDENTICAL to the cold run either way — a hit may only
skip prefill work, never move a token.

``--arrival poisson --rate R`` adds an OPEN-LOOP load section: a
seeded deterministic Poisson arrival schedule (exponential
inter-arrivals at R requests/sec) submitted on the wall clock while
the serve loop ticks, reporting tokens/sec and queue-INCLUSIVE
(submit -> finish) p50/p99 latency under load alongside the
batch-submit workload's numbers (which gate; the open-loop section
reports).

With ``--workspace`` the run records serving lifecycle events +
request/decode spans into the PR 6 flight recorder, so
``tools/trace.py <ws> --summarize`` reports serving p50/p99 (and
acceptance rate / tokens per tick under speculation) out of the box.
``--sigterm_at_tick K`` is the drain drill (the fault grammar's
synthetic-signal discipline): the serve loop installs the resilience
plane's PreemptionHandler, triggers it at tick K (a REAL SIGTERM works
identically), drains — every in-flight sequence handed back with its
partial output, accounted in the final JSON — and exits
EXIT_RESUMABLE (75). CI asserts the exit code and reconstructs
admit -> decode ticks -> drain -> exit from the merged trace.

Usage::

  python -m singa_tpu.tools.serve_bench [--concurrency 8] [--requests 16]
      [--threshold 2.0] [--d_model 256] [--n_layers 2] [--n_heads 4]
      [--vocab 256] [--max_len 128] [--prompt_len 8] [--max_new 32]
      [--block_len 16] [--kv_blocks 0] [--prefill_chunk 16]
      [--speculate_k K] [--spec_threshold 1.3] [--workload repeat]
      [--workload shared_prefix --prefix_threshold 1.5] [--prefix_cache]
      [--arrival poisson --rate R] [--workspace DIR]
      [--sigterm_at_tick K] [--no_gate]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .trace import _percentile  # one percentile definition per package


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="serve_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--concurrency", type=int, default=8,
                    help="serving slots (decode batch width)")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--threshold", type=float, default=2.0,
                    help="min tokens/sec speedup over sequential generate")
    ap.add_argument("--d_model", type=int, default=256)
    ap.add_argument("--n_layers", type=int, default=2)
    ap.add_argument("--n_heads", type=int, default=4)
    ap.add_argument("--d_ff", type=int, default=1024)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--max_len", type=int, default=128)
    ap.add_argument("--prompt_len", type=int, default=8)
    ap.add_argument("--max_new", type=int, default=48)
    ap.add_argument("--block_len", type=int, default=16)
    ap.add_argument("--kv_blocks", type=int, default=0)
    ap.add_argument("--prefill_chunk", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--speculate_k", type=int, default=0,
                    help="> 0: benchmark speculative decode at this "
                    "draft width against the one-token tick")
    ap.add_argument("--spec_drafter", default="ngram",
                    choices=("ngram", "null"))
    ap.add_argument("--spec_threshold", type=float, default=1.3,
                    help="min speculative tokens/sec over the one-token "
                    "tick (or-gated with the machinery probe)")
    ap.add_argument("--workload", default="random",
                    choices=("random", "repeat", "shared_prefix"),
                    help="prompt shape: 'repeat' tiles a short motif — "
                    "the n-gram-drafting-friendly workload the "
                    "speculation gate runs on; 'shared_prefix' gives "
                    "every request one long common prefix + a short "
                    "unique tail — the prefix-cache gate's workload "
                    "(warm vs cold admission on the same engine shape)")
    ap.add_argument("--prefix_cache", action="store_true",
                    help="enable prefix caching on the measured engine "
                    "(implied by --workload shared_prefix, whose gate "
                    "compares against a cache-disabled cold run)")
    ap.add_argument("--prefix_threshold", type=float, default=1.5,
                    help="min warm-cache tokens/sec over cold admission "
                    "on the shared_prefix workload (or-gated with the "
                    "deterministic prefill-chunks-executed >= 2x drop)")
    ap.add_argument("--kernels", default="reference",
                    choices=("reference", "fused"),
                    help="serving attention implementation on the "
                    "measured engine: 'reference' = the gather + "
                    "cache_attend oracle, 'fused' = the Pallas "
                    "paged-attention kernel (interpret mode off-TPU; "
                    "baselines always run reference, so the gate "
                    "doubles as a stream-identity check)")
    ap.add_argument("--fleet", action="store_true",
                    help="benchmark a DISAGGREGATED FLEET instead: "
                    "--fleet_hosts role-split hosts (one engine each, "
                    "serve/fleet/) behind the front-door router, vs "
                    "one unified host at the same per-host slots. "
                    "Or-gate: fleet tokens/sec >= --fleet_threshold x "
                    "single-host, OR decode-host prefill-chunks-"
                    "executed == 0 with >= 1 migration (the "
                    "deterministic role-split proof). Streams must "
                    "match the single host either way.")
    ap.add_argument("--fleet_hosts", default="prefill,decode",
                    help="comma-separated roles, one host per entry "
                    "(rank order; names are role+index, e.g. "
                    "prefill0,decode0)")
    ap.add_argument("--fleet_threshold", type=float, default=1.5,
                    help="min fleet tokens/sec over the single host "
                    "(or-gated with the role-split proof)")
    ap.add_argument("--transport", default="local",
                    choices=("local", "mailbox", "socket"),
                    help="with --fleet: the wiring under the hosts. "
                    "'local' = in-process deques (the deterministic "
                    "drill), 'mailbox' = filesystem mailboxes, "
                    "'socket' = the production TCP path (comm/wire.py "
                    "over loopback: real frames, CRCs, acks, retries). "
                    "Streams must match the single host on EVERY "
                    "wiring; socket/mailbox also report migration "
                    "round-trip latency and router status staleness")
    ap.add_argument("--wire_faults", default=None,
                    help="with --transport socket: a wire-fault plan "
                    "(resilience/faults.py grammar), e.g. "
                    "'wire_drop@12,wire_torn@18,wire_dup@24' — "
                    "ordinals count MSG sends across the transport; "
                    "the fleet must still finish with matching "
                    "streams, proving retry/redeliver/dedupe")
    ap.add_argument("--sigterm_host", default=None,
                    help="with --fleet and --sigterm_at_tick: the host "
                    "(by name, or by role = its first host) whose "
                    "preemption plane fires — it drains its in-flight "
                    "sequences TO A PEER and the fleet finishes "
                    "without it; exit 75, streams still identical")
    ap.add_argument("--rollout", default="off",
                    choices=("off", "promote", "parity_fail"),
                    help="live weight-rollout drill (serve/rollout.py): "
                    "serve the workload on a --fleet_hosts fleet and "
                    "hot-swap a NEW weight version mid-bench (canary -> "
                    "parity -> promote). 'promote' expects verdict "
                    "promoted; 'parity_fail' perturbs one expected "
                    "probe token so the health gate trips and expects "
                    "the automatic fleet-wide rollback. Gate: streams "
                    "retired BEFORE the flip tick are bitwise the "
                    "no-rollout oracle, zero streams drop or hang, and "
                    "every host lands on the expected version")
    ap.add_argument("--rollout_at_tick", type=int, default=8,
                    help="with --rollout: fleet rounds served on the "
                    "current version before the controller starts")
    ap.add_argument("--arrival", default="batch",
                    choices=("batch", "poisson"),
                    help="'poisson' adds a seeded open-loop arrival "
                    "section (tokens/sec + submit->finish p50/p99)")
    ap.add_argument("--rate", type=float, default=8.0,
                    help="poisson arrival rate, requests/sec")
    ap.add_argument("--workspace", default=None,
                    help="record serving telemetry under this workspace")
    ap.add_argument("--sigterm_at_tick", type=int, default=0,
                    help="drain drill: trigger the preemption plane at "
                    "this tick and exit 75 (0 = off)")
    ap.add_argument("--no_gate", action="store_true",
                    help="report only; never fail on the threshold")
    return ap


def _token_mismatches(ref_sched, sched) -> int:
    """Streams that differ between a reference run and the measured
    run, matched by rid (a rid missing from either side counts as a
    mismatch, never a crash)."""
    got = {r.rid: r.tokens for r in sched.finished}
    return sum(
        1 for r in ref_sched.finished if got.get(r.rid) != r.tokens
    )


def _workload(args):
    """Deterministic request set: equal prompt/budget shapes so the
    sequential baseline compiles ONE program (anything else would
    charge the old path compile time the serving path does not pay).
    ``--workload repeat`` tiles a short per-request motif — the
    prompt-lookup drafter's home turf (templated/repetitive text), and
    what greedy continuations of it keep producing."""
    import numpy as np

    rs = np.random.RandomState(args.seed)
    prompts = []
    # shared_prefix: one common "system prompt" spanning most of the
    # prompt, per-request unique tails — production template traffic.
    # Drawn ONLY for that workload: the other workloads' seeded prompt
    # streams must not shift under them (CI gates are tuned to them).
    if args.workload == "shared_prefix":
        tail = max(1, min(4, args.prompt_len // 4))
        prefix = rs.randint(0, args.vocab, size=(args.prompt_len - tail,))
    for _ in range(args.requests):
        if args.workload == "repeat":
            motif = rs.randint(0, args.vocab, size=(4,))
            pr = np.tile(motif, args.prompt_len // 4 + 1)[:args.prompt_len]
        elif args.workload == "shared_prefix":
            pr = np.concatenate(
                [prefix, rs.randint(0, args.vocab, size=(tail,))]
            )
        else:
            pr = rs.randint(0, args.vocab, size=(args.prompt_len,))
        prompts.append(pr.astype(np.int32))
    return prompts


def run_scan_reference(params, cfg, prompts, max_new):
    """models.transformer.generate, one fused compiled scan per stream:
    the strongest possible single-stream number (zero per-token
    dispatch, impossible for a real server that must stream tokens back
    as they land). Reported for transparency, not gated. -> (tokens,
    elapsed_s, outputs)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.transformer import generate

    gen = jax.jit(lambda p, t: generate(p, t, cfg, max_new))
    # warm: one full compile outside the timed region
    np.asarray(gen(params, jnp.asarray(prompts[0][None])))
    outs = []
    t0 = time.perf_counter()
    for pr in prompts:
        outs.append(
            [int(t) for t in
             np.asarray(gen(params, jnp.asarray(pr[None])))[0, len(pr):]]
        )
    elapsed = time.perf_counter() - t0
    return sum(len(o) for o in outs), elapsed, outs


def _warmed_scheduler(params, cfg, prompts, args, slots, spec_k,
                      recorder=None, preemption=None, prefix_cache=False,
                      kernels="reference"):
    """Build an engine + scheduler and warm its compiled programs
    (prefill + decode/verify) with a throwaway request, then zero the
    counters — jit caches live per engine instance, so warming a twin
    engine would warm nothing (and the recorder attaches only AFTER
    the warm, so compile time never pollutes the serving
    percentiles). With ``prefix_cache`` the throwaway request doubles
    as the CACHE warm: its fully-prefilled prompt blocks park on the
    LRU at its retirement, so every measured shared_prefix request
    admits into a warm pool — the steady state a long-running server
    with template traffic lives in."""
    import numpy as np

    from ..serve import Engine, EngineConfig, Request, Scheduler

    engine = Engine(
        params, cfg,
        EngineConfig(
            slots=slots,
            kv_block_len=args.block_len,
            kv_blocks=args.kv_blocks,
            max_prefill_chunk=args.prefill_chunk,
            spec_k=spec_k,
            spec_drafter=args.spec_drafter,
            prefix_cache=prefix_cache,
            attend_impl=kernels,
        ),
    )
    sched = Scheduler(engine, recorder=None, preemption=preemption)
    sched.submit(Request(rid=-1, prompt=np.asarray(prompts[0]),
                         max_new_tokens=2))
    sched.serve()
    if prefix_cache:
        # second throwaway with the SAME prompt: a whole-prompt hit,
        # so the copy-on-write program compiles outside the timed
        # region too (and the measured pool starts warm)
        sched.submit(Request(rid=-2, prompt=np.asarray(prompts[0]),
                             max_new_tokens=2))
        sched.serve()
    sched.recorder = recorder
    sched.reset_counters()
    engine.allocator.peak_used = engine.allocator.used_blocks
    return engine, sched


def run_continuous(params, cfg, prompts, args, slots, recorder=None,
                   preemption=None, sigterm_at_tick=0, spec_k=0,
                   prefix_cache=False, kernels="reference"):
    """The serving stack at ``slots`` concurrency (slots=1 IS the
    one-at-a-time baseline: the same engine, streaming each request's
    tokens per tick, nothing batched; ``spec_k`` > 0 routes decode
    through the speculative verify tick; ``prefix_cache`` admits into
    a cache the warm request pre-seeded; ``kernels`` picks the attend
    implementation — baselines stay on "reference", so every gate's
    token-identity bar doubles as a fused-vs-reference stream check).
    -> (scheduler, elapsed_s, drain accounting | None)."""

    from ..serve import Request

    _, sched = _warmed_scheduler(
        params, cfg, prompts, args, slots, spec_k,
        recorder=recorder, preemption=preemption,
        prefix_cache=prefix_cache, kernels=kernels,
    )
    for i, pr in enumerate(prompts):
        sched.submit(Request(rid=i, prompt=pr, max_new_tokens=args.max_new,
                             seed=args.seed + i))
    if sigterm_at_tick:
        # deterministic drill: run to the tick, trigger the plane
        # (identical flag path to a real SIGTERM), then serve() drains
        t0 = time.perf_counter()
        sched.serve(max_ticks=sigterm_at_tick)
        preemption.trigger(f"sigterm_at_tick {sigterm_at_tick}")
        acct = sched.serve()
        return sched, time.perf_counter() - t0, acct
    t0 = time.perf_counter()
    acct = sched.serve()
    return sched, time.perf_counter() - t0, acct


def measure_spec_machinery(params, cfg, args, trials=3, ticks=10):
    """Isolated speculation-machinery cost (the collective_stall
    "isolated machinery" or-gate arm): the verify program at ZERO draft
    width — the one-token tick plus everything speculation bolts on
    (draft lanes, acceptance cumprod, the rewind's masked write
    routing), with acceptance forced to zero by having nothing to
    accept — against the plain decode program on the SAME engine at
    full slot occupancy. The (k+1)-wide forward is deliberately NOT in
    this number: that is the amortized compute acceptance pays for
    (and what the end-to-end arm measures); this isolates what
    speculation costs when it buys nothing.

    The GATED ratio comes from XLA's compiled cost model (flops +
    bytes accessed + transcendentals of the two programs) — on this
    repo's 2-core CI hosts, wall-clock A/B of near-identical compiled
    programs swings 0.8-1.25x from scheduling/compile-layout variance
    (collective_stall documented the same; its slope-fit answer does
    not apply to a single fused program), while the cost model
    resolves the actual <1% machinery delta deterministically.
    Interleaved best-of-trials wall times ride the JSON un-gated for
    transparency. -> dict(cost_ratio, time_ratio, decode_ms,
    verify_k0_ms)."""
    import jax
    import numpy as np

    from ..serve import Engine, EngineConfig

    engine = Engine(
        params, cfg,
        EngineConfig(
            slots=args.concurrency,
            kv_block_len=args.block_len,
            kv_blocks=args.kv_blocks,
            max_prefill_chunk=args.prefill_chunk,
            spec_k=0,
        ),
    )
    rs = np.random.RandomState(args.seed)
    plen = min(4, args.prompt_len)
    # every probe tick advances pos by one; fit warm + 2*trials*ticks
    # advances inside max_len (small models shrink the windows; a
    # max_len too short for even 1-tick windows skips the wall timing
    # entirely — the GATED cost ratio needs no ticks at all)
    ticks = min(ticks, (cfg.max_len - plen - 2) // (2 * trials))
    for s in range(args.concurrency):
        pr = rs.randint(0, args.vocab, size=(plen,)).astype(np.int32)
        engine.admit(s, cfg.max_len)
        last = engine.prefill_chunk(s, pr, 0)
        engine.activate(s, last, plen, seed=s)
    empty = np.zeros((args.concurrency, 0), np.int32)
    nd = np.zeros((args.concurrency,), np.int32)

    def _cost(compiled):
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else (ca or {})
        return (
            float(ca.get("flops", 0.0))
            + float(ca.get("bytes accessed", 0.0))
            + float(ca.get("transcendentals", 0.0))
        )
    d_cost = _cost(
        engine._decode_jit.lower(engine.params, engine.state).compile()
    )
    v_cost = _cost(
        engine._verify_jit.lower(
            engine.params, engine.state,
            jax.numpy.asarray(empty), jax.numpy.asarray(nd),
        ).compile()
    )
    best_d = best_v = float("inf")
    if ticks >= 1:
        engine.decode()
        engine.verify(empty, nd)
        jax.block_until_ready(engine.state["tokens"])
        for _ in range(trials):
            t0 = time.perf_counter()
            for _ in range(ticks):
                engine.decode()
            jax.block_until_ready(engine.state["tokens"])
            best_d = min(best_d, time.perf_counter() - t0)
            t0 = time.perf_counter()
            for _ in range(ticks):
                engine.verify(empty, nd)
            jax.block_until_ready(engine.state["tokens"])
            best_v = min(best_v, time.perf_counter() - t0)
    timed = ticks >= 1 and best_d > 0
    return {
        "cost_ratio": v_cost / d_cost if d_cost > 0 else float("inf"),
        "time_ratio": best_v / best_d if timed else None,
        "decode_ms": best_d / ticks * 1e3 if timed else None,
        "verify_k0_ms": best_v / ticks * 1e3 if timed else None,
    }


def run_poisson(params, cfg, prompts, args, recorder=None):
    """Open-loop load: requests arrive on a seeded deterministic
    Poisson schedule (exponential inter-arrivals at ``--rate``
    requests/sec) while the serve loop ticks — the scheduler never
    sees the future, so this measures latency UNDER LOAD, queueing
    included. -> (scheduler, elapsed_s, submit->finish latencies ms)."""
    import numpy as np

    from ..serve import Request

    _, sched = _warmed_scheduler(
        params, cfg, prompts, args, args.concurrency, args.speculate_k,
        recorder=recorder, kernels=args.kernels,
    )
    rs = np.random.RandomState(args.seed + 1)
    arrivals = np.cumsum(rs.exponential(1.0 / max(args.rate, 1e-9),
                                        size=len(prompts)))
    pending = list(zip(arrivals, range(len(prompts))))
    t0 = time.perf_counter()
    while pending or sched.busy:
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            _, i = pending.pop(0)
            sched.submit(Request(
                rid=i, prompt=prompts[i], max_new_tokens=args.max_new,
                seed=args.seed + i,
            ))
        if not sched.busy:
            # idle until the next arrival (open loop: the server must
            # wait for load, never pull it forward)
            time.sleep(min(max(pending[0][0] - now, 0.0), 0.01))
            continue
        sched.tick()
    elapsed = time.perf_counter() - t0
    lat_ms = sorted(
        (r.finish_mono - r.enqueue_mono) * 1e3 for r in sched.finished
    )
    return sched, elapsed, lat_ms


class _TimedSend:
    """Transport proxy that times ``migrate`` sends (submit -> the
    transport's own done signal: for the socket wiring that is the
    receiver's ACK, i.e. the migration round trip). Everything else
    forwards untouched, so hosts/router never know it is there."""

    def __init__(self, inner):
        self._inner = inner
        self.migrate_ms: list[float] = []

    def send(self, dst, kind, payload, *, src):
        t0 = time.perf_counter()
        self._inner.send(dst, kind, payload, src=src)
        if kind == "migrate":
            self.migrate_ms.append((time.perf_counter() - t0) * 1e3)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _build_transport_arm(args):
    """The --transport wiring for the fleet drill: None for 'local'
    (build_fleet's default), a shared-root Mailbox, or a loopback
    SocketTransport (auto-bound ports; --wire_faults armed)."""
    arm = getattr(args, "transport", "local")
    if arm == "local":
        return None
    if arm == "mailbox":
        import os
        import tempfile

        from ..serve.fleet import Mailbox

        root = (
            os.path.join(args.workspace, "mailbox")
            if args.workspace
            else tempfile.mkdtemp(prefix="serve_bench_mbx_")
        )
        return Mailbox(root)
    from ..comm import SocketTransport, WireFaults
    from ..resilience.faults import FaultPlan

    faults = None
    if args.wire_faults:
        faults = WireFaults(FaultPlan.parse(args.wire_faults))
    # generous RETRY budget, tight per-attempt deadline: injected
    # drops/torn frames must end in redelivery, not a tombstone — and a
    # dropped frame costs one deadline, not five seconds of bench time
    return SocketTransport(
        connect_timeout_s=2.0, send_timeout_s=1.0, max_retries=6,
        backoff_s=0.02, backoff_cap_s=0.25, faults=faults,
    )


def build_fleet(params, cfg, args, *, transport=None):
    """Hosts (one engine each) + router per ``--fleet_hosts``, wired
    over an in-process transport — the whole multi-host fleet in one
    process, deterministic, with the REAL migration wire bytes.
    -> (hosts, router, transport)."""
    from ..serve import Engine, EngineConfig
    from ..serve.fleet import FleetHost, LocalTransport, Router

    roles = [r.strip() for r in args.fleet_hosts.split(",") if r.strip()]
    if not roles:
        raise ValueError("--fleet_hosts named no hosts")
    names, seen = [], {}
    for role in roles:
        seen[role] = seen.get(role, 0)
        names.append(f"{role}{seen[role]}")
        seen[role] += 1
    topo = list(zip(names, roles))
    ec = EngineConfig(
        slots=args.concurrency,
        kv_block_len=args.block_len,
        kv_blocks=args.kv_blocks,
        max_prefill_chunk=args.prefill_chunk,
        spec_k=args.speculate_k,
        spec_drafter=args.spec_drafter,
        prefix_cache=args.prefix_cache,
        attend_impl=args.kernels,
    )
    transport = transport or LocalTransport()
    hosts = [
        FleetHost(
            name, role, Engine(params, cfg, ec), transport,
            peers={n: r for n, r in topo if n != name},
        )
        for name, role in topo
    ]
    router = Router(
        transport, block_len=args.block_len if args.prefix_cache else 0,
    )
    return hosts, router, transport


def run_fleet(params, cfg, prompts, args, *, recorders=None,
              router_recorder=None, sigterm_at_tick=0,
              sigterm_target=None):
    """Drive the request workload through the fleet (batch submit or
    the --arrival poisson open loop). ``sigterm_at_tick`` triggers the
    target host's preemption plane at that fleet round — it drains to
    a PEER and the fleet finishes without it. -> (hosts, router,
    elapsed_s, streams {rid: tokens}, queue-inclusive latencies ms,
    drain accounting | None, wire report | None). The wire report
    (non-local --transport only) carries migration round-trip
    latencies, router status-staleness samples, and (socket) the
    transport's retry/redelivery counters."""
    import numpy as np

    from ..serve import Request

    wire_arm = _build_transport_arm(args)
    timed = _TimedSend(wire_arm) if wire_arm is not None else None
    if timed is not None and recorders:
        # attach BEFORE warmup: connections are cached, so the
        # wire_connect events a trace reconstruction needs fire during
        # the warm waves
        wire_arm.recorder = recorders[0]
    hosts, router, _ = build_fleet(params, cfg, args, transport=timed)
    by_name = {h.name: h for h in hosts}
    if sigterm_at_tick:
        if sigterm_target in by_name:
            target = by_name[sigterm_target]
        else:
            target = next(
                (h for h in hosts if h.role == (sigterm_target or "decode")),
                None,
            )
            if target is None:
                raise ValueError(
                    f"--sigterm_host {sigterm_target!r} names no fleet "
                    "host"
                )
    # compile-warm EVERY host's programs through the REAL fleet path
    # (prefill on prefill hosts, import+decode on decode hosts): one
    # warm request per decode-capable host — the tie-rotating export
    # spreads them, so no host compiles inside the measured window —
    # then zero the counters and attach recorders only after, so
    # compile time never pollutes the serving percentiles
    per_wave = max(
        1, sum(1 for h in hosts if h.role in ("decode", "unified"))
    )
    waves = 2 if args.prefix_cache else 1
    rid = -1
    for _ in range(waves):
        for _ in range(per_wave):
            router.submit(Request(rid=rid, prompt=np.asarray(prompts[0]),
                                  max_new_tokens=2))
            rid -= 1
        idle = 0
        for _ in range(10 ** 4):
            for h in hosts:
                h.tick()
            # an in-flight export sits in the transport for one round;
            # only consecutive idle rounds mean the fleet ran dry
            idle = idle + 1 if not any(h.busy for h in hosts) else 0
            if idle >= 3:
                break
    for h in hosts:
        h.sched.finished.clear()
        h.sched.reset_counters()
        h.migrate_in = h.migrate_out = 0
        h.blocks_in = h.blocks_out = 0
        h.engine.allocator.peak_used = h.engine.allocator.used_blocks
    router.routed = router.affinity_hits = 0
    if recorders:
        for h, rec in zip(hosts, recorders):
            h.sched.recorder = rec
            h._event("fleet_role", host=h.name, role=h.role)
            h._event(
                "kernel_select", site="serve.paged_attention",
                impl=args.kernels,
            )
    router.recorder = router_recorder

    if args.arrival == "poisson":
        rs = np.random.RandomState(args.seed + 1)
        arrivals = np.cumsum(
            rs.exponential(1.0 / max(args.rate, 1e-9), size=len(prompts))
        )
        pending = list(zip(arrivals, range(len(prompts))))
    else:
        pending = [(0.0, i) for i in range(len(prompts))]
    acct = None
    dead: set = set()
    rids = set(range(len(prompts)))
    tick = 0
    idle_rounds = 0
    # router status staleness: how old each host's latest-wins status
    # snapshot is when the placement loop reads it (sampled every few
    # rounds; a change resets that host's clock)
    stale_ms: list[float] = []
    stale_last: dict[str, tuple[dict, float]] = {}
    t0 = time.perf_counter()
    while True:
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            _, i = pending.pop(0)
            router.submit(Request(
                rid=i, prompt=prompts[i], max_new_tokens=args.max_new,
                seed=args.seed + i,
            ))
        if (
            sigterm_at_tick and tick >= sigterm_at_tick
            and target.name not in dead
        ):
            # the deterministic drill: the preemption plane's flag path
            # is identical to a real SIGTERM's, then the host drains to
            # its peers and stops ticking (the process is "gone")
            acct = target.drain(f"sigterm_at_tick {sigterm_at_tick}")
            dead.add(target.name)
        alive = [h for h in hosts if h.name not in dead]
        for h in alive:
            h.tick()
        # busy is re-checked AFTER the full round: an exported sequence
        # sits in the transport for one round before the peer's recv
        # absorbs it, so a single idle snapshot mid-round lies
        busy = any(h.busy for h in alive)
        finished = {
            r.rid for h in hosts for r in h.sched.finished if r.rid >= 0
        }
        if finished >= rids and not pending:
            break
        idle_rounds = 0 if busy else idle_rounds + 1
        if idle_rounds >= 4 and not pending:
            raise RuntimeError(
                "fleet stalled with requests unfinished: "
                f"{sorted(rids - finished)}"
            )
        if timed is not None and tick % 5 == 0:
            snap_t = time.perf_counter()
            for hname, st in timed.statuses().items():
                prev = stale_last.get(hname)
                if prev is None or prev[0] != st:
                    stale_last[hname] = (st, snap_t)
                else:
                    stale_ms.append((snap_t - prev[1]) * 1e3)
        if not busy and pending:
            time.sleep(min(max(pending[0][0] - now, 0.0), 0.01))
        tick += 1
    elapsed = time.perf_counter() - t0
    streams = {
        r.rid: list(r.tokens)
        for h in hosts for r in h.sched.finished if r.rid >= 0
    }
    lat_ms = sorted(
        (r.finish_mono - r.enqueue_mono) * 1e3
        for h in hosts for r in h.sched.finished if r.rid >= 0
    )
    wire = None
    if timed is not None:
        stats = getattr(wire_arm, "wire_stats", None)
        wire = {
            "migrate_rtt_ms": sorted(timed.migrate_ms),
            "status_staleness_ms": sorted(stale_ms),
            "stats": stats() if stats is not None else None,
        }
        close = getattr(wire_arm, "close", None)
        if close is not None:
            close()
    return hosts, router, elapsed, streams, lat_ms, acct, wire


def _fleet_prefix_main(args, params, cfg, prompts) -> int:
    """The --fleet shared_prefix drill: the FLEET prefix cache. Two
    unified hosts on the in-process transport. The COLD phase serves
    every request on one fresh host with the cache OFF; the WARM phase
    first serves the whole workload on the OTHER host (parking its
    blocks on that host's LRU), then serves the measured requests on a
    host that has never seen the prompts — its only path to warm KV is
    a cross-host cache_fetch -> cache_ship over the wire. Or-gate (the
    CPU-CI pattern): warm end-to-end tokens/sec >=
    --prefix_threshold x cold, OR executed prefill chunks drop >= 2x
    (deterministic). Streams must match cold bitwise and >= 1 block
    must actually ship either way."""
    import copy

    import numpy as np

    from ..serve import Request

    def drive(hosts):
        idle = 0
        for _ in range(10 ** 5):
            for h in hosts:
                h.tick()
            # an in-flight fetch/ship sits in the transport for one
            # round; only consecutive idle rounds mean the fleet ran dry
            idle = idle + 1 if not any(h.busy for h in hosts) else 0
            if idle >= 3:
                return
        raise RuntimeError("fleet prefix drill stalled")

    def submit_wave(host, rid0):
        for i, pr in enumerate(prompts):
            host.submit(Request(
                rid=rid0 + i, prompt=np.asarray(pr, np.int32),
                max_new_tokens=args.max_new, seed=args.seed + i,
            ))

    def reset(hosts):
        for h in hosts:
            h.sched.finished.clear()
            h.sched.reset_counters()
            h.cache_fetches = h.cache_fetch_timeouts = 0
            h.cache_ships_in = h.cache_ships_out = 0
            h.ship_blocks_in = h.ship_blocks_out = 0
            h.ship_bytes_in = h.ship_bytes_out = 0

    # COLD: cache off, the whole workload on ONE host (its peer idles
    # — the same per-host compute the warm phase's serving host gets)
    cargs = copy.copy(args)
    cargs.fleet_hosts = "unified,unified"
    cargs.prefix_cache = False
    cold_hosts, _, _ = build_fleet(params, cfg, cargs)
    # compile-warm off the clock, then zero the counters
    cold_hosts[1].submit(Request(
        rid=-1, prompt=np.asarray(prompts[0]), max_new_tokens=2,
    ))
    drive(cold_hosts)
    reset(cold_hosts)
    t0 = time.perf_counter()
    submit_wave(cold_hosts[1], 0)
    drive(cold_hosts)
    cold_s = time.perf_counter() - t0
    cold = {
        r.rid: list(r.tokens)
        for h in cold_hosts for r in h.sched.finished if r.rid >= 0
    }
    cold_chunks = sum(h.sched.prefill_chunks for h in cold_hosts)
    cold_tokens = sum(len(t) for t in cold.values())

    # WARM: cache on. The warm wave runs the SAME workload on host0,
    # parking every prompt's blocks (the shared prefix AND the unique
    # tails) on ITS LRU; host1 compile-warms on a DISJOINT prompt
    # (sharing the prefix here would register it locally and bypass
    # the wire entirely), so its measured admissions can only go warm
    # through cache_fetch -> cache_ship.
    wargs = copy.copy(args)
    wargs.fleet_hosts = "unified,unified"
    wargs.prefix_cache = True
    warm_hosts, _, _ = build_fleet(params, cfg, wargs)
    h0, h1 = warm_hosts
    rs = np.random.RandomState(args.seed + 997)
    h1.submit(Request(
        rid=-1,
        prompt=rs.randint(
            0, args.vocab, size=(args.prompt_len,)
        ).astype(np.int32),
        max_new_tokens=2,
    ))
    h0.submit(Request(
        rid=-2, prompt=np.asarray(prompts[0]), max_new_tokens=2,
    ))
    drive(warm_hosts)
    submit_wave(h0, 10 ** 6)  # the warm wave (uncounted)
    drive(warm_hosts)
    reset(warm_hosts)
    recorders = None
    if args.workspace:
        import os

        from ..obs.recorder import FlightRecorder

        events = os.path.join(args.workspace, "events")
        recorders = [
            FlightRecorder(events, rank=i, run_id="serve_bench_fleetprefix")
            for i in range(len(warm_hosts))
        ]
        for h, rec in zip(warm_hosts, recorders):
            h.sched.recorder = rec
            h._event("fleet_role", host=h.name, role=h.role)
    t0 = time.perf_counter()
    submit_wave(h1, 0)
    drive(warm_hosts)
    warm_s = time.perf_counter() - t0
    warm = {
        r.rid: list(r.tokens)
        for h in warm_hosts for r in h.sched.finished if r.rid >= 0
    }
    warm_chunks = sum(h.sched.prefill_chunks for h in warm_hosts)
    warm_tokens = sum(len(t) for t in warm.values())

    mismatches = sum(1 for i in cold if warm.get(i) != cold[i])
    blocks_shipped = sum(h.ship_blocks_in for h in warm_hosts)
    ship_bytes = sum(h.ship_bytes_in for h in warm_hosts)
    admitted = len(warm) or 1
    hits = sum(h.sched.prefix_hits for h in warm_hosts)
    out = {
        "fleet": True,
        "workload": "shared_prefix",
        "fleet_hosts": "unified,unified",
        "requests": len(prompts),
        "finished": len(warm),
        "tokens": warm_tokens,
        "cold_tokens": cold_tokens,
        "serve_s": round(warm_s, 4),
        "cold_s": round(cold_s, 4),
        "tokens_per_s": round(warm_tokens / warm_s, 1)
        if warm_s > 0 else 0.0,
        "cold_tokens_per_s": round(cold_tokens / cold_s, 1)
        if cold_s > 0 else 0.0,
        "hit_rate": round(hits / admitted, 4),
        "cache_fetches": sum(h.cache_fetches for h in warm_hosts),
        "cache_fetch_timeouts": sum(
            h.cache_fetch_timeouts for h in warm_hosts
        ),
        "blocks_shipped": blocks_shipped,
        "ship_bytes": ship_bytes,
        "prefill_chunks": warm_chunks,
        "cold_prefill_chunks": cold_chunks,
        "prefill_chunk_ratio": round(cold_chunks / warm_chunks, 3)
        if warm_chunks else None,
        "token_mismatches": mismatches,
        "prefix_threshold": args.prefix_threshold,
        "transport": "local",
    }
    out["fleet_speedup"] = (
        round(out["tokens_per_s"] / out["cold_tokens_per_s"], 3)
        if out["cold_tokens_per_s"] else None
    )
    # or-gate: end-to-end carries on accelerator hosts; on CPU CI the
    # deterministic arm carries (warm admissions EXECUTED >= 2x fewer
    # prefill chunks than cold). Streams must match and >= 1 block
    # must have moved over the wire either way.
    out["pass_mode"] = (
        "end_to_end"
        if (out["fleet_speedup"] or 0) >= args.prefix_threshold
        else "chunk_drop"
        if (out["prefill_chunk_ratio"] or 0) >= 2.0
        else None
    )
    out["pass"] = (
        mismatches == 0 and blocks_shipped >= 1
        and out["pass_mode"] is not None
    )
    if recorders:
        for i, rec in enumerate(recorders):
            rec.event(
                "run_stop", step=warm_hosts[i].sched.ticks, exit_code=0,
            )
            rec.close()
    print(json.dumps(out))
    if args.no_gate:
        return 0
    return 0 if out["pass"] else 1


def _rollout_main(args, params, cfg, prompts) -> int:
    """The --rollout drill: live weight hot-swap under load
    (serve/rollout.py). One fleet serves the workload; at
    --rollout_at_tick the controller stages a NEW version, canaries one
    decode host, parity-probes it, and promotes (or — parity_fail —
    trips the health gate and rolls the fleet back). The oracle is the
    identical fleet run with NO rollout: every stream retired BEFORE
    the canary flip must match it bitwise (flip identity — a hot-swap
    may only change streams that outlive it), every stream must finish
    (zero drops/hangs), and every host must land on the expected
    version."""
    import jax
    import numpy as np

    from ..models.transformer import init_lm
    from ..serve import Request
    from ..serve.fleet.router import DECODE_CAPABLE
    from ..serve.rollout import RolloutController

    def serve(hosts, router, *, stop_after=None):
        """Submit the whole workload, tick until done (or until
        ``stop_after`` fleet rounds — mid-flight). -> rounds run."""
        for i, pr in enumerate(prompts):
            router.submit(Request(
                rid=i, prompt=np.asarray(pr, np.int32),
                max_new_tokens=args.max_new, seed=args.seed + i,
            ))
        return pump(hosts, stop_after=stop_after)

    def pump(hosts, *, stop_after=None):
        idle = rounds = 0
        for _ in range(10 ** 5):
            if stop_after is not None and rounds >= stop_after:
                return rounds
            for h in hosts:
                h.tick()
            rounds += 1
            idle = idle + 1 if not any(h.busy for h in hosts) else 0
            if idle >= 3:
                return rounds
        raise RuntimeError("rollout drill stalled")

    def warm(hosts, router):
        # compile-warm every host off the clock (run_fleet's pattern)
        per_wave = max(
            1, sum(1 for h in hosts if h.role in DECODE_CAPABLE)
        )
        for k in range(per_wave):
            router.submit(Request(
                rid=-1 - k, prompt=np.asarray(prompts[0], np.int32),
                max_new_tokens=2,
            ))
        pump(hosts)
        for h in hosts:
            h.sched.finished.clear()
            h.sched.reset_counters()

    def streams_of(hosts):
        return {
            r.rid: list(r.tokens)
            for h in hosts for r in h.sched.finished if r.rid >= 0
        }

    # the no-rollout oracle: same fleet build, same workload
    o_hosts, o_router, _ = build_fleet(params, cfg, args)
    warm(o_hosts, o_router)
    serve(o_hosts, o_router)
    oracle = streams_of(o_hosts)

    # the measured run: identical fleet, hot-swapped mid-bench
    hosts, router, transport = build_fleet(params, cfg, args)
    warm(hosts, router)
    recorders = ctl_rec = None
    if args.workspace:
        import os

        from ..obs.recorder import FlightRecorder

        events = os.path.join(args.workspace, "events")
        recorders = [
            FlightRecorder(events, rank=i, run_id="serve_bench_rollout")
            for i in range(len(hosts))
        ]
        for h, rec in zip(hosts, recorders):
            h.sched.recorder = rec
            h._event("fleet_role", host=h.name, role=h.role)
        ctl_rec = FlightRecorder(
            events, rank=len(hosts), run_id="serve_bench_rollout",
        )
        ctl_rec.event("run_start", step=0, mode="serve_bench_rollout")
    next_params = init_lm(jax.random.PRNGKey(args.seed + 1), cfg)
    serve(hosts, router, stop_after=args.rollout_at_tick)
    # everything finished BEFORE the controller starts is provably
    # pre-flip: the flip-identity set the gate pins bitwise
    pre_flip = set(streams_of(hosts))
    ctl = RolloutController(
        transport, {h.name: h.role for h in hosts},
        params=next_params, version=1, cfg=cfg,
        serving=hosts[0].engine.serving,
        probes=2, probe_tokens=4, stage_timeout_s=60.0,
        recorder=ctl_rec,
        force_parity_fail=args.rollout == "parity_fail",
        tick=lambda: [h.tick() for h in hosts],
        log=lambda s: print(s, file=sys.stderr),
    )
    res = ctl.run()
    pump(hosts)  # drain the remaining streams to completion
    streams = streams_of(hosts)

    want_verdict = (
        "promoted" if args.rollout == "promote" else "rollback"
    )
    want_version = 1 if args.rollout == "promote" else 0
    pre_mismatches = sum(
        1 for i in pre_flip if streams.get(i) != oracle.get(i)
    )
    hung = sorted(set(range(len(prompts))) - set(streams))
    versions = {h.name: h.engine.params_version for h in hosts}
    out = {
        "rollout": args.rollout,
        "fleet_hosts": args.fleet_hosts,
        "requests": len(prompts),
        "finished": len(streams),
        "hung": len(hung),
        "verdict": res["verdict"],
        "want_verdict": want_verdict,
        "rollbacks": res["rollbacks"],
        "torn_ships": res["torn_ships"],
        "canary": res["canary"],
        "versions": versions,
        "pre_flip_streams": len(pre_flip),
        "pre_flip_mismatches": pre_mismatches,
        "rollout_at_tick": args.rollout_at_tick,
    }
    out["pass"] = (
        res["verdict"] == want_verdict
        and not hung
        and pre_mismatches == 0
        and all(v == want_version for v in versions.values())
    )
    if recorders:
        for i, rec in enumerate(recorders):
            rec.event(
                "run_stop", step=hosts[i].sched.ticks, exit_code=0,
            )
            rec.close()
        ctl_rec.close()
    print(json.dumps(out))
    if args.no_gate:
        return 0
    return 0 if out["pass"] else 1


def _fleet_main(args, params, cfg, prompts) -> int:
    """The --fleet drill: role-split hosts behind the front-door
    router vs ONE unified host at the same per-host slots (which is
    also the token oracle — scheduling, routing, and migration may
    never move a token). Reports per-host occupancy + queue-inclusive
    p50/p99; with --sigterm_at_tick/--sigterm_host, the drain-to-peer
    drill (exit 75, streams still identical). ``--workload
    shared_prefix`` dispatches to the fleet prefix-cache drill
    (_fleet_prefix_main) instead."""
    from ..resilience.preemption import EXIT_RESUMABLE

    if args.workload == "shared_prefix" and not args.sigterm_at_tick:
        return _fleet_prefix_main(args, params, cfg, prompts)

    n_hosts = len([r for r in args.fleet_hosts.split(",") if r.strip()])
    recorders = router_rec = None
    if args.workspace:
        import os

        from ..obs.recorder import FlightRecorder

        events = os.path.join(args.workspace, "events")
        recorders = [
            FlightRecorder(events, rank=i, run_id="serve_bench_fleet")
            for i in range(n_hosts)
        ]
        router_rec = FlightRecorder(
            events, rank=n_hosts, run_id="serve_bench_fleet"
        )
        router_rec.event("run_start", step=0, mode="serve_bench_fleet")
    # the single unified host: the number the fleet must beat AND the
    # token oracle it must match
    base_sched, base_s, _ = run_continuous(
        params, cfg, prompts, args, slots=args.concurrency,
        spec_k=args.speculate_k, prefix_cache=args.prefix_cache,
        kernels=args.kernels,
    )
    base = {r.rid: list(r.tokens) for r in base_sched.finished}
    base_tokens = base_sched.tokens_emitted + len(base_sched.finished)
    hosts, router, elapsed, streams, lat_ms, acct, wire = run_fleet(
        params, cfg, prompts, args,
        recorders=recorders, router_recorder=router_rec,
        sigterm_at_tick=args.sigterm_at_tick,
        sigterm_target=args.sigterm_host,
    )
    drill = bool(args.sigterm_at_tick)
    tokens = sum(len(t) for t in streams.values())
    mismatches = sum(
        1 for i in base if streams.get(i) != base[i]
    )
    decode_prefill_chunks = sum(
        h.sched.prefill_chunks for h in hosts if h.role == "decode"
    )
    migrations = sum(h.migrate_in for h in hosts)
    out = {
        "fleet": True,
        "fleet_hosts": args.fleet_hosts,
        "concurrency": args.concurrency,
        "requests": len(prompts),
        "finished": len(streams),
        "tokens": tokens,
        "serve_s": round(elapsed, 4),
        "tokens_per_s": round(tokens / elapsed, 1) if elapsed > 0 else 0.0,
        "single_tokens_per_s": round(base_tokens / base_s, 1)
        if base_s > 0 else 0.0,
        # queue-INCLUSIVE (front-door submit -> finish, wherever the
        # sequence finished) latency across every host
        "p50_ms": round(_percentile(lat_ms, 0.50), 2),
        "p99_ms": round(_percentile(lat_ms, 0.99), 2),
        "hosts": {
            h.name: {
                "role": h.role,
                "migrate_in": h.migrate_in,
                "migrate_out": h.migrate_out,
                "blocks_in": h.blocks_in,
                "blocks_out": h.blocks_out,
                "prefill_chunks": h.sched.prefill_chunks,
                **h.sched.occupancy(),
            }
            for h in hosts
        },
        "migrations": migrations,
        "routed": router.routed,
        "affinity_hits": router.affinity_hits,
        "token_mismatches": mismatches,
        "decode_prefill_chunks": decode_prefill_chunks,
        "fleet_threshold": args.fleet_threshold,
        "transport": args.transport,
    }
    if wire is not None:
        rtt = wire["migrate_rtt_ms"]
        stale = wire["status_staleness_ms"]
        out["wire"] = {
            "migrate_rtt_ms": {
                "p50": round(_percentile(rtt, 0.50), 3),
                "p99": round(_percentile(rtt, 0.99), 3),
                "n": len(rtt),
            },
            "status_staleness_ms": {
                "p50": round(_percentile(stale, 0.50), 3),
                "p99": round(_percentile(stale, 0.99), 3),
                "n": len(stale),
            },
        }
        if wire["stats"] is not None:
            # the transport's own verdict counters (socket only), sans
            # the raw per-peer latency lists trace --summarize owns
            out["wire"].update({
                k: v for k, v in wire["stats"].items() if k != "send_ms"
            })
    out["fleet_speedup"] = (
        round(out["tokens_per_s"] / out["single_tokens_per_s"], 3)
        if out["single_tokens_per_s"] else None
    )
    has_decode = any(h.role == "decode" for h in hosts)
    # or-gate (the stall tools' pattern): the end-to-end speedup
    # carries on accelerator hosts, where N fleet hosts ARE N chips'
    # worth of decode bandwidth; on CPU CI every "host" shares the
    # same cores, so the deterministic arm carries — the role split
    # PROVED (decode hosts executed zero prefill chunks while >= 1
    # migrated sequence actually streamed through them). Tokens must
    # match the single host either way.
    out["pass_mode"] = (
        "end_to_end"
        if (out["fleet_speedup"] or 0) >= args.fleet_threshold
        else "role_split"
        if has_decode and decode_prefill_chunks == 0 and migrations > 0
        else None
    )
    out["pass"] = mismatches == 0 and out["pass_mode"] is not None
    if drill:
        out["drained"] = acct is not None
        if acct is not None:
            out["drain"] = acct
    if recorders:
        for i, rec in enumerate(recorders):
            rec.event(
                "run_stop", step=hosts[i].sched.ticks,
                exit_code=EXIT_RESUMABLE if drill and acct else 0,
            )
            rec.close()
        router_rec.close()
    print(json.dumps(out))
    if drill:
        return EXIT_RESUMABLE if acct is not None and out["pass"] else 1
    if args.no_gate:
        return 0
    return 0 if out["pass"] else 1


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    import jax

    from ..models.transformer import TransformerConfig, init_lm
    from ..resilience.preemption import EXIT_RESUMABLE, PreemptionHandler

    cfg = TransformerConfig(
        vocab=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff, max_len=args.max_len,
    )
    params = init_lm(jax.random.PRNGKey(args.seed), cfg)
    prompts = _workload(args)

    if args.rollout != "off":
        # the live weight-rollout drill owns its whole flow (fleet
        # build, oracle, controller, flip-identity gate)
        return _rollout_main(args, params, cfg, prompts)

    if args.fleet:
        # the disaggregated-fleet drill owns its whole flow (its own
        # per-host recorders, baseline, gate, and drain drill)
        return _fleet_main(args, params, cfg, prompts)

    recorder = None
    if args.workspace:
        import os

        from ..obs.recorder import FlightRecorder

        recorder = FlightRecorder(
            os.path.join(args.workspace, "events"), rank=0,
            run_id="serve_bench",
        )
        recorder.event("run_start", step=0, mode="serve_bench")
        # which implementation the measured engine's attend seam runs
        # (site -> impl), so trace --summarize's incident report says
        # which path a run took
        recorder.event(
            "kernel_select", step=0, site="serve.paged_attention",
            impl=args.kernels,
        )
    handler = PreemptionHandler()
    handler.install()

    drill = bool(args.sigterm_at_tick)
    shared = args.workload == "shared_prefix" and not drill
    spec = args.speculate_k > 0 and not shared
    if not drill and not spec and not shared:
        # the gated baseline: the SAME serving stack, one stream at a
        # time (slots=1) — what tools/generate.py-style single-stream
        # serving pays per token. The fused-scan reference rides along
        # un-gated (see run_scan_reference).
        seq_sched, seq_s, _ = run_continuous(
            params, cfg, prompts, args, slots=1
        )
        seq_tokens = seq_sched.tokens_emitted + len(seq_sched.finished)
        scan_tokens, scan_s, scan_outs = run_scan_reference(
            params, cfg, prompts, args.max_new
        )
    if not drill and spec:
        # the speculation baseline: the SAME engine/scheduler at the
        # SAME concurrency, one-token ticks (spec off) — the number
        # speculation must beat, and the token oracle it must match
        base_sched, base_s, _ = run_continuous(
            params, cfg, prompts, args, slots=args.concurrency
        )
    if shared:
        # the prefix-cache baseline: the SAME engine shape with the
        # cache DISABLED — cold admission re-prefills every prompt; it
        # is both the number warm must beat and the token oracle warm
        # must match bitwise
        cold_sched, cold_s, _ = run_continuous(
            params, cfg, prompts, args, slots=args.concurrency,
            spec_k=args.speculate_k,
        )
    sched, serve_s, acct = run_continuous(
        params, cfg, prompts, args, slots=args.concurrency,
        recorder=recorder, preemption=handler,
        sigterm_at_tick=args.sigterm_at_tick, spec_k=args.speculate_k,
        prefix_cache=shared or args.prefix_cache, kernels=args.kernels,
    )
    if acct is not None and not drill:
        # a REAL preemption arrived mid-benchmark: the serve loop
        # drained — report the accounting and exit resumable like every
        # other drained host, never fall through to the gate math over
        # a half-finished request set
        drill = True

    lat = sorted(r.latency_s * 1e3 for r in sched.finished)
    out = {
        "concurrency": args.concurrency,
        "kernels": args.kernels,
        "requests": args.requests,
        "finished": len(sched.finished),
        "tokens": sched.tokens_emitted
        + sum(1 for r in sched.finished),  # + first tokens from prefill
        "serve_s": round(serve_s, 4),
        "tokens_per_s": round(
            (sched.tokens_emitted + len(sched.finished)) / serve_s, 1
        )
        if serve_s > 0
        else 0.0,
        "p50_ms": round(_percentile(lat, 0.50), 2),
        "p99_ms": round(_percentile(lat, 0.99), 2),
        **sched.occupancy(),
    }
    if not drill and spec:
        base_tokens = base_sched.tokens_emitted + len(base_sched.finished)
        out["spec_k"] = args.speculate_k
        out["spec_drafter"] = args.spec_drafter
        out["base_tokens_per_s"] = round(
            base_tokens / base_s, 1
        ) if base_s > 0 else 0.0
        out["spec_speedup"] = round(
            out["tokens_per_s"] / out["base_tokens_per_s"], 3
        ) if out["base_tokens_per_s"] else None
        # identity is the hard bar: every stream's tokens must equal
        # the one-token-tick run's — speculation may change *when*
        # tokens appear, never *which*
        out["token_mismatches"] = _token_mismatches(base_sched, sched)
        probe = measure_spec_machinery(params, cfg, args)

        def _r(v, nd=3):
            return None if v is None else round(v, nd)
        out["spec_machinery_ratio"] = _r(probe["cost_ratio"], 4)
        out["spec_machinery_time_ratio"] = _r(probe["time_ratio"])
        out["decode_tick_ms"] = _r(probe["decode_ms"])
        out["verify_k0_tick_ms"] = _r(probe["verify_k0_ms"])
        out["spec_threshold"] = args.spec_threshold
        # or-gate (the stall tools' pattern): the end-to-end speedup
        # carries where drafting lands (the accelerator bar — one
        # weight stream buys up to k+1 tokens; on a CPU host decode is
        # compute-bound, so the (k+1)-wide verify pays ~(k+1)x compute
        # and end-to-end cannot win by physics); the isolated-machinery
        # arm is the honest CPU fallback — speculation must cost <= 5%
        # of the tick when it buys nothing (see measure_spec_machinery
        # for why the gated ratio is the compiled cost model)
        out["pass_mode"] = (
            "end_to_end"
            if (out["spec_speedup"] or 0) >= args.spec_threshold
            else "machinery"
            if probe["cost_ratio"] <= 1.05
            else None
        )
        out["pass"] = (
            out["token_mismatches"] == 0 and out["pass_mode"] is not None
        )
    if shared and acct is None:
        cold_tokens = cold_sched.tokens_emitted + len(cold_sched.finished)
        out["cold_tokens_per_s"] = round(
            cold_tokens / cold_s, 1
        ) if cold_s > 0 else 0.0
        out["prefix_speedup"] = round(
            out["tokens_per_s"] / out["cold_tokens_per_s"], 3
        ) if out["cold_tokens_per_s"] else None
        out["prefill_chunks_cold"] = cold_sched.prefill_chunks
        out["prefill_chunks_warm"] = sched.prefill_chunks
        out["prefill_chunk_ratio"] = round(
            cold_sched.prefill_chunks / sched.prefill_chunks, 3
        ) if sched.prefill_chunks else None
        # identity is the hard bar: warm admission may only skip
        # prefill work, never move a token
        out["token_mismatches"] = _token_mismatches(cold_sched, sched)
        out["prefix_threshold"] = args.prefix_threshold
        # or-gate (the stall tools' pattern): end-to-end warm/cold
        # tokens/sec carries where prefill dominates the workload (the
        # production bar); the prefill-chunks-EXECUTED drop is the
        # deterministic, host-independent arm — a counter, not a
        # clock — and carries on hosts where decode compute swamps the
        # skipped prefill. Tokens must match bitwise either way.
        out["pass_mode"] = (
            "end_to_end"
            if (out["prefix_speedup"] or 0) >= args.prefix_threshold
            else "prefill_chunks"
            if (out["prefill_chunk_ratio"] or 0) >= 2.0
            else None
        )
        out["pass"] = (
            out["token_mismatches"] == 0
            and out.get("prefix_hit_rate", 0) > 0
            and out["pass_mode"] is not None
        )
    if not drill and args.arrival == "poisson":
        # open-loop section: reports alongside the gated batch numbers
        psched, pelapsed, plat = run_poisson(
            params, cfg, prompts, args, recorder=None
        )
        out["poisson"] = {
            "rate": args.rate,
            "finished": len(psched.finished),
            "tokens_per_s": round(
                (psched.tokens_emitted + len(psched.finished)) / pelapsed, 1
            ) if pelapsed > 0 else 0.0,
            # queue-INCLUSIVE (submit -> finish) latency under load —
            # the open-loop number batch submission cannot show
            "p50_ms": round(_percentile(plat, 0.50), 2),
            "p99_ms": round(_percentile(plat, 0.99), 2),
            "backpressure_ticks": psched.backpressure_ticks,
        }
    if not drill and not spec and not shared:
        out["seq_tokens_per_s"] = round(seq_tokens / seq_s, 1)
        out["scan_tokens_per_s"] = round(scan_tokens / scan_s, 1)
        out["speedup"] = round(
            out["tokens_per_s"] / out["seq_tokens_per_s"], 3
        ) if out["seq_tokens_per_s"] else None
        # steady-state capacity ratio: full-occupancy decode ticks only,
        # both sides (admission work is a per-request constant that a
        # long-running server amortizes to nothing; this is the number
        # the batched decode is responsible for)
        steady = steady_seq = 0.0
        if sched.full_tick_s > 0:
            steady = sched.full_tick_tokens / sched.full_tick_s
        if seq_sched.full_tick_s > 0:
            steady_seq = seq_sched.full_tick_tokens / seq_sched.full_tick_s
        out["steady_tokens_per_s"] = round(steady, 1)
        out["steady_seq_tokens_per_s"] = round(steady_seq, 1)
        out["steady_speedup"] = (
            round(steady / steady_seq, 3) if steady_seq else None
        )
        # tokens must MATCH the single-stream paths stream-for-stream —
        # throughput from wrong tokens is no throughput at all. Both
        # baselines vote: scan reference AND slots=1 serving.
        mismatches = sum(
            1
            for i, o in enumerate(scan_outs)
            if o != next(r for r in sched.finished if r.rid == i).tokens
            or o != next(
                r for r in seq_sched.finished if r.rid == i
            ).tokens
        )
        out["token_mismatches"] = mismatches
        out["threshold"] = args.threshold
        # or-gate (ckpt/input/collective_stall's pattern): the END-TO-END
        # speedup carries where the workload is long enough to amortize
        # admission; the STEADY-STATE ratio is the honest capacity
        # measurement on short CI workloads and noisy shared runners.
        # Either way the tokens must match the single-stream paths.
        out["pass_mode"] = (
            "end_to_end"
            if (out["speedup"] or 0) >= args.threshold
            else "steady_state"
            if (out["steady_speedup"] or 0) >= args.threshold
            else None
        )
        out["pass"] = mismatches == 0 and out["pass_mode"] is not None
    if drill:
        out["drained"] = acct is not None
        if acct is not None:
            out["drain"] = acct
    if recorder is not None:
        recorder.event(
            "run_stop", step=sched.ticks,
            exit_code=EXIT_RESUMABLE if (drill and acct) else 0,
        )
        recorder.close()
    print(json.dumps(out))
    if drill:
        return EXIT_RESUMABLE if acct is not None else 1
    if args.no_gate:
        return 0
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
