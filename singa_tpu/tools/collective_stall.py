"""Measure the gradient-collective stall: exact vs quantized vs overlapped.

The grad_comm claim (parallel/collectives.py) is that casting each
bucket's gradients to a scaled int8/bf16 wire format before the
data-axis reduction, and chaining per-bucket reductions in reverse-topo
(gradient-readiness) order, shrinks the step-end gradient collective
WITHOUT slowing the step: the quantize/dequantize math is cheap
elementwise work, the wire value is a quarter / half the bytes, and the
bucket chain lets the scheduler overlap reductions with backward
compute. This tool — the sibling of ckpt/input/update_stall — measures
it by timing the same small MLP job on an ``ndata``-wide virtual data
mesh six ways:

  exact       no grad_comm block (today's fp32 collective)
  quantized   mode quantized, per-param scales (no bucket chain)
  overlap     mode exact, ``--buckets`` reverse-topo groups chained
  q8_overlap  quantized + bucketized (the full machinery)
  q8_ring     q8_overlap + ``kernels { grad_allreduce: quantized_ring }``
              (the int8-on-the-wire ring, ops/quantized_collective.py)
  q8_hier     q8_overlap + ``kernels { grad_allreduce: q8_hier }`` with
              ``ring { intra_degree: 2 }`` (the two-level hierarchical
              ring: f32 intra-slice hops, int8 inter-slice hops)

and printing one JSON line::

  {"exact_step_ms": .., "quantized_step_ms": .., "overlap_step_ms": ..,
   "q8_overlap_step_ms": .., "q8_ring_step_ms": .., "quantized_ratio":
   .., "overlap_ratio": .., "q8_overlap_ratio": .., "q8_ring_ratio":
   .., "comm_ms": {mode: ..}, "wire_bytes": {..}, "wire_bytes_ratio":
   .., "threshold": .., "pass": ..}

Exit status 0 iff BOTH gates hold. Gate 1 (unchanged): the q8_overlap
machinery keeps step time within ``threshold`` x exact (default 1.0:
the accelerator-host bar, where the wire shrink pays) OR its isolated
per-step machinery cost (the ``measure_comm_ms`` slope fit) stays
under ``machinery_share`` of the exact step (default 5% — the CPU-host
fallback, ckpt_stall's or-gate pattern). The fallback exists because
on this CPU host the same config's compiled step time varies ±10%
BETWEEN PROCESSES (compile-layout luck; measured 0.81-1.16x for
identical programs) while the machinery's true cost — stable under the
slope fit, which subtracts the shared dispatch bias — is 1-2% of the
step; a bare step-ratio gate at 1.0 would be a coin flip on noise, not
a measurement of the machinery. Gate 2 (the q8_ring arm,
attend_stall's deterministic-arm pattern): the ring's step stays
within ``threshold`` x exact (real hardware, where shard_map is not an
emulation) OR the MODELED per-device wire bytes crossing the data axis
drop by >= ``wire_threshold`` (default 3.5) vs the reference fp32
collective — ``wire_bytes_ratio``, counted two ways that must agree:
the analytic ppermute-payload model
(``quantized_collective.modeled_wire_bytes``) and the step jaxpr's
actual ppermute operand bytes (``ppermute_wire_bytes`` — the program,
not a clock), so the ~3.9x int8 byte drop carries on CPU hosts where
wall-clock A/B of a per-shard emulated program is noise. Gate 3 (the
q8_hier arm, same pattern): the hierarchical step stays within
``threshold`` x exact OR its deterministic arm holds — the PER-LEVEL
modeled bytes (``modeled_wire_bytes_levels``) equal the per-level
jaxpr-counted ppermute bytes (``ppermute_wire_bytes_levels``) on both
levels AND the scarce inter-slice bytes times ``intra_degree`` stay at
or under the flat single-level ring's bytes (the exact K(M-1) <= KM-1
identity: the hierarchy never pays MORE on the slow wire than the flat
ring would). At the default ``--ndata 2`` the factored 2x1 geometry is
degenerate (no inter hops — the gate holds trivially); CI runs the
real 2x2 arm with ``--ndata 4 --head 12`` (the 12-wide head keeps
every param chunkable by 4). ``pass_mode`` / ``ring_pass_mode`` /
``hier_pass_mode`` in the JSON say which criterion carried. The
exact mode is the unchanged baseline by construction: an inert/absent
grad_comm block traces the identical program (tests/test_grad_comm.py
pins this at the jaxpr level).

``measure_comm_ms`` is importable (bench.py reuses it per workload
row): it slope-fits the gradient-reduction machinery in isolation —
one jitted program running N chained ``_reduce_grads`` rounds — so the
reported ms is the marginal per-reduction cost, free of dispatch
latency. ``record_comm_probe`` is the trainer's one-shot telemetry
calibration: the same chained program timed once under the ``comm``
phase, so the flight recorder gets a real measured span for
tools/trace.py --summarize's comm share.

Usage::

  python -m singa_tpu.tools.collective_stall [--steps N] [--warmup N]
      [--trials N] [--batch N] [--hidden N] [--head N] [--ndata N]
      [--buckets N] [--dtype int8|bf16] [--zero_update] [--threshold R]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def _comm_inputs(trainer):
    """(grads, residuals) the chained-reduce program runs on: ones in
    the live params' stored shapes (an all-zero gradient would pin the
    int8 scale to its floor — not the representative regime), plus the
    trainer's actual residual buffers."""
    import jax
    import jax.numpy as jnp

    from ..parallel.collectives import is_residual_key

    grads = jax.tree.map(jnp.ones_like, dict(trainer.params))
    res = {
        k: v for k, v in trainer.buffers.items() if is_residual_key(k)
    }
    return grads, res


def _comm_program(trainer, n: int):
    """Jit n chained reduction rounds (the constrain + quantize +
    dequantize + residual-update machinery, nothing else). A
    quantized_ring trainer's rounds run the real shard_map'd ring
    (``_ring_reduce_probe`` — each round's ppermutes move the int8
    chunks); every other mode rides ``_reduce_grads``."""
    import jax
    import jax.numpy as jnp

    reduce = (
        trainer._ring_reduce_probe
        if trainer._comm is not None and trainer._comm.ring
        else trainer._reduce_grads
    )

    def prog(grads, res):
        def body(carry, i):
            g, r = carry
            g2, r2 = reduce(g, r)
            return (g2, {**r, **r2}), jnp.float32(0)

        (g, _), _ = jax.lax.scan(body, (grads, res), jnp.arange(n))
        return g

    # inputs are live-state-shaped (and the residuals ARE the live
    # buffers) — never donate them
    return jax.jit(prog)  # netlint: disable=JAX003


def measure_comm_ms(trainer, i1: int = 4, i2: int = 20,
                    trials: int = 3) -> float:
    """Slope-fit the gradient-reduction machinery in isolation: time two
    chained-round window sizes and return the marginal per-reduction
    cost in ms (bench.py's two-window methodology). For the exact mode
    this is the bare zero_update constraint (~0 off a data mesh)."""
    import jax.numpy as jnp

    grads, res = _comm_inputs(trainer)
    fns = {n: _comm_program(trainer, n) for n in (i1, i2)}

    def run(n) -> float:
        t0 = time.perf_counter()
        g = fns[n](grads, res)
        # value materialization, not block_until_ready (the tunnel can
        # let block_until_ready return early — bench.py's methodology)
        float(jnp.sum(jnp.abs(next(iter(g.values())))))
        return time.perf_counter() - t0

    for n in fns:  # compile
        run(n)
    best = {n: float("inf") for n in fns}
    for _ in range(trials):
        for n in fns:
            best[n] = min(best[n], run(n))
    # floor at 0: a tiny reduction's window delta can sink under
    # dispatch jitter on a contended host — a negative marginal ms must
    # never poison bench rows or the stall JSON
    return max(0.0, (best[i2] - best[i1]) / (i2 - i1) * 1e3)


def record_comm_probe(trainer, rounds: int = 16) -> float:
    """The trainer's one-shot telemetry calibration: run ``rounds``
    chained reductions ONCE under the ``comm`` phase (compile + warmup
    outside the timed region), so the flight recorder gets a real
    measured span whose dur/steps is the per-reduction cost, and emit a
    ``comm_probe`` event carrying the host-side number. Returns the
    per-reduction ms."""
    import jax.numpy as jnp

    grads, res = _comm_inputs(trainer)
    fn = _comm_program(trainer, rounds)

    def run() -> float:
        g = fn(grads, res)
        return float(jnp.sum(jnp.abs(next(iter(g.values())))))

    run()  # compile + warm, outside the span
    t0 = time.perf_counter()
    with trainer.timers.phase("comm", steps=rounds):
        run()
    ms = (time.perf_counter() - t0) / rounds * 1e3
    if trainer.telemetry is not None:
        spec = trainer._comm
        trainer.telemetry.event(
            "comm_probe",
            step=trainer.start_step,
            mode=trainer.comm_mode,
            dtype=trainer.comm_dtype,
            buckets=spec.buckets if spec is not None else 0,
            rounds=rounds,
            comm_ms=round(ms, 4),
        )
    return ms


def _mode_conf(mode: str, dtype: str, buckets: int) -> str:
    """grad_comm conf text for one measured mode ("" for exact)."""
    if mode == "exact":
        return ""
    q8b = (
        f"grad_comm {{ mode: quantized dtype: {dtype} "
        f"buckets: {buckets} }}"
    )
    blocks = {
        "quantized": f'grad_comm {{ mode: quantized dtype: {dtype} }}',
        "overlap": f"grad_comm {{ mode: exact buckets: {buckets} }}",
        "q8_overlap": q8b,
        "q8_ring": q8b + "\nkernels { grad_allreduce: quantized_ring }",
        "q8_hier": (
            q8b
            + "\nkernels { grad_allreduce: q8_hier }"
            + "\nring { intra_degree: 2 }"
        ),
    }
    return blocks[mode]


def measure_wire_bytes(trainer) -> dict:
    """Modeled per-device bytes crossing the data axis per step,
    reference vs quantized_ring, for ONE trainer's real param set (the
    deterministic arm — cost models and the traced program, no clocks).

    ``reference`` prices the fp32 collective the reference path cannot
    narrow (a bandwidth-optimal ring all-reduce of the gradient
    elements; the reduce-scatter half alone under zero_update);
    ``quantized_ring`` is the ring's modeled ppermute payload, and
    ``ring_jaxpr`` re-counts it from the step jaxpr's actual ppermute
    operand bytes x trip counts — the gated model must match what the
    program sends (tests pin equality). A ``q8_hier`` trainer carries
    the per-level split both ways: modeled ``intra``/``inter`` (+
    ``flat_ring``, the same-n single-level baseline) from the trainer's
    model, ``ring_jaxpr_intra``/``ring_jaxpr_inter`` from the jaxpr
    (``ppermute_wire_bytes_levels``), with ``ring_jaxpr`` their sum."""
    import jax
    import jax.numpy as jnp

    from ..ops.quantized_collective import (
        ppermute_wire_bytes,
        ppermute_wire_bytes_levels,
    )

    assert trainer._comm is not None and trainer._comm.ring
    out = trainer.wire_bytes_model()
    batch = trainer._assemble_host_batch(trainer.train_net)
    rng = jax.random.fold_in(trainer._step_key, 0)
    jaxpr = jax.make_jaxpr(trainer._train_step_entry)(
        trainer.params, trainer.state, trainer.buffers, jnp.int32(0),
        batch, rng,
    )
    if trainer._comm.hier and trainer._ring_hier is not None:
        intra_ax, inter_ax, k, _ = trainer._ring_hier
        levels = ppermute_wire_bytes_levels(
            jaxpr, intra_axis=intra_ax, inter_axis=inter_ax,
            intra_degree=k,
        )
        out["ring_jaxpr_intra"] = int(levels["intra"])
        out["ring_jaxpr_inter"] = int(levels["inter"])
        out["ring_jaxpr"] = int(levels["intra"] + levels["inter"])
    else:
        out["ring_jaxpr"] = int(ppermute_wire_bytes(jaxpr))
    return out


def _make_runner(shard: str, batch: int, hidden: int, warmup: int,
                 mode: str, dtype: str, buckets: int, ndata: int,
                 zero: bool, head: int = 10):
    """-> (trainer, window(steps) -> seconds) for one grad_comm mode.

    Every mode runs the identical per-step sync loop on the same
    ndata-wide data mesh (device_cache off, like update_stall); only the
    gradient-collective machinery differs."""
    import jax
    import jax.numpy as jnp

    from ..config import parse_model_config
    from ..parallel import build_mesh
    from ..trainer import Trainer
    from .input_stall import _CONF

    text = _CONF.format(shard=shard, batch=batch, hidden=hidden,
                        head=head)
    block = _mode_conf(mode, dtype, buckets)
    if block:
        text += "\n" + block + "\n"
    cfg = parse_model_config(text)
    cfg.zero_update = zero
    mesh = build_mesh(ndata, 1, jax.devices()[:ndata])
    trainer = Trainer(
        cfg, seed=0, log=lambda s: None, mesh=mesh,
        prefetch=False, device_cache=False,
    )
    quant = ("quantized", "q8_overlap", "q8_ring", "q8_hier")
    want = "quantized" if mode in quant else "exact"
    assert trainer.comm_mode == want, (mode, trainer.comm_mode)
    assert (mode in ("q8_ring", "q8_hier")) == (
        trainer._comm is not None and trainer._comm.ring
    ), mode
    assert (mode == "q8_hier") == (
        trainer._comm is not None and trainer._comm.hier
    ), mode

    def sync() -> float:
        return float(jnp.sum(jnp.abs(next(iter(trainer.params.values())))))

    state = {"step": 0}

    def run(steps: int) -> None:
        step0 = state["step"]
        for s in range(step0, step0 + steps):
            trainer.train_one_batch(s)
        state["step"] = step0 + steps

    run(warmup)  # compile
    sync()

    def window(steps: int) -> float:
        t0 = time.perf_counter()
        run(steps)
        sync()
        return time.perf_counter() - t0

    return trainer, window


MODES = (
    "exact", "quantized", "overlap", "q8_overlap", "q8_ring", "q8_hier",
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="collective_stall", description=__doc__
    )
    ap.add_argument("--steps", type=int, default=12, help="timed steps")
    ap.add_argument("--warmup", type=int, default=4, help="untimed steps")
    ap.add_argument(
        "--trials", type=int, default=3,
        help="windows per mode; the best (least-contended) one counts",
    )
    # the probe regime (update_stall's reasoning): a compute-
    # representative step against which the grad_comm machinery's fixed
    # per-step cost — elementwise quantize math plus the emulated
    # collectives' memcpys, which the int8 wire format shrinks — is the
    # honest small share it is on real models
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument(
        "--head", type=int, default=10,
        help="classifier width; 12 keeps every param chunkable when "
        "--ndata 4 hosts the real 2x2 hierarchical geometry",
    )
    ap.add_argument("--records", type=int, default=8192,
                    help="synthetic dataset size")
    ap.add_argument("--ndata", type=int, default=2,
                    help="data-axis width (virtual CPU devices)")
    ap.add_argument("--buckets", type=int, default=4,
                    help="bucket count for the overlapped modes")
    ap.add_argument("--dtype", choices=("int8", "bf16"), default="int8")
    ap.add_argument(
        "--zero_update", action="store_true",
        help="compose every mode with the ZeRO update sharding (the "
        "quantized reduce-scatter path)",
    )
    ap.add_argument(
        "--threshold", type=float, default=1.0,
        help="max allowed q8_overlap/exact step-time ratio",
    )
    ap.add_argument(
        "--machinery_share", type=float, default=0.05,
        help="CPU-host fallback: pass when the isolated machinery cost "
        "(comm_ms slope fit) is under this share of the exact step",
    )
    ap.add_argument(
        "--wire_threshold", type=float, default=3.5,
        help="q8_ring deterministic arm: min reference/ring modeled "
        "wire-bytes ratio (int8 models ~3.9x; the CPU-host carry)",
    )
    args = ap.parse_args(argv)

    # the device-count flag must land before the first backend query
    # (__graft_entry__.dryrun_multichip's dance)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.ndata}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..data.loader import synthetic_arrays, write_records

    root = tempfile.mkdtemp(prefix="singa_tpu_collective_stall_")
    shard = os.path.join(root, "shard")
    write_records(shard, *synthetic_arrays(args.records, seed=0))
    runners = {
        mode: _make_runner(
            shard, args.batch, args.hidden, args.warmup, mode,
            args.dtype, args.buckets, args.ndata, args.zero_update,
            head=args.head,
        )
        for mode in MODES
    }
    # INTERLEAVED best-of-trials (ckpt/input/update_stall's
    # methodology): one window per mode per round so host-load bursts
    # land on every mode
    best = {mode: float("inf") for mode in runners}
    for _ in range(args.trials):
        for mode, (_, window) in runners.items():
            best[mode] = min(best[mode], window(args.steps) / args.steps)
    ms = {mode: best[mode] * 1e3 for mode in MODES}
    comm_ms = {
        mode: round(measure_comm_ms(t), 3) for mode, (t, _) in runners.items()
    }
    ratio = ms["q8_overlap"] / ms["exact"]
    share = comm_ms["q8_overlap"] / ms["exact"]
    ratio_ok = ratio <= args.threshold
    share_ok = share <= args.machinery_share
    ok = ratio_ok or share_ok
    # --- gate 2: the int8-on-the-wire ring. Wall clock is the real-
    # hardware arm (on CPU the ring is a per-shard emulation, strictly
    # slower); the deterministic arm is the modeled per-device wire
    # bytes crossing the data axis — jaxpr-counted, must drop >=
    # wire_threshold vs the reference fp32 collective ---
    wire = measure_wire_bytes(runners["q8_ring"][0])
    # the gated ratio divides by the JAXPR-counted bytes (what the
    # traced program actually ppermutes), and the analytic model must
    # agree with it exactly — a ring regression that moves extra or
    # wider bytes changes the program count even though the pure
    # size-arithmetic model cannot see it
    wire_ratio = (
        wire["reference"] / wire["ring_jaxpr"]
        if wire["ring_jaxpr"]
        else None
    )
    wire_model_ok = wire["quantized_ring"] == wire["ring_jaxpr"]
    ring_ratio = ms["q8_ring"] / ms["exact"]
    ring_ratio_ok = ring_ratio <= args.threshold
    wire_ok = wire_model_ok and (wire_ratio or 0) >= args.wire_threshold
    ring_ok = ring_ratio_ok or wire_ok
    # --- gate 3: the hierarchical two-level ring. Deterministic arm:
    # the per-level analytic model matches the per-level jaxpr count on
    # BOTH levels, and the scarce inter-slice bytes x intra_degree stay
    # at or under the flat same-n ring (K(M-1) <= KM-1, exact) ---
    hwire = measure_wire_bytes(runners["q8_hier"][0])
    hier_deg = int(hwire.get("intra_degree", 1))
    hier_model_ok = (
        hwire.get("intra") == hwire.get("ring_jaxpr_intra")
        and hwire.get("inter") == hwire.get("ring_jaxpr_inter")
    )
    hier_wire_ok = hier_model_ok and (
        hwire.get("inter", 0) * hier_deg <= hwire.get("flat_ring", 0)
    )
    hier_ratio = ms["q8_hier"] / ms["exact"]
    hier_ratio_ok = hier_ratio <= args.threshold
    hier_ok = hier_ratio_ok or hier_wire_ok
    out = {
        "exact_step_ms": round(ms["exact"], 3),
        "quantized_step_ms": round(ms["quantized"], 3),
        "overlap_step_ms": round(ms["overlap"], 3),
        "q8_overlap_step_ms": round(ms["q8_overlap"], 3),
        "q8_ring_step_ms": round(ms["q8_ring"], 3),
        "q8_hier_step_ms": round(ms["q8_hier"], 3),
        "quantized_ratio": round(ms["quantized"] / ms["exact"], 3),
        "overlap_ratio": round(ms["overlap"] / ms["exact"], 3),
        "q8_overlap_ratio": round(ratio, 3),
        "q8_ring_ratio": round(ring_ratio, 3),
        "q8_hier_ratio": round(hier_ratio, 3),
        "comm_ms": comm_ms,
        "wire_bytes": wire,
        "hier_wire_bytes": hwire,
        "hier_model_matches_jaxpr": hier_model_ok,
        "hier_intra_degree": hier_deg,
        "wire_bytes_ratio": round(wire_ratio, 3) if wire_ratio else None,
        "wire_model_matches_jaxpr": wire_model_ok,
        "wire_threshold": args.wire_threshold,
        "dtype": args.dtype,
        "buckets": args.buckets,
        "ndata": args.ndata,
        "zero_update": bool(args.zero_update),
        "threshold": args.threshold,
        "machinery_share": round(share, 4),
        "machinery_share_threshold": args.machinery_share,
        "pass_mode": (
            ("step_ratio" if ratio_ok else "machinery_share")
            if ok
            else None
        ),
        "ring_pass_mode": (
            ("step_ratio" if ring_ratio_ok else "wire_bytes")
            if ring_ok
            else None
        ),
        "hier_pass_mode": (
            ("step_ratio" if hier_ratio_ok else "wire_bytes")
            if hier_ok
            else None
        ),
        "pass": ok and ring_ok and hier_ok,
    }
    print(json.dumps(out))
    return 0 if (ok and ring_ok and hier_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
