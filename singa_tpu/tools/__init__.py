"""Operator tooling (the reference's script/ + examples/mnist/*.sh):

  graph      net-JSON -> graphviz dot          (script/graph.py)
  draw       training-log curves -> PNG        (script/draw.py)
  partition  record lists across worker groups (script/load_data.py)
  sweep      scaling sweep over mesh sizes     (examples/mnist/batch.sh)
"""
