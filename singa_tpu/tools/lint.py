"""netlint CLI: static validation for job configs and JAX-hazard source lint.

Usage:
  python -m singa_tpu.tools.lint examples/                 # every conf
  python -m singa_tpu.tools.lint job.conf --cluster c.conf # + sharding
  python -m singa_tpu.tools.lint --self                    # AST pass over
                                                           # singa_tpu/
  python -m singa_tpu.tools.lint --list-rules              # rule catalogue

Paths may be .conf files, .py files, or directories (recursively linting
both kinds). Model vs cluster confs are told apart by their fields
(``nworkers``/``workspace`` mark a cluster conf). Sharding divisibility
rules (SHD*) need mesh axis widths, so they run only when ``--cluster``
supplies a cluster conf.

Exit status: 0 = no ERROR diagnostics (WARNING/INFO allowed), 1 = at
least one ERROR (or any WARNING under ``--strict``), 2 = usage error.
Suppress codes globally with ``--ignore CODE[,CODE]``; suppress AST
findings per line with ``# netlint: disable=CODE``.
"""

from __future__ import annotations

import argparse
import os
import sys

from ..config import textproto
from ..lint import (
    Collector,
    elastic_rules,
    engine_rules,
    lint_cluster_text,
    lint_model_text,
    lint_python_file,
    render_json,
    render_rule_table,
    render_text,
    ring_rules,
    sharding_rules_static,
)
from ..lint.ast_rules import walk_source_files
from ..lint.net_rules import CFG000
from ..lint.shape_rules import shape_pass


def _is_cluster_raw(raw: dict) -> bool:
    return "nworkers" in raw or "workspace" in raw


def _lint_conf(
    path: str, col: Collector, widths: dict[str, int] | None,
    cluster_cfg=None,
) -> None:
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        col.emit(CFG000, path, f"cannot read: {e}")
        return
    try:
        raw = textproto.parse(text)
    except textproto.TextProtoError as e:
        col.emit(CFG000, path, str(e))
        return
    if _is_cluster_raw(raw):
        lint_cluster_text(text, path, col, raw=raw)
        return
    errors_before = col.count("ERROR")
    model_cfg = lint_model_text(text, path, col, raw=raw)
    if model_cfg is None:
        return
    # engine-compatibility checks need the cluster conf itself (engine
    # selection reads nservers/synchronous, not the axis widths);
    # ring_rules additionally reads the data-axis width for the
    # chunk-divisibility arm (KRN002)
    engine_rules(model_cfg, cluster_cfg, path, col)
    ring_rules(model_cfg, cluster_cfg, widths, path, col)
    # elastic-restore admission (ELA001) needs the target mesh's axis
    # widths, so it rides --cluster like the SHD*/KRN002 width arms
    elastic_rules(model_cfg, widths, path, col)
    if col.count("ERROR") > errors_before:
        # the graph is already known-broken; building it would only
        # re-report the same breakage through SHP001. The config-level
        # sharding checks are independent of graph validity, though —
        # report everything in one run
        if widths:
            sharding_rules_static(model_cfg, widths, path, col)
        return
    built = shape_pass(model_cfg, path, col, widths)
    if widths:
        # batch divisibility (SHD003) is config-level and always applies;
        # the SHD001 neuron-dim heuristic is only the fallback for nets
        # that could not build (data sources absent) — built nets got the
        # precise per-param check in shape_pass
        sharding_rules_static(
            model_cfg, widths, path, col, neuron_dims=not built
        )


def _collect(paths: list[str]) -> tuple[list[str], list[str], list[str]]:
    """-> (conf files, python files, missing)."""
    confs: list[str] = []
    pys: list[str] = []
    missing: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for full in walk_source_files(p, (".conf", ".py")):
                (confs if full.endswith(".conf") else pys).append(full)
        elif os.path.isfile(p):
            (confs if not p.endswith(".py") else pys).append(p)
        else:
            missing.append(p)
    return confs, pys, missing


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="singa_tpu.tools.lint",
        description="static config/graph/sharding validator + JAX lint",
    )
    ap.add_argument("paths", nargs="*", help=".conf/.py files or dirs")
    ap.add_argument(
        "--cluster",
        default=None,
        help="cluster conf supplying mesh axis widths for SHD* rules",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--self",
        action="store_true",
        dest="self_lint",
        help="AST-lint the installed singa_tpu package source",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="treat WARNING diagnostics as failures",
    )
    ap.add_argument(
        "--ignore",
        default="",
        help="comma-separated diagnostic codes to drop",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        print(render_rule_table())
        return 0
    if not args.paths and not args.self_lint:
        ap.print_usage(sys.stderr)
        print(
            "error: give at least one path, or --self / --list-rules",
            file=sys.stderr,
        )
        return 2

    col = Collector(
        ignore={c.strip() for c in args.ignore.split(",") if c.strip()}
    )

    widths = None
    cluster_cfg = None
    if args.cluster:
        try:
            with open(args.cluster, "r", encoding="utf-8") as f:
                ctext = f.read()
        except OSError as e:
            print(f"error: --cluster {args.cluster}: {e}", file=sys.stderr)
            return 2
        cluster_cfg, widths = lint_cluster_text(ctext, args.cluster, col)

    confs, pys, bad = _collect(args.paths)
    if bad:
        for p in bad:
            print(f"error: no such path {p!r}", file=sys.stderr)
        return 2
    # --cluster already linted its file; don't report it twice when the
    # same conf also arrives via the positional paths
    cluster_real = (
        os.path.realpath(args.cluster) if args.cluster else None
    )
    for path in confs:
        if cluster_real and os.path.realpath(path) == cluster_real:
            continue
        _lint_conf(path, col, widths, cluster_cfg=cluster_cfg)
    if args.self_lint:
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pys.extend(walk_source_files(pkg_root, (".py",)))
    # `lint singa_tpu/ --self` must not report every finding twice
    seen_py: set[str] = set()
    for path in pys:
        real = os.path.realpath(path)
        if real not in seen_py:
            seen_py.add(real)
            lint_python_file(path, col)

    diags = col.sorted()
    if args.format == "json":
        print(render_json(diags))
    elif diags:
        print(render_text(diags))
    nerr = col.count("ERROR")
    nwarn = col.count("WARNING")
    if args.format == "text":
        scanned = len(confs) + len(seen_py)
        print(
            f"netlint: {scanned} target(s), {nerr} error(s), "
            f"{nwarn} warning(s)"
        )
    return 1 if col.has_errors(strict=args.strict) else 0


if __name__ == "__main__":
    sys.exit(main())
