"""netlint CLI: static validation for job configs and JAX-hazard source lint.

Usage:
  python -m singa_tpu.tools.lint examples/                 # every conf
  python -m singa_tpu.tools.lint job.conf --cluster c.conf # + sharding
  python -m singa_tpu.tools.lint --self                    # AST pass over
                                                           # singa_tpu/
  python -m singa_tpu.tools.lint --list-rules              # rule catalogue
  python -m singa_tpu.tools.lint job.conf --cluster c.conf --explain-cost
                                                           # cost report
  python -m singa_tpu.tools.lint job.conf --fix [--dry-run]
                                                           # did-you-mean
                                                           # rewrites

Paths may be .conf files, .py files, or directories (recursively linting
both kinds). Model vs cluster confs are told apart by their fields
(``nworkers``/``workspace`` mark a cluster conf). Sharding divisibility
rules (SHD*) need mesh axis widths, so they run only when ``--cluster``
supplies a cluster conf.

Exit status: 0 = no ERROR diagnostics (WARNING/INFO allowed), 1 = at
least one ERROR (or any WARNING under ``--strict``), 2 = usage error.
Suppress codes globally with ``--ignore CODE[,CODE]``; suppress AST
findings per line with ``# netlint: disable=CODE``.
"""

from __future__ import annotations

import argparse
import difflib
import os
import sys
import tempfile

from ..config import textproto
from ..lint import (
    Collector,
    elastic_rules,
    engine_rules,
    lint_cluster_text,
    lint_model_text,
    lint_python_file,
    render_json,
    render_rule_table,
    render_text,
    ring_rules,
    sharding_rules_static,
)
from ..lint.ast_rules import walk_source_files
from ..lint.cost_model import (
    DEFAULT_COMM_FRACTION,
    cost_rules,
    render_cost_report,
)
from ..lint.net_rules import CFG000
from ..lint.shape_rules import shape_pass


def _is_cluster_raw(raw: dict) -> bool:
    return "nworkers" in raw or "workspace" in raw


def _lint_conf(
    path: str, col: Collector, widths: dict[str, int] | None,
    cluster_cfg=None, comm_fraction: float = DEFAULT_COMM_FRACTION,
    reports: list | None = None,
) -> None:
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        col.emit(CFG000, path, f"cannot read: {e}")
        return
    try:
        raw = textproto.parse(text)
    except textproto.TextProtoError as e:
        col.emit(CFG000, path, str(e))
        return
    if _is_cluster_raw(raw):
        lint_cluster_text(text, path, col, raw=raw)
        return
    errors_before = col.count("ERROR")
    model_cfg = lint_model_text(text, path, col, raw=raw)
    if model_cfg is None:
        return
    # engine-compatibility checks need the cluster conf itself (engine
    # selection reads nservers/synchronous, not the axis widths);
    # ring_rules additionally reads the data-axis width for the
    # chunk-divisibility arm (KRN002)
    engine_rules(model_cfg, cluster_cfg, path, col)
    ring_rules(model_cfg, cluster_cfg, widths, path, col)
    # elastic-restore admission (ELA001) needs the target mesh's axis
    # widths, so it rides --cluster like the SHD*/KRN002 width arms
    elastic_rules(model_cfg, widths, path, col)
    if col.count("ERROR") > errors_before:
        # the graph is already known-broken; building it would only
        # re-report the same breakage through SHP001. The config-level
        # sharding checks are independent of graph validity, though —
        # report everything in one run
        if widths:
            sharding_rules_static(model_cfg, widths, path, col)
        return
    built = shape_pass(model_cfg, path, col, widths)
    if widths:
        # batch divisibility (SHD003) is config-level and always applies;
        # the SHD001 neuron-dim heuristic is only the fallback for nets
        # that could not build (data sources absent) — built nets got the
        # precise per-param check in shape_pass
        sharding_rules_static(
            model_cfg, widths, path, col, neuron_dims=not built
        )
    # cost-aware shardlint (MEM001/COST001/SRV002/FLT002): the static
    # HBM/collective/bubble model; returns the --explain-cost report
    # when the train net built
    report = cost_rules(
        model_cfg, cluster_cfg, widths, path, col,
        comm_fraction=comm_fraction,
    )
    if reports is not None and report is not None:
        reports.append(report)


def apply_fixes(
    diags: list, *, dry_run: bool = False, out=None
) -> int:
    """Apply the machine-applicable ``Diagnostic.fix`` rewrites
    (CFG001/CFG002 single-candidate did-you-means) in place; -> number
    of fixes applied (or that WOULD apply under ``dry_run``, which
    prints a unified diff instead of writing).

    Each fix is re-verified against the file text at its recorded
    (line, col) span before anything is touched — a quoted enum value's
    span points at the opening quote, so a leading quote is tolerated —
    and files are rewritten atomically (tmp + rename). Fixes land
    bottom-up so earlier spans stay valid."""
    if out is None:
        # resolve at call time: binding sys.stdout as the default would
        # pin the stream the interpreter had at import
        out = sys.stdout
    by_path: dict[str, list] = {}
    for d in diags:
        if d.fix is not None and d.code in ("CFG001", "CFG002"):
            by_path.setdefault(d.fix.path, []).append(d.fix)
    applied = 0
    for path, fixes in sorted(by_path.items()):
        try:
            with open(path, "r", encoding="utf-8") as f:
                old_text = f.read()
        except OSError as e:
            print(f"--fix: cannot read {path}: {e}", file=sys.stderr)
            continue
        lines = old_text.splitlines(keepends=True)
        changed = 0
        for fix in sorted(
            fixes, key=lambda x: (x.line, x.col), reverse=True
        ):
            if not 1 <= fix.line <= len(lines):
                continue
            line = lines[fix.line - 1]
            i = fix.col - 1
            if line[i : i + len(fix.old)] != fix.old:
                if line[i : i + 1] in "\"'" and line[
                    i + 1 : i + 1 + len(fix.old)
                ] == fix.old:
                    i += 1  # quoted value: span points at the quote
                else:
                    continue  # text drifted since the parse: skip
            lines[fix.line - 1] = (
                line[:i] + fix.new + line[i + len(fix.old):]
            )
            changed += 1
        if not changed:
            continue
        new_text = "".join(lines)
        if dry_run:
            out.write(
                "".join(
                    difflib.unified_diff(
                        old_text.splitlines(keepends=True),
                        new_text.splitlines(keepends=True),
                        fromfile=path,
                        tofile=f"{path} (fixed)",
                    )
                )
            )
        else:
            d = os.path.dirname(os.path.abspath(path)) or "."
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(path) + ".", dir=d
            )
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    f.write(new_text)
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                finally:
                    raise
        applied += changed
    return applied


def _collect(paths: list[str]) -> tuple[list[str], list[str], list[str]]:
    """-> (conf files, python files, missing)."""
    confs: list[str] = []
    pys: list[str] = []
    missing: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for full in walk_source_files(p, (".conf", ".py")):
                (confs if full.endswith(".conf") else pys).append(full)
        elif os.path.isfile(p):
            (confs if not p.endswith(".py") else pys).append(p)
        else:
            missing.append(p)
    return confs, pys, missing


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="singa_tpu.tools.lint",
        description="static config/graph/sharding validator + JAX lint",
    )
    ap.add_argument("paths", nargs="*", help=".conf/.py files or dirs")
    ap.add_argument(
        "--cluster",
        default=None,
        help="cluster conf supplying mesh axis widths for SHD* rules",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--self",
        action="store_true",
        dest="self_lint",
        help="AST-lint the installed singa_tpu package source",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="treat WARNING diagnostics as failures",
    )
    ap.add_argument(
        "--ignore",
        default="",
        help="comma-separated diagnostic codes to drop",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    ap.add_argument(
        "--explain-cost",
        action="store_true",
        help="print the per-conf cost-model report table (HBM components, "
        "collective bytes, pipeline bubble)",
    )
    ap.add_argument(
        "--cost-comm-fraction",
        type=float,
        default=DEFAULT_COMM_FRACTION,
        metavar="F",
        help="COST001 fires when modeled collective bytes exceed F x "
        f"modeled compute bytes (default {DEFAULT_COMM_FRACTION}; "
        "0 disables)",
    )
    ap.add_argument(
        "--fix",
        action="store_true",
        help="apply unambiguous CFG001/CFG002 did-you-mean rewrites in "
        "place (atomic write); with --dry-run, print the diff instead",
    )
    ap.add_argument(
        "--dry-run",
        action="store_true",
        help="with --fix: show the unified diff without writing",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        print(render_rule_table())
        return 0
    if not args.paths and not args.self_lint:
        ap.print_usage(sys.stderr)
        print(
            "error: give at least one path, or --self / --list-rules",
            file=sys.stderr,
        )
        return 2

    col = Collector(
        ignore={c.strip() for c in args.ignore.split(",") if c.strip()}
    )

    widths = None
    cluster_cfg = None
    if args.cluster:
        try:
            with open(args.cluster, "r", encoding="utf-8") as f:
                ctext = f.read()
        except OSError as e:
            print(f"error: --cluster {args.cluster}: {e}", file=sys.stderr)
            return 2
        cluster_cfg, widths = lint_cluster_text(ctext, args.cluster, col)

    confs, pys, bad = _collect(args.paths)
    if bad:
        for p in bad:
            print(f"error: no such path {p!r}", file=sys.stderr)
        return 2
    # --cluster already linted its file; don't report it twice when the
    # same conf also arrives via the positional paths
    cluster_real = (
        os.path.realpath(args.cluster) if args.cluster else None
    )
    reports: list = []
    for path in confs:
        if cluster_real and os.path.realpath(path) == cluster_real:
            continue
        _lint_conf(
            path, col, widths, cluster_cfg=cluster_cfg,
            comm_fraction=args.cost_comm_fraction, reports=reports,
        )
    if args.self_lint:
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pys.extend(walk_source_files(pkg_root, (".py",)))
    # `lint singa_tpu/ --self` must not report every finding twice
    seen_py: set[str] = set()
    for path in pys:
        real = os.path.realpath(path)
        if real not in seen_py:
            seen_py.add(real)
            lint_python_file(path, col)

    diags = col.sorted()
    if args.format == "json":
        print(render_json(diags))
    elif diags:
        print(render_text(diags))
    if args.explain_cost:
        for report in reports:
            print(render_cost_report(report))
    if args.fix:
        applied = apply_fixes(diags, dry_run=args.dry_run)
        verb = "would apply" if args.dry_run else "applied"
        print(f"netlint --fix: {verb} {applied} fix(es)")
    nerr = col.count("ERROR")
    nwarn = col.count("WARNING")
    if args.format == "text":
        scanned = len(confs) + len(seen_py)
        print(
            f"netlint: {scanned} target(s), {nerr} error(s), "
            f"{nwarn} warning(s)"
        )
    return 1 if col.has_errors(strict=args.strict) else 0


if __name__ == "__main__":
    sys.exit(main())
