"""Plot training-log curves to a PNG.

The reference plots metric columns from log files with matplotlib
(script/draw.py). This parses the trainer's own log lines —

    step 90: train loss : 0.825172, precision : 0.907813 [...]
    step 100: test loss : 0.668926, precision : 0.907813

— into per-phase series and renders one subplot per metric (never a
dual-axis chart: loss and precision live on different scales, so each
gets its own axis). Phases take fixed categorical colors: train, test,
validation — assignment never reshuffles when a phase is absent.

Usage:
  python -m singa_tpu.tools.draw --log train.log --output curves.png [--logx]
"""

from __future__ import annotations

import argparse
import re
import sys
from collections import defaultdict

# fixed categorical slots (validated default palette, light mode)
_PHASE_COLORS = {
    "train": "#2a78d6",
    "test": "#eb6834",
    "validation": "#1baf7a",
}
_SURFACE = "#fcfcfb"
_TEXT = "#0b0b0b"
_TEXT_2 = "#52514e"
_GRID = "#e4e3df"

_LINE = re.compile(
    r"step (\d+): (train|test|validation)\b[^A-Za-z]*(.*)"
)
_METRIC = re.compile(r"([A-Za-z_][\w ]*?)\s*:\s*([-+eE.\d]+)")


def parse_log(text: str) -> dict[str, dict[str, list[tuple[int, float]]]]:
    """-> {metric: {phase: [(step, value), ...]}}"""
    out: dict[str, dict[str, list[tuple[int, float]]]] = defaultdict(
        lambda: defaultdict(list)
    )
    for line in text.splitlines():
        m = _LINE.search(line)
        if not m:
            continue
        step, phase, rest = int(m.group(1)), m.group(2), m.group(3)
        rest = rest.split("[")[0]  # strip the timer suffix
        for name, val in _METRIC.findall(rest):
            try:
                out[name.strip()][phase].append((step, float(val)))
            except ValueError:
                continue
    return {k: dict(v) for k, v in out.items()}


def draw(curves, output: str, logx: bool = False) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    metrics = sorted(curves)
    fig, axes = plt.subplots(
        len(metrics), 1, figsize=(8, 3.2 * len(metrics)),
        squeeze=False, facecolor=_SURFACE,
    )
    for ax, metric in zip(axes[:, 0], metrics):
        ax.set_facecolor(_SURFACE)
        for phase in ("train", "test", "validation"):  # fixed slot order
            series = curves[metric].get(phase)
            if not series:
                continue
            xs, ys = zip(*series)
            ax.plot(
                xs, ys, color=_PHASE_COLORS[phase], linewidth=2,
                label=phase, solid_capstyle="round",
            )
        if logx:
            ax.set_xscale("log")
        ax.set_ylabel(metric, color=_TEXT)
        ax.grid(True, color=_GRID, linewidth=0.8)
        ax.tick_params(colors=_TEXT_2)
        for spine in ax.spines.values():
            spine.set_visible(False)
        if sum(bool(curves[metric].get(p)) for p in _PHASE_COLORS) > 1:
            ax.legend(frameon=False, labelcolor=_TEXT)
    axes[-1, 0].set_xlabel("step", color=_TEXT)
    fig.tight_layout()
    fig.savefig(output, dpi=120)
    plt.close(fig)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="singa_tpu.tools.draw")
    ap.add_argument("--log", required=True, help="trainer log file")
    ap.add_argument("--output", required=True, help="output PNG")
    ap.add_argument("--logx", action="store_true", help="log-scale steps")
    args = ap.parse_args(argv)
    with open(args.log) as f:
        curves = parse_log(f.read())
    if not curves:
        print("no metric lines found in log", file=sys.stderr)
        return 1
    draw(curves, args.output, args.logx)
    print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
