"""Cluster launch & admin: the reference's run.sh / node.sh, TPU-native.

The reference launches multi-host jobs by ssh-ing ``build/singa
-procsID=$count -hostfile ...`` onto each hostfile line with lock files
for liveness (examples/mnist/run.sh:19-37), and administers the fleet
with node.sh verbs (ps/ls/scp/ssh/exec over the hostfile). This module
is that operator surface for singa-tpu:

    python -m singa_tpu.tools.cluster start -n 2 -hostfile hf \
        -model_conf job.conf [-cluster_conf c.conf] [-workspace ws]
    python -m singa_tpu.tools.cluster stop -hostfile hf
    python -m singa_tpu.tools.cluster ps|ssh -hostfile hf
    python -m singa_tpu.tools.cluster ls|exec -hostfile hf -arg <path|cmd>
    python -m singa_tpu.tools.cluster scp -hostfile hf -arg <path>

``start`` runs ``python -m singa_tpu.main -procsID=k -hostfile ...`` on
hostfile line k — in-process rank k rendezvouses through
jax.distributed (parallel/launch.py), the collective replacement for
the reference's Router PING/PONG barrier. Local addresses (localhost /
127.x / this hostname) launch as child processes; anything else goes
over ssh with the reference's non-interactive options. Liveness uses
pid files in <workspace>/procs (the run.sh lock-file discipline:
created at spawn, removed by ``stop``; ``ps`` reports stale ones).

TPU pods don't need any of this: the pod runtime launches one process
per host itself and injects the coordinator environment, so the whole
job is

    gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all \
        --command="cd singa-tpu && python -m singa_tpu.main \
                   -model_conf examples/mnist/mlp.conf"

(init_distributed sees the pod environment and calls
jax.distributed.initialize() with no arguments). This module is for
reference-style CPU/GPU fleets and local multi-process runs.
"""

from __future__ import annotations

import argparse
import os
import re
import shlex
import signal
import socket
import subprocess
import sys

from ..parallel.launch import read_hostfile

SSH_OPTS = [
    "-oStrictHostKeyChecking=no",
    "-oUserKnownHostsFile=/dev/null",
    "-oLogLevel=quiet",
]


def _is_local(host: str) -> bool:
    name = host.split(":", 1)[0]
    return name in ("localhost", "127.0.0.1", socket.gethostname()) or (
        name.startswith("127.")
    )


def _ssh(host: str, cmd: str, background: bool = False):
    argv = ["ssh", *SSH_OPTS, host.split(":", 1)[0], cmd]
    if background:
        return subprocess.Popen(argv)
    return subprocess.run(argv, capture_output=True, text=True)


def _proc_dir(workspace: str) -> str:
    d = os.path.join(workspace, "procs")
    os.makedirs(d, exist_ok=True)
    return d


def start(args) -> int:
    hosts = read_hostfile(args.hostfile)
    n = args.n or len(hosts)
    if n > len(hosts):
        print(
            f"start: asked for {n} procs but hostfile has {len(hosts)} "
            "lines", file=sys.stderr,
        )
        return 2
    pdir = _proc_dir(args.workspace)
    hostfile = os.path.abspath(args.hostfile)
    if n < len(hosts):
        # children must rendezvous as an n-process job: hand them a
        # truncated hostfile, or init_distributed would block forever
        # waiting for ranks that never launch
        hostfile = os.path.join(pdir, "hostfile")
        with open(hostfile, "w") as f:
            f.write("\n".join(hosts[:n]) + "\n")
    # children must import singa_tpu regardless of the operator's cwd:
    # put the package's parent directory on their PYTHONPATH (a pip
    # install wouldn't need this; the in-repo layout does)
    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable, "-m", "singa_tpu.main",
        "-model_conf", os.path.abspath(args.model_conf),
        "-hostfile", hostfile,
    ]
    if args.cluster_conf:
        cmd += ["-cluster_conf", os.path.abspath(args.cluster_conf)]
    launches: list[tuple[int, str, subprocess.Popen]] = []
    for rank in range(n):
        host = hosts[rank]
        rank_cmd = cmd + ["-procsID", str(rank)]
        log = os.path.join(pdir, f"rank{rank}.log")
        pidfile = os.path.join(pdir, f"rank{rank}.pid")
        if _is_local(host):
            with open(log, "w") as lf:
                p = subprocess.Popen(
                    rank_cmd, stdout=lf, stderr=subprocess.STDOUT,
                    cwd=os.getcwd(), env=env,
                )
            with open(pidfile, "w") as pf:
                pf.write(str(p.pid))
            print(f"rank {rank} on {host}: pid {p.pid} (log {log})")
        else:
            # the reference's ssh fan-out (run.sh:19-37); the remote
            # writes its own pid file next to its log. pid files /
            # logs assume the workspace is a SHARED filesystem (NFS) —
            # without one, `stop` falls back to pkill over ssh.
            remote = (
                f"mkdir -p {shlex.quote(pdir)} && "
                f"cd {shlex.quote(os.getcwd())} && "
                f"PYTHONPATH={shlex.quote(pkg_parent)}:$PYTHONPATH "
                f"nohup {shlex.join(rank_cmd)} > {shlex.quote(log)} 2>&1 "
                f"& echo $! > {shlex.quote(pidfile)}"
            )
            launches.append((rank, host, _ssh(host, remote, background=True)))
            print(f"rank {rank} on {host}: launching over ssh (log {log})")
    # the ssh commands background the trainer and exit immediately, so a
    # short wait surfaces unreachable hosts/bad keys instead of leaving
    # the local ranks hanging at the rendezvous with no clue why
    rc = 0
    for rank, host, p in launches:
        try:
            if p.wait(timeout=20) != 0:
                print(
                    f"rank {rank} on {host}: ssh launch FAILED "
                    f"(rc={p.returncode}) — remaining ranks will block at "
                    "the rendezvous until this rank starts",
                    file=sys.stderr,
                )
                rc = 1
        except subprocess.TimeoutExpired:
            print(f"rank {rank} on {host}: ssh still connecting...")
    return rc


def _pids(workspace: str) -> dict[int, tuple[str, int]]:
    pdir = _proc_dir(workspace)
    out = {}
    for f in sorted(os.listdir(pdir)):
        if f.startswith("rank") and f.endswith(".pid"):
            rank = int(f[4:-4])
            with open(os.path.join(pdir, f)) as pf:
                out[rank] = (os.path.join(pdir, f), int(pf.read().strip()))
    return out


def _alive(pid: int) -> bool:
    """True for a RUNNING process. Zombies count as dead: start() holds
    the local children's Popen handles without waiting, so an exited
    child stays a zombie until this process exits — os.kill(pid, 0)
    succeeds on it, and treating that as alive made `stop`/wait loops
    burn their full deadlines on already-finished ranks."""
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            # state = first field after the parenthesized comm (which
            # may itself contain spaces/parens — split on the LAST ')')
            if f.read().rsplit(")", 1)[1].split()[0] == "Z":
                return False
    except (OSError, IndexError):  # no /proc: keep the kill(0) answer
        pass
    return True


def _is_singa_main(pid: int) -> bool:
    """Guard against recycled PIDs in stale pid files: only SIGTERM a
    process whose cmdline is actually a singa_tpu.main run. Where the
    check is impossible (no /proc — e.g. macOS), fall back to trusting
    the pid file rather than refusing to stop live children."""
    if not os.path.isdir("/proc"):
        return True
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return b"singa_tpu" in f.read()
    except OSError:  # pid's /proc entry gone
        return False


def _stop_scope_pattern(args) -> str:
    """pkill -f pattern scoped to THIS job's children, not every
    singa_tpu.main on the host: children carry -model_conf and
    -hostfile as absolute paths on their cmdlines (see start())."""
    tokens = []
    if args.model_conf:
        tokens.append(re.escape(os.path.abspath(args.model_conf)))
    # either the operator's hostfile or the truncated copy start() wrote
    # into the workspace
    tokens.append(re.escape(os.path.abspath(args.hostfile)))
    tokens.append(re.escape(os.path.join(_proc_dir(args.workspace), "hostfile")))
    return f"singa_tpu[.]main.*({'|'.join(tokens)})"


def stop(args) -> int:
    hosts = read_hostfile(args.hostfile)
    pids = _pids(args.workspace)
    for rank, (pidfile, pid) in sorted(pids.items()):
        host = hosts[rank] if rank < len(hosts) else "localhost"
        if _is_local(host):
            if _alive(pid) and _is_singa_main(pid):
                os.kill(pid, signal.SIGTERM)
                print(f"rank {rank}: SIGTERM pid {pid}")
            elif _alive(pid):
                print(
                    f"rank {rank}: pid {pid} is not a singa_tpu.main "
                    "process (recycled pid?) — leaving it alone"
                )
            else:
                print(f"rank {rank}: pid {pid} already gone")
        else:
            _ssh(host, f"kill {pid} 2>/dev/null || true")
            print(f"rank {rank} on {host}: kill {pid} over ssh")
        os.unlink(pidfile)
    # remote ranks whose pid files live on the remote disk (workspace
    # not shared) have no local record — sweep them the run.sh way
    # ("killall -q singa", run.sh:42-45)
    recorded = set(pids)
    pat = _stop_scope_pattern(args)
    for rank, host in enumerate(hosts):
        if rank not in recorded and not _is_local(host):
            # shlex.quote, not manual single quotes: re.escape protects
            # the regex but a workspace/conf path containing a quote
            # would break the remote shell string (and the alternation
            # would silently match nothing)
            _ssh(host, f"pkill -f {shlex.quote(pat)} 2>/dev/null || true")
            print(f"{host}: pkill -f {shlex.quote(pat)} (no local pid record)")
    return 0


def ps(args) -> int:
    hosts = read_hostfile(args.hostfile)
    pids = _pids(args.workspace)
    if pids:
        for rank, (_, pid) in sorted(pids.items()):
            host = hosts[rank] if rank < len(hosts) else "localhost"
            state = "alive" if _is_local(host) and _alive(pid) else (
                "remote" if not _is_local(host) else "DEAD (stale pidfile)"
            )
            print(f"rank {rank} on {host}: pid {pid} {state}")
        return 0
    for host in hosts:  # no workspace records: fleet-wide pgrep
        if _is_local(host):
            r = subprocess.run(
                ["pgrep", "-af", "singa_tpu.main"],
                capture_output=True, text=True,
            )
            print(f"{host}:\n{r.stdout}", end="")
        else:
            r = _ssh(host, "pgrep -af singa_tpu.main || true")
            print(f"{host}:\n{r.stdout}", end="")
    return 0


def fleet_exec(args) -> int:
    """node.sh's generic verb: run a command on every host. Nonzero when
    any host failed, so &&-chained launch scripts fail fast."""
    rc = 0
    for host in read_hostfile(args.hostfile):
        if _is_local(host):
            r = subprocess.run(
                args.arg, shell=True, capture_output=True, text=True
            )
        else:
            r = _ssh(host, args.arg)
        rc = rc or r.returncode
        print(f"--- {host} (rc={r.returncode})\n{r.stdout}{r.stderr}", end="")
    return rc


def fleet_ls(args) -> int:
    args.arg = f"ls -l {shlex.quote(args.arg)}"
    return fleet_exec(args)


def fleet_ssh(args) -> int:
    """Connectivity check (node.sh `ssh` verb)."""
    ok = True
    for host in read_hostfile(args.hostfile):
        if _is_local(host):
            print(f"{host}: local")
            continue
        r = _ssh(host, "exit")
        state = "ok" if r.returncode == 0 else f"FAILED rc={r.returncode}"
        ok = ok and r.returncode == 0
        print(f"{host}: {state}")
    return 0 if ok else 1


def fleet_scp(args) -> int:
    """Push a path to every remote host at the SAME absolute path
    (node.sh `scp` verb) — a relative destination would resolve against
    the remote home while `start` cd's into this cwd."""
    path = os.path.abspath(args.arg)
    rc = 0
    for host in read_hostfile(args.hostfile):
        if _is_local(host):
            print(f"{host}: local, skipping")
            continue
        r = subprocess.run(
            ["scp", *SSH_OPTS, "-r", path,
             f"{host.split(':', 1)[0]}:{path}"],
            capture_output=True, text=True,
        )
        rc = rc or r.returncode
        print(f"{host}: rc={r.returncode} {r.stderr}".rstrip())
    return rc


VERBS = {
    "start": start,
    "stop": stop,
    "ps": ps,
    "ls": fleet_ls,
    "ssh": fleet_ssh,
    "scp": fleet_scp,
    "exec": fleet_exec,
}


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="singa_tpu.tools.cluster",
                                 description=__doc__)
    ap.add_argument("verb", choices=sorted(VERBS))
    ap.add_argument("-hostfile", required=True)
    ap.add_argument("-n", type=int, default=0,
                    help="process count (start; default: every host)")
    ap.add_argument("-model_conf", default=None)
    ap.add_argument("-cluster_conf", default=None)
    ap.add_argument("-workspace", default="ws",
                    help="pid files + logs land in <workspace>/procs")
    ap.add_argument("-arg", default="",
                    help="path (ls/scp) or command (exec)")
    args = ap.parse_args(argv)
    if args.verb == "start" and not args.model_conf:
        ap.error("start requires -model_conf")
    return VERBS[args.verb](args)


if __name__ == "__main__":
    sys.exit(main())
