"""Fused-paged-attention gate: reference vs Pallas decode ticks plus a
modeled attention-bytes comparison (sibling of ckpt/input/update/
collective_stall).

Measures the serving engine's hot path two ways on the SAME weights,
workload, and pool geometry:

  wall clock   interleaved best-of-trials decode-tick timing on two
               fully-occupied engines — ``kernels { paged_attention:
               reference }`` vs ``fused`` — the end-to-end arm.
  bytes model  the attention seam's memory traffic per decode tick per
               layer: the REFERENCE side is XLA's compiled cost model
               ("bytes accessed") of the isolated gather ->
               ``cache_attend`` program — it prices the dense
               ``(slots, H, cache_len, D)`` materialization the engine
               pays per layer per tick; the FUSED side is the kernel's
               own block-tile read model
               (``ops.paged_attention.modeled_bytes`` — what its
               CostEstimate declares on hardware: Q + the live K/V
               block tiles the clamped grid fetches + O). The XLA cost
               analysis of the INTERPRETED kernel is reported
               alongside un-gated (``fused_xla_bytes``): it models the
               emulation's loop-carried buffers, not the kernel's
               traffic, so gating on it would measure the interpreter,
               not the kernel.

Or-gate (the stall tools' pattern): fused end-to-end decode tokens/sec
>= ``--threshold`` (default 1.1) x reference, OR the modeled
attention-bytes drop >= ``--bytes_threshold`` (default 2.0) — the
deterministic, host-independent arm. On this repo's CPU CI hosts the
bytes arm carries: the fused kernel runs through the Pallas
interpreter there (a fori_loop emulation that is strictly slower than
XLA's fused dense attend), so the wall-clock arm only wins on a real
TPU where the kernel compiles through Mosaic. Token streams must be
IDENTICAL between the two engines either way — a kernel may only move
bytes, never a token.

Usage::

  python -m singa_tpu.tools.attend_stall [--concurrency 8]
      [--d_model 256] [--n_layers 2] [--n_heads 2] [--vocab 256]
      [--max_len 128] [--block_len 16] [--prefill_chunk 16]
      [--requests 8] [--max_new 16] [--trials 3] [--ticks 10]
      [--threshold 1.1] [--bytes_threshold 2.0] [--no_gate]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="attend_stall", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--d_model", type=int, default=256)
    ap.add_argument("--n_layers", type=int, default=2)
    ap.add_argument("--n_heads", type=int, default=2)
    ap.add_argument("--d_ff", type=int, default=1024)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--max_len", type=int, default=128)
    ap.add_argument("--block_len", type=int, default=16)
    ap.add_argument("--kv_blocks", type=int, default=0)
    ap.add_argument("--prefill_chunk", type=int, default=16)
    ap.add_argument("--prompt_len", type=int, default=8)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--max_new", type=int, default=16)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--threshold", type=float, default=1.1,
                    help="min fused/reference decode tokens/sec (the "
                    "end-to-end or-gate arm; real-TPU bar)")
    ap.add_argument("--bytes_threshold", type=float, default=2.0,
                    help="min reference/fused modeled attention-bytes "
                    "ratio (the deterministic or-gate arm)")
    ap.add_argument("--no_gate", action="store_true")
    return ap


def _serving(args, impl):
    from ..serve import EngineConfig

    return EngineConfig(
        slots=args.concurrency,
        kv_block_len=args.block_len,
        kv_blocks=args.kv_blocks,
        max_prefill_chunk=args.prefill_chunk,
        attend_impl=impl,
    )


def _filled_engine(params, cfg, args, impl):
    """An engine with every slot admitted, prefilled, and live — the
    full-occupancy steady state the decode-tick probe times."""
    import numpy as np

    from ..serve import Engine

    engine = Engine(params, cfg, _serving(args, impl))
    rs = np.random.RandomState(args.seed)
    plen = min(args.prompt_len, max(1, cfg.max_len // 4))
    for s in range(args.concurrency):
        pr = rs.randint(0, args.vocab, size=(plen,)).astype(np.int32)
        engine.admit(s, cfg.max_len)
        last = engine.prefill_chunk(s, pr, 0)
        engine.activate(s, last, plen, seed=s)
    return engine, plen


def measure_attend_bytes(params, cfg, args):
    """Modeled memory traffic of the attention seam for ONE decode tick
    of ONE layer at the probe's cache fill. -> dict with the gated
    ``bytes_ratio`` (reference XLA model / fused block-tile model) and
    the transparency numbers. Deterministic: compiled cost analysis +
    arithmetic, no clocks."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import cache_attend
    from ..ops.paged_attention import (
        live_blocks,
        modeled_bytes,
        paged_attention,
    )

    engine, plen = _filled_engine(params, cfg, args, "reference")
    s, h, d = args.concurrency, cfg.n_heads, cfg.head_dim
    bl = engine.pool.block_len
    kp, vp = engine.state["k"][0], engine.state["v"][0]
    tables = engine.state["tables"]
    # mid-generation cache fill: the steady state a serving pool sits
    # at (deterministic — derived from the workload, not measured)
    pos = jnp.full((s, 1), plen + args.max_new // 2, jnp.int32)
    q = jnp.zeros((s, h, 1, d))

    def ref_attend(q, kp, vp, tables, pos):
        return cache_attend(q, *engine._gather_kv(kp, vp, tables), pos)

    def fused_attend(q, kp, vp, tables, pos):
        return paged_attention(q, kp, vp, tables, pos, interpret=True)

    def xla_bytes(fn):
        c = jax.jit(fn).lower(q, kp, vp, tables, pos).compile()
        ca = c.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else (ca or {})
        return float(ca.get("bytes accessed", 0.0))

    ref_bytes = xla_bytes(ref_attend)
    fused_xla = xla_bytes(fused_attend)
    # the kernel's own clamp formula — the gated model cannot drift
    # from what the grid fetches
    live_total = int(s * int(live_blocks(
        int(pos[0, 0]), bl, engine.pool.max_blocks_per_seq
    )))
    fused_model = modeled_bytes(s, h, 1, d, bl, live_total)
    return {
        "ref_bytes": ref_bytes,
        "fused_bytes": float(fused_model),
        "fused_xla_bytes": fused_xla,
        "bytes_ratio": round(ref_bytes / fused_model, 3)
        if fused_model else None,
        "cache_fill": int(pos[0, 0]),
        "live_blocks": live_total,
    }


def measure_decode_ticks(params, cfg, args):
    """Interleaved best-of-trials decode-tick wall times on two
    fully-occupied engines (reference vs fused) — the end-to-end arm.
    -> dict(ref_ms, fused_ms, speedup)."""
    import jax

    ref, plen = _filled_engine(params, cfg, args, "reference")
    fus, _ = _filled_engine(params, cfg, args, "fused")
    # every probe tick advances pos by one; fit warm + trials windows
    ticks = max(1, min(
        args.ticks, (cfg.max_len - plen - 2) // (2 * args.trials)
    ))
    for e in (ref, fus):
        e.decode()
        jax.block_until_ready(e.state["tokens"])
    best = {"ref": float("inf"), "fused": float("inf")}
    for _ in range(args.trials):
        for name, e in (("ref", ref), ("fused", fus)):
            t0 = time.perf_counter()
            for _ in range(ticks):
                e.decode()
            jax.block_until_ready(e.state["tokens"])
            best[name] = min(best[name], time.perf_counter() - t0)
    return {
        "ref_tick_ms": round(best["ref"] / ticks * 1e3, 3),
        "fused_tick_ms": round(best["fused"] / ticks * 1e3, 3),
        "speedup": round(best["ref"] / best["fused"], 3)
        if best["fused"] > 0 else None,
        "ticks": ticks,
    }


def _streams(params, cfg, args, impl):
    """The full serving workload (interleaved ragged admits/retires)
    under ``impl`` — the token-identity oracle run."""
    import numpy as np

    from ..serve import Engine, Request, Scheduler

    engine = Engine(params, cfg, _serving(args, impl))
    sched = Scheduler(engine)
    rs = np.random.RandomState(args.seed + 1)
    for i in range(args.requests):
        plen = int(rs.randint(3, max(4, args.prompt_len + 1)))
        pr = rs.randint(0, args.vocab, size=(plen,)).astype(np.int32)
        sched.submit(Request(
            rid=i, prompt=pr,
            max_new_tokens=int(rs.randint(4, args.max_new + 1)),
        ))
    sched.serve()
    return {r.rid: r.tokens for r in sched.finished}


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    import jax

    from ..models.transformer import TransformerConfig, init_lm

    cfg = TransformerConfig(
        vocab=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff, max_len=args.max_len,
    )
    params = init_lm(jax.random.PRNGKey(args.seed), cfg)

    out = {"concurrency": args.concurrency, "block_len": args.block_len}
    out.update(measure_attend_bytes(params, cfg, args))
    out.update(measure_decode_ticks(params, cfg, args))
    ref_streams = _streams(params, cfg, args, "reference")
    fused_streams = _streams(params, cfg, args, "fused")
    out["token_mismatches"] = sum(
        1 for rid, toks in ref_streams.items()
        if fused_streams.get(rid) != toks
    )
    out["threshold"] = args.threshold
    out["bytes_threshold"] = args.bytes_threshold
    out["pass_mode"] = (
        "end_to_end"
        if (out["speedup"] or 0) >= args.threshold
        else "bytes"
        if (out["bytes_ratio"] or 0) >= args.bytes_threshold
        else None
    )
    out["pass"] = (
        out["token_mismatches"] == 0 and out["pass_mode"] is not None
    )
    print(json.dumps(out))
    if args.no_gate:
        return 0
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
