"""Measure the input stall: streaming step time with prefetch off vs on.

The zero-stall input pipeline's claim (data/device_prefetch.py) is that a
dataset too large to pin in HBM no longer pays host batch assembly +
``device_put`` on the step path: per-step runs get a double-buffered
device feeder (batch k+1 transfers while step k computes), and chunked
runs get staged scan blocks (one stacked transfer per window, double-
buffered at chunk granularity, one dispatch per window instead of per
step). This tool measures it — and reproduces the OLD synchronous stall
as the baseline — by timing the same small MLP job on a NON-device-cached
(streaming) config three ways:

  sync      prefetch off: assemble + transfer on the step path, one
            dispatch per step (the reference behavior)
  prefetch  the per-step device feeder (feeder_mode "prefetch")
  stream    staged scan chunks (feeder_mode "stream")

and printing one JSON line::

  {"sync_step_ms": .., "prefetch_step_ms": .., "stream_step_ms": ..,
   "prefetch_ratio": .., "stream_ratio": .., "threshold": .., "pass": ..}

Exit status 0 iff EITHER mode's ratio vs sync is <= ``threshold``
(default 1.0: prefetch-on must not be slower than prefetch-off). On an
accelerator host both should win — the feeder's host work and the
transfer overlap device compute. On a CPU-only host the feeder's CPU
time is stolen from the very cores doing the "device" compute (no
pipeline can hide CPU work from itself), so the per-step feeder lands
near sync — but the STREAM mode's dispatch amortization (one compiled
scan per window) is host-independent and carries the gate.
``pass_mode`` in the JSON says which mode carried.

Usage::

  python -m singa_tpu.tools.input_stall [--steps N] [--warmup N]
      [--batch N] [--hidden N] [--records N] [--trials N] [--threshold R]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


_CONF = """
name: "input-stall-probe"
train_steps: 1000000
checkpoint_frequency: 0
updater {{
  base_learning_rate: 0.05
  learning_rate_change_method: kFixed
  momentum: 0.9
  type: kSGD
}}
neuralnet {{
  layer {{
    name: "data"
    type: "kShardData"
    data_param {{ path: "{shard}" batchsize: {batch} }}
  }}
  layer {{
    name: "mnist"
    type: "kMnistImage"
    srclayers: "data"
    mnist_param {{ norm_a: 127.5 norm_b: 1 }}
  }}
  layer {{ name: "label" type: "kLabel" srclayers: "data" }}
  layer {{
    name: "fc1"
    type: "kInnerProduct"
    srclayers: "mnist"
    inner_product_param {{ num_output: {hidden} }}
    param {{ name: "weight" init_method: kUniform low: -0.05 high: 0.05 }}
    param {{ name: "bias" init_method: kConstant value: 0 }}
  }}
  layer {{ name: "tanh1" type: "kTanh" srclayers: "fc1" }}
  layer {{
    name: "fc2"
    type: "kInnerProduct"
    srclayers: "tanh1"
    inner_product_param {{ num_output: {head} }}
    param {{ name: "weight" init_method: kUniform low: -0.05 high: 0.05 }}
    param {{ name: "bias" init_method: kConstant value: 0 }}
  }}
  layer {{
    name: "loss"
    type: "kSoftmaxLoss"
    softmaxloss_param {{ topk: 1 }}
    srclayers: "fc2"
    srclayers: "label"
  }}
}}
"""


def _make_runner(shard: str, batch: int, hidden: int, warmup: int,
                 mode: str, chunk: int):
    """-> window(steps) -> seconds, for one probe mode.

    ``mode``: "sync" / "prefetch" / "stream". All three run NON-cached
    (``device_cache=False`` — the streaming regime this tool is about).
    The runner is warmed (compile + first staged block) before
    returning. Window timing is whole-window wall clock with one final
    value materialization (ckpt_stall's methodology): a per-step device
    sync would serialize the stream against the feeder's transfers and
    measure the serialization, not the stall."""
    import jax.numpy as jnp

    from ..config import parse_model_config
    from ..trainer import Trainer

    cfg = parse_model_config(_CONF.format(shard=shard, batch=batch,
                                          hidden=hidden, head=10))
    trainer = Trainer(
        cfg, seed=0, log=lambda s: None,
        prefetch=mode != "sync",
        device_cache=False,
        stream_chunks=mode == "stream",
    )
    assert trainer.feeder_mode == mode, (trainer.feeder_mode, mode)

    def sync() -> float:
        return float(jnp.sum(jnp.abs(next(iter(trainer.params.values())))))

    if mode == "stream":
        # chunk windows on the run() loop's schedule. NEVER clamp a
        # window: the stager staged exactly _chunk_len(s) steps, and a
        # shorter take is a schedule mismatch — run whole windows until
        # at least `steps` steps have elapsed and normalize by the
        # actual count (with cadences off, _chunk_len is the chunk cap)
        def run(step0: int, steps: int) -> int:
            s, end = step0, step0 + steps
            while s < end:
                n = trainer._chunk_len(s)
                trainer.train_chunk(s, n)
                s += n
            return s
    else:
        def run(step0: int, steps: int) -> int:
            for s in range(step0, step0 + steps):
                trainer.train_one_batch(s)
            return step0 + steps

    state = {"step": 0}
    state["step"] = run(0, max(warmup, chunk))  # compile + fill buffers
    sync()

    def window(steps: int) -> tuple[float, int]:
        step0 = state["step"]
        t0 = time.perf_counter()
        state["step"] = run(step0, steps)
        sync()
        return time.perf_counter() - t0, state["step"] - step0

    return window


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="input_stall", description=__doc__)
    ap.add_argument("--steps", type=int, default=96, help="timed steps")
    ap.add_argument("--warmup", type=int, default=8, help="untimed steps")
    ap.add_argument(
        "--trials", type=int, default=3,
        help="windows per mode; the best (least-contended) one counts",
    )
    # the probe regime: a ~10 ms step whose batch assembly (a ~3 MB
    # fancy-index gather + transfer per 1024-record batch) and per-step
    # dispatch are both real shares of the step path — the regime where
    # both feeder wins are measurable. A compute-saturated probe
    # (`--batch 8192`) measures ~nothing on a CPU host: the feeder's
    # host work is stolen from the "device" cores either way (measured
    # stream 0.99x there vs 0.69x here).
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--records", type=int, default=4096,
                    help="synthetic dataset size (streamed, never cached)")
    ap.add_argument(
        "--threshold", type=float, default=1.0,
        help="max allowed prefetch-on/prefetch-off step-time ratio "
        "(either feeder mode may carry it)",
    )
    args = ap.parse_args(argv)

    from ..data.loader import synthetic_arrays, write_records

    # a modest chunk cap keeps the staged blocks (2 in flight) small
    chunk = int(os.environ.get("SINGA_TPU_CHUNK", "16"))
    os.environ["SINGA_TPU_CHUNK"] = str(chunk)
    root = tempfile.mkdtemp(prefix="singa_tpu_input_stall_")
    shard = os.path.join(root, "shard")
    write_records(shard, *synthetic_arrays(args.records, seed=0))
    # INTERLEAVED best-of-trials (ckpt_stall's methodology): one window
    # per mode per round, minimum per mode — ambient host-load bursts
    # land on all modes instead of skewing one ratio
    runners = {
        mode: _make_runner(shard, args.batch, args.hidden, args.warmup,
                           mode, chunk)
        for mode in ("sync", "prefetch", "stream")
    }
    best = {mode: float("inf") for mode in runners}
    for _ in range(args.trials):
        for mode, window in runners.items():
            elapsed, nsteps = window(args.steps)
            best[mode] = min(best[mode], elapsed / nsteps)
    sync_ms = best["sync"] * 1e3
    prefetch_ms = best["prefetch"] * 1e3
    stream_ms = best["stream"] * 1e3
    prefetch_ok = prefetch_ms <= sync_ms * args.threshold
    stream_ok = stream_ms <= sync_ms * args.threshold
    out = {
        "sync_step_ms": round(sync_ms, 3),
        "prefetch_step_ms": round(prefetch_ms, 3),
        "stream_step_ms": round(stream_ms, 3),
        "prefetch_ratio": round(prefetch_ms / sync_ms, 3),
        "stream_ratio": round(stream_ms / sync_ms, 3),
        "threshold": args.threshold,
        "pass_mode": (
            "stream" if stream_ok else "prefetch" if prefetch_ok else None
        ),
        "pass": stream_ok or prefetch_ok,
    }
    print(json.dumps(out))
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
