"""Merge per-rank flight-recorder logs into one Chrome trace + summary.

The flight recorder (singa_tpu/obs/) leaves one JSONL event log per
rank in ``<workspace>/events/``. This tool is the post-mortem view of a
multi-host incident:

  merge (default)   fold every ``rank_k.jsonl`` into ONE Perfetto-
      loadable ``trace.json``: span records become 'X' duration events
      (pid = rank, tid = track: phases / feeder / stager / ckpt_writer),
      lifecycle events become instant events on each rank's 'events'
      thread. Ranks share no monotonic epoch, so the merge aligns on
      wall clock (each record carries both).

  --summarize       one JSON report instead: step-time p50/p99 (from
      train spans, normalized per step), input/ckpt/comm stall shares,
      guard/fault/restart counts, checkpoint commit outcomes, and
      per-rank skew (max wall-clock spread of the same display step /
      drain barrier across ranks). The ``comm`` share comes from the
      grad_comm calibration probe (a one-shot chained-reduce span the
      trainer records at run start when quantized/overlapped gradient
      collectives are active): per-reduction ms over the train span's
      per-step p50 — the modeled fraction of the step the gradient-
      collective machinery accounts for, not an on-step-path
      measurement (the collective runs inside the jitted step).

Usage::

  python -m singa_tpu.tools.trace <workspace-or-events-dir> [-o trace.json]
  python -m singa_tpu.tools.trace <workspace-or-events-dir> --summarize
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def _events_dir(path: str) -> str:
    """Accept the workspace, its events subdir, or any dir holding
    rank_*.jsonl files."""
    for cand in (os.path.join(path, "events"), path):
        if glob.glob(os.path.join(cand, "rank_*.jsonl")):
            return cand
    raise FileNotFoundError(
        f"no rank_*.jsonl event logs under {path!r} (or {path!r}/events)"
    )


def load_events(path: str) -> tuple[list[dict], int]:
    """-> (records sorted by wall time, unparseable-line count). A torn
    tail line (the process died mid-append) is skipped, not fatal —
    that is exactly the situation a post-mortem runs in."""
    records: list[dict] = []
    skipped = 0
    for fn in sorted(glob.glob(os.path.join(_events_dir(path), "rank_*.jsonl"))):
        with open(fn, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    skipped += 1
                    continue
                if isinstance(rec, dict) and "ts" in rec:
                    records.append(rec)
                else:
                    skipped += 1
    records.sort(key=lambda r: r["ts"])
    return records, skipped


# ---------------------------------------------------------------------------
# merge -> Chrome trace
# ---------------------------------------------------------------------------

#: stable tid assignment per track so the Perfetto lanes sort usefully
_TRACK_TIDS = {
    "phases": 1,
    "feeder": 2,
    "stager": 3,
    "ckpt_writer": 4,
    "serving": 5,
    "requests": 6,
    "events": 9,
}


def to_chrome_trace(records: list[dict]) -> dict:
    """-> the Chrome-trace JSON object ({"traceEvents": [...]})."""
    if not records:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(r["ts"] for r in records)
    events: list[dict] = []
    seen_threads: set[tuple[int, int]] = set()
    ranks: set[int] = set()

    def tid_for(track: str) -> int:
        return _TRACK_TIDS.get(track, 8)

    for r in records:
        rank = int(r.get("rank", 0))
        ranks.add(rank)
        ts_us = (r["ts"] - t0) * 1e6
        if r.get("kind") == "span":
            track = r.get("track", "phases")
            tid = tid_for(track)
            args = {"step": r.get("step")}
            if "steps" in r:
                args["steps"] = r["steps"]
            events.append({
                "name": r.get("name", "span"),
                "cat": track,
                "ph": "X",
                "ts": ts_us,
                "dur": max(0.0, float(r.get("dur", 0.0))) * 1e6,
                "pid": rank,
                "tid": tid,
                "args": args,
            })
        else:
            track, tid = "events", _TRACK_TIDS["events"]
            args = {"step": r.get("step")}
            args.update(r.get("data", {}))
            events.append({
                "name": r.get("kind", "event"),
                "cat": "lifecycle",
                "ph": "i",
                "s": "t",  # thread-scoped instant marker
                "ts": ts_us,
                "pid": rank,
                "tid": tid,
                "args": args,
            })
        seen_threads.add((rank, tid))

    meta: list[dict] = []
    for rank in sorted(ranks):
        meta.append({
            "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
            "args": {"name": f"rank {rank}"},
        })
    names = {tid: track for track, tid in _TRACK_TIDS.items()}
    for rank, tid in sorted(seen_threads):
        meta.append({
            "name": "thread_name", "ph": "M", "pid": rank, "tid": tid,
            "args": {"name": names.get(tid, "other")},
        })
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {"wall_epoch_s": t0},
    }


# ---------------------------------------------------------------------------
# summarize
# ---------------------------------------------------------------------------


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def summarize(records: list[dict]) -> dict:
    """The incident report: rates, stall shares, lifecycle counts,
    per-rank skew."""
    spans = [r for r in records if r.get("kind") == "span"]
    life = [r for r in records if r.get("kind") != "span"]

    # step-time percentiles: each train span covers `steps` steps; its
    # per-step time repeats with that weight so chunked windows don't
    # undercount relative to per-step dispatch
    per_step_ms: list[float] = []
    comm_ms: list[float] = []
    # serving tier: per-request latency spans + decode-tick spans
    # (serve/scheduler.py records both), summarized like step times
    request_ms: list[float] = []
    tick_s = 0.0
    tick_tokens = 0
    ticks = 0
    phase_totals: dict[str, float] = {}
    for s in spans:
        if s.get("track") == "requests":
            request_ms.append(float(s.get("dur", 0.0)) * 1e3)
        elif s.get("track") == "serving":
            tick_s += float(s.get("dur", 0.0))
            tick_tokens += int(s.get("steps", 0))
            ticks += 1
        if s.get("track") != "phases":
            phase_totals[s.get("track", "?")] = (
                phase_totals.get(s.get("track", "?"), 0.0) + s.get("dur", 0.0)
            )
            continue
        name = s.get("name", "?")
        dur = float(s.get("dur", 0.0))
        phase_totals[name] = phase_totals.get(name, 0.0) + dur
        if name == "train":
            n = max(1, int(s.get("steps", 1)))
            per_step_ms.extend([dur / n * 1e3] * min(n, 4096))
        elif name == "comm":
            # calibration probe: dur covers `steps` chained reductions
            n = max(1, int(s.get("steps", 1)))
            comm_ms.append(dur / n * 1e3)
    per_step_ms.sort()
    comm_ms.sort()
    request_ms.sort()

    train_t = phase_totals.get("train", 0.0)
    data_t = phase_totals.get("data", 0.0)
    ckpt_t = phase_totals.get("ckpt", 0.0)
    step_path = train_t + data_t + ckpt_t

    counts: dict[str, int] = {}
    for r in life:
        counts[r.get("kind", "?")] = counts.get(r.get("kind", "?"), 0) + 1

    by_rank: dict[int, int] = {}
    for r in records:
        by_rank[int(r.get("rank", 0))] = (
            by_rank.get(int(r.get("rank", 0)), 0) + 1
        )

    # per-rank skew: the same display step / drain barrier seen on
    # multiple ranks should land at (nearly) the same wall instant —
    # the max spread is the cross-rank lag a post-mortem cares about
    skew = 0.0
    for kind in ("step", "drain_barrier"):
        marks: dict[int, dict[int, float]] = {}
        for r in life:
            if r.get("kind") != kind or r.get("step") is None:
                continue
            marks.setdefault(int(r["step"]), {})[int(r.get("rank", 0))] = (
                r["ts"]
            )
        for ts_by_rank in marks.values():
            if len(ts_by_rank) > 1:
                skew = max(
                    skew, max(ts_by_rank.values()) - min(ts_by_rank.values())
                )

    # speculative decode: per-tick spec_draft/spec_accept events carry
    # drafted/accepted token counts (serve/scheduler.py)
    spec_drafted = sum(
        int(r["data"].get("drafted", 0))
        for r in life
        if r.get("kind") == "spec_draft" and isinstance(r.get("data"), dict)
    )
    spec_accepted = sum(
        int(r["data"].get("accepted", 0))
        for r in life
        if r.get("kind") == "spec_accept" and isinstance(r.get("data"), dict)
    )

    # kernel selection: the run-start kernel_select event says which
    # attend implementation the serving engine ran (reference | fused)
    # — incident reports must say which path a run took
    attend_impl = next(
        (
            r["data"].get("impl")
            for r in reversed(life)
            if r.get("kind") == "kernel_select"
            and isinstance(r.get("data"), dict)
            and r["data"].get("site") == "serve.paged_attention"
        ),
        None,
    )

    # training tier twin: the trainer's train.grad_allreduce
    # kernel_select event says which wire implementation the data-axis
    # gradient collective ran (reference | quantized_ring) and the
    # modeled per-device bytes it moves per step — reported next to
    # comm_ms_per_step so a post-mortem sees both the machinery's time
    # cost and its wire cost (None = no grad_comm machinery / old log)
    grad_select = next(
        (
            r["data"]
            for r in reversed(life)
            if r.get("kind") == "kernel_select"
            and isinstance(r.get("data"), dict)
            and r["data"].get("site") == "train.grad_allreduce"
        ),
        None,
    )

    # prefix cache: per-admission prefix_hit events carry shared-block
    # and saved-prefill-chunk counts (serve/scheduler.py _admit_some)
    prefix_hit_events = [
        r["data"]
        for r in life
        if r.get("kind") == "prefix_hit" and isinstance(r.get("data"), dict)
    ]
    blocks_shared = sum(
        int(d.get("blocks_shared", 0)) for d in prefix_hit_events
    )
    prefill_chunks_saved = sum(
        int(d.get("chunks_saved", 0)) for d in prefix_hit_events
    )

    # fleet prefix cache: cache_fetch -> cache_ship round trips
    # (counted on the receiver, dir="in", where the bytes landed —
    # dir="out" double-counts the same frame on the sender), partial-
    # tail hits, decode-written block registrations
    ships_in = [
        r["data"]
        for r in life
        if r.get("kind") == "cache_ship"
        and isinstance(r.get("data"), dict)
        and r["data"].get("dir") == "in"
    ]
    partial_hit_events = [
        r["data"]
        for r in life
        if r.get("kind") == "partial_hit" and isinstance(r.get("data"), dict)
    ]

    # fleet: per-host roles from the run-start fleet_role events, and
    # block-migration volume from migrate_in (counted on the importer,
    # where the blocks actually landed; migrate_out double-counts a
    # drain-to-peer re-migration)
    fleet_roles: dict[int, str] = {}
    for r in life:
        if r.get("kind") == "fleet_role" and isinstance(r.get("data"), dict):
            fleet_roles[int(r.get("rank", 0))] = r["data"].get("role")
    migrate_in_events = [
        r for r in life
        if r.get("kind") == "migrate_in" and isinstance(r.get("data"), dict)
    ]
    migrated_blocks = sum(
        int(r["data"].get("blocks", 0)) for r in migrate_in_events
    )
    hosts: dict[str, dict] = {}
    if fleet_roles:
        per_rank: dict[int, dict[str, int]] = {}
        per_rank_cache: dict[int, dict[str, int]] = {}
        for r in life:
            rank = int(r.get("rank", 0))
            if rank not in fleet_roles:
                continue
            per_rank.setdefault(rank, {})
            k = r.get("kind", "?")
            per_rank[rank][k] = per_rank[rank].get(k, 0) + 1
            d = r.get("data") if isinstance(r.get("data"), dict) else None
            if d is None:
                continue
            acc = per_rank_cache.setdefault(rank, {})
            if k == "prefix_hit":
                acc["chunks_saved"] = (
                    acc.get("chunks_saved", 0)
                    + int(d.get("chunks_saved", 0))
                )
            elif k == "cache_ship":
                way = "in" if d.get("dir") == "in" else "out"
                acc[f"ships_{way}"] = acc.get(f"ships_{way}", 0) + 1
                acc[f"ship_bytes_{way}"] = (
                    acc.get(f"ship_bytes_{way}", 0) + int(d.get("bytes", 0))
                )
                acc[f"ship_blocks_{way}"] = (
                    acc.get(f"ship_blocks_{way}", 0)
                    + int(d.get("blocks", 0))
                )
        for rank in sorted(fleet_roles):
            c = per_rank.get(rank, {})
            cc = per_rank_cache.get(rank, {})
            admitted = c.get("request_admit", 0)
            hosts[str(rank)] = {
                "role": fleet_roles[rank],
                "admitted": admitted,
                "prefill_chunks": c.get("prefill", 0),
                "migrate_in": c.get("migrate_in", 0),
                "migrate_out": c.get("migrate_out", 0),
                "retired": c.get("retire", 0),
                "evicted": c.get("evict", 0),
                "drains": c.get("drain", 0),
                # fleet prefix cache, this host's view: hit rate over
                # its admissions, chunks its hits skipped, fetch/ship
                # traffic in both directions
                "prefix_hits": c.get("prefix_hit", 0),
                "prefix_hit_rate": (
                    round(c.get("prefix_hit", 0) / admitted, 4)
                    if admitted else None
                ),
                "partial_hits": c.get("partial_hit", 0),
                "chunks_saved": cc.get("chunks_saved", 0),
                "cache_fetches": c.get("cache_fetch", 0),
                "cache_fetch_timeouts": c.get("cache_fetch_timeout", 0),
                "cache_ships_in": cc.get("ships_in", 0),
                "cache_ships_out": cc.get("ships_out", 0),
                "ship_bytes_in": cc.get("ship_bytes_in", 0),
                "ship_bytes_out": cc.get("ship_bytes_out", 0),
            }

    # wire transport (comm/wire.py): connect/retry/timeout/redeliver
    # lifecycle counts plus per-peer send-latency percentiles from
    # wire_send events — enough to reconstruct connect -> retry ->
    # redeliver -> resume from a merged multi-host trace
    wire_counts = {
        k: counts.get(f"wire_{k}", 0)
        for k in (
            "connect", "send", "retry", "timeout", "redeliver",
            "crc_reject", "partition_heal",
        )
    }
    wire_peer_ms: dict[str, list[float]] = {}
    for r in life:
        if r.get("kind") != "wire_send" or not isinstance(
            r.get("data"), dict
        ):
            continue
        peer = str(r["data"].get("peer", "?"))
        wire_peer_ms.setdefault(peer, []).append(
            float(r["data"].get("ms", 0.0))
        )
    wire_peers = {}
    for peer in sorted(wire_peer_ms):
        ms = sorted(wire_peer_ms[peer])
        wire_peers[peer] = {
            "sends": len(ms),
            "send_ms": {
                "p50": round(_percentile(ms, 0.50), 3),
                "p99": round(_percentile(ms, 0.99), 3),
            },
        }

    # live weight rollout (serve/rollout.py): per-host flip history
    # keyed by rank from the cross-rank merge, weight-ship volume,
    # canary parity verdict, aborts/rollbacks, final verdict
    flip_events = [
        r for r in life
        if r.get("kind") == "rollout_flip"
        and isinstance(r.get("data"), dict)
    ]
    weight_ships = [
        r["data"] for r in life
        if r.get("kind") == "weight_ship"
        and isinstance(r.get("data"), dict)
    ]
    rollout_aborts = [
        r["data"] for r in life
        if r.get("kind") == "rollout_abort"
        and isinstance(r.get("data"), dict)
    ]
    rollout_canary = [
        r["data"] for r in life
        if r.get("kind") == "rollout_canary"
        and isinstance(r.get("data"), dict)
    ]
    rollout_done = [
        r["data"] for r in life
        if r.get("kind") == "rollout_done"
        and isinstance(r.get("data"), dict)
    ]
    rollout_hosts: dict[str, dict] = {}
    for r in flip_events:
        d = r["data"]
        e = rollout_hosts.setdefault(str(int(r.get("rank", 0))), {
            "version": 0, "flip_tick": None, "flips": 0,
            "rollbacks": 0,
        })
        e["flips"] += 1
        e["version"] = int(d.get("version", 0))
        e["flip_tick"] = d.get("tick")
        if d.get("rollback"):
            e["rollbacks"] += 1

    faults = [
        r["data"].get("fault")
        for r in life
        if r.get("kind") == "fault" and isinstance(r.get("data"), dict)
    ]
    guard_rollbacks = counts.get("guard_rollback", 0)
    last_steps = [
        r for r in life if r.get("kind") == "step"
    ]
    steps_per_s = [
        r["data"]["steps_per_s"]
        for r in last_steps
        if isinstance(r.get("data"), dict) and "steps_per_s" in r["data"]
    ]

    return {
        "records": len(records),
        "ranks": {str(k): v for k, v in sorted(by_rank.items())},
        "step_time_ms": {
            "p50": round(_percentile(per_step_ms, 0.50), 3),
            "p99": round(_percentile(per_step_ms, 0.99), 3),
            "n": len(per_step_ms),
        },
        "steps_per_s": {
            "mean": round(sum(steps_per_s) / len(steps_per_s), 3)
            if steps_per_s
            else None,
            "windows": len(steps_per_s),
        },
        "stall_shares": {
            "input": round(data_t / step_path, 4) if step_path > 0 else 0.0,
            "ckpt": round(ckpt_t / step_path, 4) if step_path > 0 else 0.0,
            # the gradient-collective machinery's modeled share of the
            # step (probe p50 / train per-step p50; see docstring)
            "comm": round(
                _percentile(comm_ms, 0.50)
                / _percentile(per_step_ms, 0.50),
                4,
            )
            if comm_ms and per_step_ms and _percentile(per_step_ms, 0.50)
            else 0.0,
        },
        "comm_ms_per_step": round(_percentile(comm_ms, 0.50), 4)
        if comm_ms
        else None,
        # which wire implementation reduced gradients (the
        # train.grad_allreduce kernel_select run-start event) and its
        # modeled per-device data-axis bytes per step
        "grad_wire_impl": grad_select.get("impl") if grad_select else None,
        "wire_bytes_per_step": (
            grad_select.get("wire_bytes_per_step") if grad_select else None
        ),
        "counts": {
            "faults": len(faults),
            "guard_rollbacks": guard_rollbacks,
            "restarts": counts.get("restart", 0),
            "crashes": counts.get("crash", 0),
            "drains": counts.get("drain", 0),
            "peer_deaths": counts.get("peer_death", 0),
            "watchdog_stalls": counts.get("watchdog_stall", 0),
            "checkpoints_written": counts.get("ckpt_written", 0),
            "latest_promotions": counts.get("ckpt_latest", 0),
            "torn_commits": sum(
                1
                for r in life
                if r.get("kind") == "ckpt_commit"
                and isinstance(r.get("data"), dict)
                and not r["data"].get("ok", True)
            ),
        },
        "fired_faults": faults,
        "max_rank_skew_s": round(skew, 4),
        # serving tier (None unless serving spans/events are present):
        # request-latency percentiles from per-request spans, decode
        # throughput from tick spans, lifecycle counts from events
        "serving": {
            # which attend implementation served this run (the
            # kernel_select run-start event; None = pre-kernels log)
            "attend_impl": attend_impl,
            "request_latency_ms": {
                "p50": round(_percentile(request_ms, 0.50), 2),
                "p99": round(_percentile(request_ms, 0.99), 2),
                "n": len(request_ms),
            },
            "decode_ticks": ticks,
            "tokens": tick_tokens + len(request_ms),
            "tokens_per_s": round(tick_tokens / tick_s, 1)
            if tick_s > 0
            else 0.0,
            # speculative decode's amortization factor: emitted tokens
            # per verify/decode tick (1.0 * live slots without
            # speculation; higher = accepted drafts riding one weight
            # stream) and the drafter's acceptance rate (None = no
            # speculation events in this log)
            "tokens_per_tick": round(tick_tokens / ticks, 2)
            if ticks
            else None,
            "acceptance_rate": round(spec_accepted / spec_drafted, 4)
            if spec_drafted
            else None,
            "spec_drafted": spec_drafted,
            "spec_accepted": spec_accepted,
            # prefix cache: hit rate over admissions, shared blocks,
            # and prefill chunks the hits skipped (None = no prefix
            # lifecycle events in this log — cache off or no hits)
            "prefix_hit_rate": round(
                len(prefix_hit_events)
                / max(1, counts.get("request_admit", 0)),
                4,
            )
            if prefix_hit_events
            else None,
            "blocks_shared": blocks_shared,
            "prefill_chunks_saved": prefill_chunks_saved,
            "cow_copies": counts.get("cow_copy", 0),
            "lru_evictions": counts.get("lru_evict", 0),
            "lru_reclaims": sum(
                int(r["data"].get("blocks", 1))
                for r in life
                if r.get("kind") == "lru_reclaim"
                and isinstance(r.get("data"), dict)
            ),
            "admitted": counts.get("request_admit", 0),
            "retired": counts.get("retire", 0),
            "evicted": counts.get("evict", 0),
            "backpressure": counts.get("backpressure", 0),
            # fleet (zero / empty without fleet events in the log):
            # cross-host sequence migrations, the block volume they
            # moved, front-door placements, and per-role host rows
            # keyed by rank from the cross-rank merge
            "migrations": len(migrate_in_events),
            "migrated_blocks": migrated_blocks,
            "routed": counts.get("route", 0),
            # fleet prefix cache (None = no fetch/ship/partial events
            # in this log): cross-host warm-KV traffic counted on the
            # receiving side, partial-tail sharing, decode-written
            # block registrations
            "fleet_cache": {
                "fetches": counts.get("cache_fetch", 0),
                "fetch_timeouts": counts.get("cache_fetch_timeout", 0),
                "ships": len(ships_in),
                "blocks_shipped": sum(
                    int(d.get("blocks", 0)) for d in ships_in
                ),
                "ship_bytes": sum(
                    int(d.get("bytes", 0)) for d in ships_in
                ),
                "partial_hits": len(partial_hit_events),
                "tail_tokens_shared": sum(
                    int(d.get("tail_tokens", 0))
                    for d in partial_hit_events
                ),
                "decode_registers": counts.get("decode_register", 0),
            }
            if (
                counts.get("cache_fetch") or ships_in
                or partial_hit_events or counts.get("decode_register")
            )
            else None,
            "hosts": hosts or None,
        }
        if (
            request_ms or ticks or counts.get("request_admit")
            or fleet_roles or counts.get("route")
        )
        else None,
        # live weight rollout (None unless rollout/weight_ship events
        # are present): ship volume counted on the receiver, torn-frame
        # rejections, per-rank flip history, canary parity verdict,
        # aborts with their documented reasons, and the controller's
        # final verdict (promoted / rollback / quarantined / paused)
        "rollout": {
            "ships_in": sum(
                1 for s in weight_ships
                if s.get("dir") == "in" and s.get("ok")
            ),
            "ship_bytes_in": sum(
                int(s.get("bytes", 0)) for s in weight_ships
                if s.get("dir") == "in" and s.get("ok")
            ),
            "torn_ships": sum(
                1 for s in weight_ships
                if s.get("dir") == "in" and not s.get("ok", True)
            ),
            "stages": counts.get("rollout_stage", 0),
            "flips": sum(
                1 for r in flip_events
                if not r["data"].get("rollback")
            ),
            "rollbacks": sum(
                1 for r in flip_events if r["data"].get("rollback")
            ),
            "canary": {
                "parity": bool(rollout_canary[-1].get("parity")),
                "probes": int(rollout_canary[-1].get("probes", 0)),
            }
            if rollout_canary
            else None,
            "aborts": [
                {
                    "reason": a.get("reason"),
                    "version": a.get("version"),
                }
                for a in rollout_aborts
            ],
            "verdict": rollout_done[-1].get("verdict")
            if rollout_done
            else None,
            "version": rollout_done[-1].get("version")
            if rollout_done
            else None,
            "hosts": rollout_hosts or None,
        }
        if (
            flip_events or weight_ships or rollout_done
            or rollout_aborts or counts.get("rollout_stage")
        )
        else None,
        # wire transport (None unless wire_* events are present — the
        # mailbox/in-process wirings emit none): retry/redelivery
        # verdict counts + per-peer send-latency percentiles
        "wire": {
            **wire_counts,
            "peer_deaths": counts.get("peer_death", 0),
            "peers": wire_peers or None,
        }
        if any(wire_counts.values())
        else None,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="trace", description=__doc__)
    ap.add_argument(
        "path", help="workspace (or its events/ dir) holding rank_*.jsonl"
    )
    ap.add_argument(
        "-o", "--output", default=None,
        help="merged Chrome-trace output (default: <path>/trace.json)",
    )
    ap.add_argument(
        "--summarize", action="store_true",
        help="print the incident summary JSON instead of merging",
    )
    args = ap.parse_args(argv)

    try:
        records, skipped = load_events(args.path)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    if skipped:
        print(
            f"trace: skipped {skipped} unparseable line(s) "
            "(torn tail from a dead process?)",
            file=sys.stderr,
        )
    if args.summarize:
        print(json.dumps(summarize(records), indent=2))
        return 0
    trace = to_chrome_trace(records)
    out = args.output or os.path.join(args.path, "trace.json")
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    os.replace(tmp, out)
    print(
        json.dumps({
            "trace": out,
            "events": len(trace["traceEvents"]),
            "records": len(records),
            "skipped": skipped,
        })
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
