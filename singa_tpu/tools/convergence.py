"""Full-length convergence runs of the example job configs.

The reference's workloads define the parity bar: MNIST MLP 60k steps
(reference examples/mnist/mlp.conf:2, ~98% top-1), LeNet 10k steps
(conv.conf:2, ~99%), CIFAR AlexNet 70k steps (~80%). Real MNIST/CIFAR
cannot be downloaded in this zero-egress image (documented in
BASELINE.md), so each run uses the best available stand-in at FULL
reference length and width:

  mlp / conv  sklearn digits upscaled to 28x28 (1438 train / 359 test)
  alexnet     structured synthetic RGB (kron-upsampled class templates,
              5000 train / 1000 test with disjoint noise)

Usage:  python -m singa_tpu.tools.convergence [mlp mlp_elastic conv alexnet]
            [--grad_comm exact|q8|q8wire|q8hier|bf16] [--steps N]
            [--hidden_scale R] [--batch N]

Prints one JSON line per workload: {name, steps, wall_sec,
steps_per_sec, final_test_accuracy, final_test_loss} — the convergence
table in BASELINE.md records these.

``--grad_comm`` runs the workload under a gradient-collective mode
(parallel/collectives.py): ``q8`` = quantized int8 with error feedback,
``q8wire`` = q8 with the reduction itself on the int8-on-the-wire
quantized ring (``kernels { grad_allreduce: quantized_ring }``,
ops/quantized_collective.py — run it under a >1-wide data axis, e.g.
``XLA_FLAGS=--xla_force_host_platform_device_count=4``, or the ring is
a trivial 0-hop loop), ``bf16`` = quantized bf16, ``exact`` = an
explicit exact block (must be bitwise-identical to no flag at all).
This is the END-TO-END numerics validation for the quantized
collective — CI's grad-comm parity gate runs the mlp workload with and
without ``--grad_comm q8`` and asserts the final test loss/accuracy
agree within tolerance, proving the error feedback preserves
convergence over a whole run, not just one step; the ``q8wire`` arm
re-runs it through the ring and holds the SAME bar against ``q8``,
proving the per-hop re-quantization (whose wire rounding goes
un-fed-back — the documented one-shot-EF caveat) does not move
convergence. ``q8hier`` is the two-level hierarchical ring
(``kernels { grad_allreduce: q8_hier }`` + ``ring { intra_degree: 2 }``
— the data axis must be even; f32 intra-slice hops, int8 inter-slice
hops) held to the same bar; the true 2x2 factored-mesh parity runs in
tests/test_quantized_collective.py's hier suite.
``--steps`` / ``--hidden_scale`` / ``--batch`` shrink the run for
CPU-hosted CI (hidden_scale scales kInnerProduct widths, keeping the
10-class head, like __graft_entry__._flagship_cfg); full-length parity
numbers belong to accelerator runs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")


def _digits_shards(tmp: str) -> tuple[str, str]:
    from ..data.loader import digits_arrays, write_records

    train = os.path.join(tmp, "train_shard")
    test = os.path.join(tmp, "test_shard")
    write_records(train, *digits_arrays("train"))
    write_records(test, *digits_arrays("test"))
    return train, test


def _cifar_shards(tmp: str) -> tuple[str, str, str]:
    from ..data.loader import compute_mean, structured_rgb, write_records

    train = os.path.join(tmp, "train_shard")
    test = os.path.join(tmp, "test_shard")
    # class_amplitude 10 (r5): shared base + small per-class delta gives
    # the task a real Bayes error so the full-length accuracy can
    # actually fail — the legacy independent templates saturated the
    # 70k-step run at a ceiling-pinned 100% (VERDICT r4 weak #5). The
    # amplitude is calibrated by a measured chip scan
    # (bench/ablations/alexnet_amplitude_scan.py): A=6 collapses
    # training to chance (the conf's init/lr cannot extract a
    # 2%-contrast signal a linear probe resolves), A=10 lands 93.9%,
    # A=16 re-saturates at 99.4%.
    write_records(
        train, *structured_rgb(5000, seed=0, noise_seed=1, class_amplitude=10)
    )
    write_records(
        test, *structured_rgb(1000, seed=0, noise_seed=2, class_amplitude=10)
    )
    mean = os.path.join(tmp, "mean.npy")
    compute_mean(train, mean)
    return train, test, mean


def _patch_paths(cfg, train: str, test: str, mean: str | None = None):
    for layer in cfg.neuralnet.layer:
        if layer.data_param is not None and layer.data_param.path:
            is_test = "kTrain" in (layer.exclude or [])
            layer.data_param.path = test if is_test else train
        p = getattr(layer, "rgbimage_param", None)
        if mean is not None and p is not None and p.meanfile:
            p.meanfile = mean


def _shrink_cfg(cfg, steps: int, hidden_scale: float, batch: int):
    """CPU-CI-sized cut of a full-length workload: fewer steps, scaled
    kInnerProduct widths (the 10-class head kept), smaller batch."""
    if steps:
        cfg.train_steps = steps
    for layer in cfg.neuralnet.layer:
        p = getattr(layer, "inner_product_param", None)
        if hidden_scale != 1.0 and p is not None and p.num_output > 10:
            p.num_output = max(8, int(p.num_output * hidden_scale))
        if batch and layer.data_param is not None and layer.data_param.path:
            layer.data_param.batchsize = batch
    return cfg


def run_workload(name: str, log=print, *, grad_comm: str = "",
                 steps: int = 0, hidden_scale: float = 1.0,
                 batch: int = 0) -> dict:
    from ..config import load_cluster_config, load_model_config
    from ..parallel import apply_grad_comm_tag
    from ..trainer import Trainer, make_trainer

    tmp = tempfile.mkdtemp(prefix=f"singa_tpu_conv_{name}_")
    cluster = None
    if name in ("mlp", "mlp_elastic"):
        # same job conf both ways, like the reference: mlp.conf declares
        # param_type "Elastic" (reference mlp.conf:13); the cluster conf
        # picks the engine — async+nservers routes to the ReplicaTrainer
        # running the declared protocol, the default synchronous cluster
        # runs the north-star sync ParamSync engine
        cfg = load_model_config(
            os.path.join(REPO, "examples", "mnist", "mlp.conf")
        )
        if name == "mlp_elastic":
            cluster = load_cluster_config(
                os.path.join(
                    REPO, "examples", "mnist", "cluster_elastic.conf"
                )
            )
            cluster.workspace = tmp
        _patch_paths(cfg, *_digits_shards(tmp))
    elif name == "conv":
        cfg = load_model_config(
            os.path.join(REPO, "examples", "mnist", "conv.conf")
        )
        _patch_paths(cfg, *_digits_shards(tmp))
    elif name == "alexnet":
        cfg = load_model_config(
            os.path.join(REPO, "examples", "cifar10", "alexnet.conf")
        )
        train, test, mean = _cifar_shards(tmp)
        _patch_paths(cfg, train, test, mean)
    else:
        raise ValueError(f"unknown workload {name!r}")
    cfg.checkpoint_frequency = 0  # no workspace configured for these runs
    _shrink_cfg(cfg, steps, hidden_scale, batch)
    apply_grad_comm_tag(cfg, grad_comm)
    if name in ("conv", "alexnet") and not cfg.compute_dtype:
        # fp32 convs lower with Precision.HIGHEST (multi-pass bf16
        # emulation, matching the reference's fp32 cblas accumulate);
        # through this image's tunneled TPU that XLA compile measurably
        # exceeds 9 minutes for even the LeNet step (bf16 compiles in
        # 35 s) — see BASELINE.md r3 notes. Convergence runs therefore
        # use bf16 compute with fp32 master params; the accuracy bar is
        # unaffected (tests/test_chunk.py pins bf16 ≡ fp32 convergence
        # on these workloads' scale).
        cfg.compute_dtype = "bfloat16"

    if cluster is not None:
        trainer = make_trainer(cfg, cluster, seed=0, log=log, prefetch=False)
    else:
        trainer = Trainer(cfg, seed=0, log=log, prefetch=False)
    t0 = time.perf_counter()
    trainer.run()
    wall = time.perf_counter() - t0
    # final accuracy over the full test stream (enough steps to cover it)
    pipe = next(iter(trainer._pipelines[id(trainer.test_net)].values()))
    nsteps = max(1, int(np.ceil(pipe.n / pipe.batchsize)))
    final = trainer.evaluate(
        trainer.test_net, nsteps, "final-test", cfg.train_steps
    )
    (m,) = final.values()
    return {
        "name": name,
        "steps": cfg.train_steps,
        "wall_sec": round(wall, 1),
        "steps_per_sec": round(cfg.train_steps / wall, 1),
        "engine": type(trainer).__name__,
        "grad_comm": grad_comm or "off",
        "final_test_accuracy": round(float(m["precision"]), 6),
        "final_test_loss": round(float(m["loss"]), 6),
    }


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="convergence", description=__doc__)
    ap.add_argument("workloads", nargs="*",
                    default=["mlp", "mlp_elastic", "conv", "alexnet"])
    ap.add_argument("--grad_comm", default="",
                    choices=("", "exact", "q8", "q8wire", "q8hier",
                             "bf16"),
                    help="gradient-collective mode (q8 = quantized int8 "
                    "with error feedback; q8wire = q8 through the "
                    "int8-on-the-wire quantized ring, kernels { "
                    "grad_allreduce: quantized_ring })")
    ap.add_argument("--steps", type=int, default=0,
                    help="override train_steps (CI-sized runs)")
    ap.add_argument("--hidden_scale", type=float, default=1.0,
                    help="scale kInnerProduct widths (10-class head kept)")
    ap.add_argument("--batch", type=int, default=0,
                    help="override data-layer batch size")
    args = ap.parse_args(argv)
    quiet = lambda s: None  # noqa: E731
    for name in args.workloads:
        result = run_workload(
            name, log=quiet, grad_comm=args.grad_comm, steps=args.steps,
            hidden_scale=args.hidden_scale, batch=args.batch,
        )
        print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
