"""Measure the flight recorder's step-path overhead: on vs off.

The telemetry plane's contract (singa_tpu/obs/) is that it is ALWAYS ON
for free: per-step cost is an O(1) in-memory span append (span mode) —
no write syscalls, no device syncs — with file I/O only at display
cadence. This tool gates that claim the way ckpt_stall/input_stall gate
theirs: the same small MLP job timed with telemetry off and on
(span recording active, a step record + flush every ``--display``
steps), interleaved best-of-trials windows, one JSON line::

  {"off_step_ms": .., "on_step_ms": .., "ratio": ..,
   "events": .., "writes": .., "threshold": .., "pass": ..}

Exit 0 iff ``on <= threshold x off`` (default 1.02 — the acceptance
bar: telemetry may cost at most 2% of mean step time). ``writes`` in
the JSON is the recorder's file-open count — it must equal the number
of cadence flushes, never the number of steps.

Usage::

  python -m singa_tpu.tools.telemetry_overhead [--steps N] [--warmup N]
      [--trials N] [--display N] [--threshold R]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


_CONF = """
name: "telemetry-overhead-probe"
train_steps: 100000
updater {{
  base_learning_rate: 0.05
  learning_rate_change_method: kFixed
  momentum: 0.9
  type: kSGD
}}
neuralnet {{
  layer {{
    name: "data"
    type: "kShardData"
    data_param {{ path: "{shard}" batchsize: {batch} }}
  }}
  layer {{
    name: "mnist"
    type: "kMnistImage"
    srclayers: "data"
    mnist_param {{ norm_a: 127.5 norm_b: 1 }}
  }}
  layer {{ name: "label" type: "kLabel" srclayers: "data" }}
  layer {{
    name: "fc1"
    type: "kInnerProduct"
    srclayers: "mnist"
    inner_product_param {{ num_output: {hidden} }}
    param {{ name: "weight" init_method: kUniform low: -0.05 high: 0.05 }}
    param {{ name: "bias" init_method: kConstant value: 0 }}
  }}
  layer {{ name: "tanh1" type: "kTanh" srclayers: "fc1" }}
  layer {{
    name: "fc2"
    type: "kInnerProduct"
    srclayers: "tanh1"
    inner_product_param {{ num_output: 10 }}
    param {{ name: "weight" init_method: kUniform low: -0.05 high: 0.05 }}
    param {{ name: "bias" init_method: kConstant value: 0 }}
  }}
  layer {{
    name: "loss"
    type: "kSoftmaxLoss"
    softmaxloss_param {{ topk: 1 }}
    srclayers: "fc2"
    srclayers: "label"
  }}
}}
"""


def _make_runner(root: str, shard: str, batch: int, hidden: int,
                 warmup: int, display: int, telemetry: bool):
    """-> (window(steps) -> seconds, recorder-or-None). Per-step
    driving (bench methodology: whole-window wall clock, one final
    materialization) with the device-cached dataset, so windows measure
    step dispatch + the recorder's buffer appends, not batch assembly
    noise."""
    import jax.numpy as jnp

    from ..config import parse_model_config
    from ..trainer import Trainer

    cfg = parse_model_config(
        _CONF.format(shard=shard, batch=batch, hidden=hidden)
    )
    trainer = Trainer(
        cfg, None, seed=0, log=lambda s: None,
        prefetch=False, device_cache=True,
    )
    rec = None
    if telemetry:
        from ..obs.recorder import FlightRecorder

        rec = FlightRecorder(
            tempfile.mkdtemp(prefix="tel_events_", dir=root),
            rank=0, run_id="overhead-probe", log=lambda s: None,
        )
        trainer.attach_telemetry(rec)

    def sync() -> float:
        return float(jnp.sum(jnp.abs(next(iter(trainer.params.values())))))

    state = {"step": 0}
    for _ in range(warmup):
        trainer.train_one_batch(state["step"])
        state["step"] += 1
    sync()

    def window(steps: int) -> float:
        step = state["step"]
        t0 = time.perf_counter()
        for _ in range(steps):
            trainer.train_one_batch(step)
            step += 1
            if rec is not None and step % display == 0:
                # the display-cadence path telemetry actually adds: a
                # step record (host values only) + the buffered flush
                rec.event(
                    "step", step=step,
                    phase_ms={
                        p: trainer.timers.mean_ms(p)
                        for p in trainer.timers.phases()
                    },
                )
                rec.flush()
        sync()
        elapsed = time.perf_counter() - t0
        state["step"] = step
        return elapsed

    return window, rec


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="telemetry_overhead", description=__doc__
    )
    ap.add_argument("--steps", type=int, default=60, help="timed steps")
    ap.add_argument("--warmup", type=int, default=5, help="untimed steps")
    ap.add_argument(
        "--trials", type=int, default=4,
        help="windows per mode; the best (least-contended) one counts",
    )
    ap.add_argument("--display", type=int, default=10,
                    help="steps per display-cadence flush")
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument(
        "--threshold", type=float, default=1.02,
        help="max allowed on/off mean-step-time ratio",
    )
    args = ap.parse_args(argv)

    from ..data.loader import synthetic_arrays, write_records

    root = tempfile.mkdtemp(prefix="singa_tpu_tel_")
    shard = os.path.join(root, "shard")
    write_records(shard, *synthetic_arrays(1024, seed=0))
    # interleaved best-of-trials (the stall tools' methodology): one
    # window per mode per round, minimum per mode, so ambient host load
    # spreads across both modes instead of skewing the ratio
    runners = {
        mode: _make_runner(
            root, shard, args.batch, args.hidden, args.warmup,
            args.display, telemetry=mode,
        )
        for mode in (False, True)
    }
    best = {mode: float("inf") for mode in runners}
    for _ in range(args.trials):
        for mode, (window, _) in runners.items():
            best[mode] = min(best[mode], window(args.steps))
    off_ms = best[False] / args.steps * 1e3
    on_ms = best[True] / args.steps * 1e3
    rec = runners[True][1]
    out = {
        "off_step_ms": round(off_ms, 3),
        "on_step_ms": round(on_ms, 3),
        "ratio": round(on_ms / off_ms, 4),
        "events": rec.recorded,
        "writes": rec.writes,
        "threshold": args.threshold,
        "pass": on_ms / off_ms <= args.threshold,
    }
    print(json.dumps(out))
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
