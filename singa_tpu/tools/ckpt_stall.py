"""Measure the checkpoint stall: step time with per-step saves vs none.

The zero-stall pipeline's whole claim (resilience/async_ckpt.py) is that
``checkpoint_frequency: 1`` costs ~nothing: the save becomes a
non-blocking device snapshot + a background write, so the step loop
never waits on disk. This tool measures it — and reproduces the OLD
synchronous stall as the baseline — by timing the same small MLP job
three ways on the per-step path:

  nockpt   no checkpointing at all (the reference step time)
  async    checkpoint EVERY step through the async pipeline
  sync     checkpoint every step through the synchronous save

and printing one JSON line::

  {"nockpt_step_ms": .., "async_step_ms": .., "sync_step_ms": ..,
   "async_ratio": .., "sync_ratio": .., "threshold": .., "pass": ..}

Exit status 0 iff EITHER ``async_ratio <= threshold`` (default 1.25 —
the accelerator-host bar: the zero-stall claim measured directly) OR
``async <= 1.1 x sync`` (the host-independent invariant: the async
path is never slower than the sync path it replaces). The second
clause exists because on a CPU-only host the writer's CPU time is
stolen from the very cores doing the "device" compute — no pipeline
can hide CPU work from itself — so async lands near sync there
(measured: async ~1.3-1.6x, sync ~1.7-1.8x of no-checkpointing on a
2-core host) while on a real accelerator the step loop runs free of
the writer. ``pass_mode`` in the JSON says which clause carried.

Probe regimes: the default (``--hidden 64 --batch 8192``) keeps step
compute well above the per-save write cost — the regime where hiding
the write is possible at all; checkpoint-heavy (``--hidden 512
--batch 2048``) saves ~3.3 MB per ~45 ms step and shows the sync stall
at its worst. Usage::

  python -m singa_tpu.tools.ckpt_stall [--steps N] [--warmup N]
      [--batch N] [--hidden N] [--trials N] [--threshold R]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


_CONF = """
name: "ckpt-stall-probe"
train_steps: 100000
checkpoint_frequency: 0
updater {{
  base_learning_rate: 0.05
  learning_rate_change_method: kFixed
  momentum: 0.9
  type: kSGD
}}
neuralnet {{
  layer {{
    name: "data"
    type: "kShardData"
    data_param {{ path: "{shard}" batchsize: {batch} }}
  }}
  layer {{
    name: "mnist"
    type: "kMnistImage"
    srclayers: "data"
    mnist_param {{ norm_a: 127.5 norm_b: 1 }}
  }}
  layer {{ name: "label" type: "kLabel" srclayers: "data" }}
  layer {{
    name: "fc1"
    type: "kInnerProduct"
    srclayers: "mnist"
    inner_product_param {{ num_output: {hidden} }}
    param {{ name: "weight" init_method: kUniform low: -0.05 high: 0.05 }}
    param {{ name: "bias" init_method: kConstant value: 0 }}
  }}
  layer {{ name: "tanh1" type: "kTanh" srclayers: "fc1" }}
  layer {{
    name: "fc2"
    type: "kInnerProduct"
    srclayers: "tanh1"
    inner_product_param {{ num_output: 10 }}
    param {{ name: "weight" init_method: kUniform low: -0.05 high: 0.05 }}
    param {{ name: "bias" init_method: kConstant value: 0 }}
  }}
  layer {{
    name: "loss"
    type: "kSoftmaxLoss"
    softmaxloss_param {{ topk: 1 }}
    srclayers: "fc2"
    srclayers: "label"
  }}
}}
resilience {{ keep_last: 2 backoff_base: 0 }}
"""


def _make_runner(
    root: str, shard: str, batch: int, hidden: int,
    warmup: int, ckpt: str | None,
):
    """-> (window(steps) -> seconds, close()) for one probe mode.

    ``ckpt``: None = no saves, "sync" / "async" = a save EVERY step
    through that path. The runner is warmed (compile + first save)
    before returning, so windows measure steady state. Window timing is
    whole-window wall clock with ONE final value materialization
    (bench.py's methodology): a per-step device sync would serialize
    the execution stream with the writer's device->host copies and
    measure the serialization, not the stall. In-flight background
    writes at window end are NOT awaited — writes continuing past the
    step loop is exactly the zero-stall contract (backpressure bounds
    the backlog at one window)."""
    import jax.numpy as jnp

    from ..config import parse_model_config
    from ..config.schema import ClusterConfig
    from ..resilience import FaultPlan, ResilienceContext
    from ..trainer import Trainer

    cfg = parse_model_config(
        _CONF.format(shard=shard, batch=batch, hidden=hidden)
    )
    cluster = ClusterConfig()
    cluster.workspace = tempfile.mkdtemp(prefix="ckpt_stall_", dir=root)
    ctx = None
    if ckpt is not None:
        cfg.resilience.async_checkpoint = ckpt == "async"
        ctx = ResilienceContext(
            cfg.resilience, FaultPlan(), log=lambda s: None
        )
    # per-step driving (train_one_batch below, never run()) with the
    # device-resident dataset: host work per step is an index vector,
    # so the windows measure step compute + the save path, not 25 MB of
    # per-step host batch assembly jittering against the writer thread
    trainer = Trainer(
        cfg, cluster, seed=0, log=lambda s: None,
        prefetch=False, device_cache=True,
    )
    if ctx is not None:
        ctx.bind(trainer)

    def sync() -> float:
        return float(jnp.sum(jnp.abs(next(iter(trainer.params.values())))))

    state = {"step": 0}
    for _ in range(warmup):  # compile + first save, untimed
        trainer.train_one_batch(state["step"])
        if ckpt is not None:
            trainer.save(state["step"] + 1)
        state["step"] += 1
    sync()

    def window(steps: int) -> float:
        step = state["step"]
        t0 = time.perf_counter()
        for _ in range(steps):
            trainer.train_one_batch(step)
            if ckpt is not None:
                trainer.save(step + 1)
            step += 1
        sync()
        elapsed = time.perf_counter() - t0
        state["step"] = step
        # drain OUTSIDE the timed region: in-flight background writes
        # must not bleed CPU into the next mode's interleaved window
        # (that would inflate the baselines async is compared against)
        if ctx is not None:
            ctx.flush_async(raise_errors=False)
        return elapsed

    def close() -> None:
        if ctx is not None:
            ctx.flush_async(raise_errors=False)
            ctx.stop()

    return window, close


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="ckpt_stall", description=__doc__)
    ap.add_argument("--steps", type=int, default=20, help="timed steps")
    ap.add_argument("--warmup", type=int, default=4, help="untimed steps")
    ap.add_argument(
        "--trials", type=int, default=3,
        help="windows per mode; the best (least-contended) one counts",
    )
    # batch/hidden size the probe's step-compute : checkpoint-bytes
    # ratio. The defaults pick the regime where hiding the write is
    # possible at all: step compute well above the writer's per-save
    # cost. A step CHEAPER than its own checkpoint write at frequency 1
    # is writer-throughput-bound by design (backpressure throttles the
    # loop instead of growing memory) — and on a CPU-only host the
    # writer's own CPU time is stolen from the "device", so a
    # checkpoint-HEAVY probe (`--hidden 512 --batch 2048`) measures
    # core contention, not the stall; use it to reproduce the old
    # synchronous path's stall as a baseline.
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--hidden", type=int, default=64,
                    help="fc1 width (sets checkpoint bytes)")
    ap.add_argument(
        "--threshold", type=float, default=1.25,
        help="max allowed async/nockpt mean-step-time ratio",
    )
    args = ap.parse_args(argv)

    from ..data.loader import synthetic_arrays, write_records

    root = tempfile.mkdtemp(prefix="singa_tpu_stall_")
    shard = os.path.join(root, "shard")
    write_records(shard, *synthetic_arrays(1024, seed=0))
    # INTERLEAVED best-of-trials: one window per mode per round, minimum
    # per mode. Measuring each mode's windows in its own phase lets a
    # burst of ambient host load land entirely on one mode and skew the
    # ratio (observed 1.0x-1.5x swings on a 2-core host); interleaving
    # spreads the noise across all three, and the min discards it.
    runners = {
        mode: _make_runner(
            root, shard, args.batch, args.hidden, args.warmup, mode
        )
        for mode in (None, "async", "sync")
    }
    best = {mode: float("inf") for mode in runners}
    for _ in range(args.trials):
        for mode, (window, _) in runners.items():
            best[mode] = min(best[mode], window(args.steps))
    for _, close in runners.values():
        close()
    nockpt = best[None] / args.steps * 1e3
    async_ms = best["async"] / args.steps * 1e3
    sync_ms = best["sync"] / args.steps * 1e3
    # Two ways to pass, because the probe runs on whatever jax.devices()
    # gives. Where compute runs on an accelerator, the writer's host CPU
    # is free and the zero-stall claim is directly measurable:
    # async within `threshold` of no checkpointing at all. On a CPU-only
    # host the writer's CPU time is stolen from the very cores doing
    # the "device" compute — no pipeline can hide CPU work from itself —
    # so the gate degrades to the invariant that IS host-independent:
    # the async path is never slower than the sync path it replaces
    # (within 10% noise). A regression that serializes the pipeline
    # (e.g. a step-path flush) fails both clauses.
    vs_nockpt = async_ms / nockpt <= args.threshold
    vs_sync = async_ms <= sync_ms * 1.1
    out = {
        "nockpt_step_ms": round(nockpt, 3),
        "async_step_ms": round(async_ms, 3),
        "sync_step_ms": round(sync_ms, 3),
        "async_ratio": round(async_ms / nockpt, 3),
        "sync_ratio": round(sync_ms / nockpt, 3),
        "threshold": args.threshold,
        "pass_mode": (
            "vs_nockpt" if vs_nockpt else "vs_sync" if vs_sync else None
        ),
        "pass": vs_nockpt or vs_sync,
    }
    print(json.dumps(out))
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
