"""Fused batch normalization with a hand-written VJP.

Why this exists (round-4 perf work): autodiff through
``jnp.mean``/``jnp.var`` plus fp32 casts generated 4-6 extra
full-activation passes per BatchNorm; on ResNet-50 at batch 128 the 53
BN layers owned 18 ms of a 50.9 ms train step (measured by layer
ablation on a v5e chip, BASELINE.md r4). This implementation does the
information-theoretic minimum of HBM traffic:

  fwd:  one fused read of x for both moments (sum and sum-of-squares
        accumulated in fp32 inside the reduction — no materialized fp32
        copy), then one read+write for the normalize.
  bwd:  one fused read of (dy, x) for the two reductions
        (sum(dy), sum(dy*xhat)), one read of (dy, x) + write for dx.

Total: 8 activation-sized bf16 touches for fwd+bwd, vs ~14 (some fp32)
from autodiff of the naive formula.

The reference has no batch normalization (its registry tops out at LRN,
/root/reference/src/worker/neuralnet.cc:13-33); this op backs the
kBatchNorm extension layer (singa_tpu/layers/norm.py) that the ResNet
configs (BASELINE stretch config 5) are built from.

``batch_norm_train`` returns (y, mean, var). The y-cotangent math is
the standard BN backward:

  dgamma = sum(dy * xhat),  dbeta = sum(dy)
  dx     = gamma*inv * (dy - dbeta/n - xhat * dgamma/n)

and the mean/var cotangents contribute dmean/n + 2*dvar*(x-mean)/n,
folded into the same dx pass (free when they are the usual structural
zeros — XLA constant-folds them away).

Numerics: one-pass moments E[x^2]-E[x]^2 cancel catastrophically when
|mean|/std exceeds ~3e3 in fp32 (ulp 6e-8 of mean^2 swamps std^2).
Two defenses, both costless on the hot path:

  1. an optional per-channel ``shift`` anchor subtracted inside the
     pass (layers/norm.py passes its running-mean buffer — a free
     independent input, unlike anchors computed from x, which measured
     +2.5ms/step on ResNet-50 by serializing ahead of every stats
     reduction);
  2. a lax.cond rescue: when any channel's one-pass variance is within
     10x of the cancellation noise floor (var < 1e-5 * mean_shifted^2,
     i.e. |mean|/std > ~316 in the anchored frame), a second,
     cancellation-free pass E[(x - s - m)^2] recomputes the exact
     variance. The predicate is false in any sane training regime, so
     the branch never runs — but step 0 with a cold anchor and a
     pathologically offset input is still *correct*, just one pass
     slower. (Under vmap, cond lowers to select and both branches pay —
     don't vmap this op; the trainer never does.)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _axes_shape(x: jnp.ndarray):
    """Reduction axes and broadcast shape for (N, C) or (N, C, H, W)."""
    if x.ndim == 2:
        return (0,), (1, -1)
    return (0, 2, 3), (1, -1, 1, 1)


def _moments(x: jnp.ndarray, axes, shape, n: int, shift):
    """Single-pass fp32 batch moments of the shifted data: with
    s = shift (a per-channel mean estimate), E[x] = E[x-s] + s and
    Var[x] = E[(x-s)^2] - E[x-s]^2. The elementwise cast, subtract, and
    square all fuse into the two reductions, so x is read once from HBM
    and no fp32 copy is materialized. See the module docstring for the
    cancellation rescue."""
    sf = None if shift is None else shift.astype(jnp.float32).reshape(shape)

    def shifted(xx):
        xxf = xx.astype(jnp.float32)
        return xxf if sf is None else xxf - sf

    xf = shifted(x)
    s1 = jnp.sum(xf, axes)
    s2 = jnp.sum(xf * xf, axes)
    m = s1 / n
    var = jnp.maximum(s2 / n - m * m, 0.0)

    def exact_var():
        # cancellation-free second pass around the now-known exact mean.
        # Recompute the shifted cast from x INSIDE the branch: closing
        # over xf would force XLA to materialize the fp32 copy in HBM
        # for the branch operand (measured +4ms/step on ResNet-50 even
        # with the branch never taken)
        d = shifted(x) - m.reshape(shape)
        return jnp.sum(d * d, axes) / n

    suspect = jnp.any(var * 1e5 < m * m)
    var = jax.lax.cond(suspect, exact_var, lambda: var)
    mean = m if shift is None else m + shift.astype(jnp.float32)
    return mean, var


def _apply(x, gamma, beta, eps, shift):
    axes, shape = _axes_shape(x)
    n = x.size // x.shape[1]
    mean, var = _moments(x, axes, shape, n, shift)
    inv = jax.lax.rsqrt(var + eps)
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - scale * mean
    y = (
        x * scale.astype(x.dtype).reshape(shape)
        + shift.astype(x.dtype).reshape(shape)
    )
    return y, mean, var, inv


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def batch_norm_train(x, gamma, beta, eps=1e-5, shift=None):
    """-> (y, mean, var). Batch stats are fp32; y stays in x.dtype.

    ``shift`` (optional, (C,)) is a numerical-stability anchor for the
    one-pass moments — pass a running-mean estimate; it does not change
    the math and receives a zero gradient."""
    y, mean, var, _ = _apply(x, gamma, beta, eps, shift)
    return y, mean, var


def _bn_fwd(x, gamma, beta, eps, shift):
    y, mean, var, inv = _apply(x, gamma, beta, eps, shift)
    return (y, mean, var), (x, gamma, beta, mean, inv, shift)


def _bn_bwd(eps, res, cts):
    dy, dmean, dvar = cts
    x, gamma, beta, mean, inv, shift = res
    axes, shape = _axes_shape(x)
    n = x.size // x.shape[1]
    dyf = dy.astype(jnp.float32)
    xc = x.astype(jnp.float32) - mean.reshape(shape)
    xhat = xc * inv.reshape(shape)
    dbeta = jnp.sum(dyf, axes)
    dgamma = jnp.sum(dyf * xhat, axes)
    k = (gamma.astype(jnp.float32) * inv).reshape(shape)
    dxf = k * (
        dyf - (dbeta / n).reshape(shape) - xhat * (dgamma / n).reshape(shape)
    )
    # mean/var output cotangents: usually structural zeros (running-stat
    # updates are detached); the terms fuse into the same dx pass and
    # XLA folds them away when zero, so generality costs nothing
    dxf = dxf + (dmean / n).reshape(shape) + xc * (2.0 / n * dvar).reshape(shape)
    dx = dxf.astype(x.dtype)
    # shift is a stability anchor that cancels out of the math — zero
    # gradient (None when the arg was None, matching its pytree)
    dshift = None if shift is None else jnp.zeros_like(shift)
    return (
        dx,
        dgamma.astype(gamma.dtype),
        dbeta.astype(beta.dtype),
        dshift,
    )


batch_norm_train.defvjp(_bn_fwd, _bn_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def batch_norm_train_sampled(x, gamma, beta, eps, stride, shift=None):
    """Subsample-stats BatchNorm (OPT-IN, different math — r5 knob).

    Batch moments are computed from the first ``batch/stride`` sample
    rows (a contiguous prefix — see _apply_sampled for why not a
    strided slice) and the backward treats them as DETACHED constants:

        dx = gamma * inv * dy          (no reduction dependency)
        dgamma/dbeta exact as usual

    Two deliberate approximations vs batch_norm_train:
      * stats see batch/stride samples (an unbiased but noisier moment
        estimate — large batches tolerate this the way ghost/virtual BN
        does);
      * the mean/var gradient paths are dropped (straight-through).
    Why it exists: measured on ResNet-50 @128/v5e, exact BN's marginal
    cost is 14.6 ms of a 46.6 ms step, and the irreducible same-math
    term (the stats read, 2.71 GB) caps a perfect conv-epilogue kernel
    at ~3.3 ms back = 34.7% MFU (bench/ablations/bn_roofline.py). This
    knob removes (stride-1)/stride of the stats read AND lets XLA fuse
    the whole backward into one (dy, x) read since dx no longer waits
    on the reductions. Exposed as batchnorm_param.stats_sample_stride
    (default 1 = exact op); convergence consequences are the user's
    opt-in.

    Returns (y, mean, var) like batch_norm_train.
    """
    y, mean, var, _ = _apply_sampled(x, gamma, beta, eps, stride, shift)
    return y, mean, var


def _apply_sampled(x, gamma, beta, eps, stride, shift):
    axes, shape = _axes_shape(x)
    # contiguous PREFIX rows, not a strided slice: x[::stride] lowers to
    # a gather/copy on TPU (measured: the stride-4 knob ran 9 ms SLOWER
    # than exact BN with it), while x[:n/stride] is a zero-cost view.
    # Batches are shuffled streams, so a prefix is as unbiased a sample
    # as a stride.
    nkeep = max(1, x.shape[0] // stride)
    xs = jax.lax.slice_in_dim(x, 0, nkeep, 1, axis=0)
    n = xs.size // xs.shape[1]
    mean, var = _moments(xs, axes, shape, n, shift)
    inv = jax.lax.rsqrt(var + eps)
    scale = gamma.astype(jnp.float32) * inv
    sh = beta.astype(jnp.float32) - scale * mean
    y = (
        x * scale.astype(x.dtype).reshape(shape)
        + sh.astype(x.dtype).reshape(shape)
    )
    return y, mean, var, inv


def _bns_fwd(x, gamma, beta, eps, stride, shift):
    y, mean, var, inv = _apply_sampled(x, gamma, beta, eps, stride, shift)
    return (y, mean, var), (x, gamma, beta, mean, inv, shift)


def _bns_bwd(eps, stride, res, cts):
    dy, _dmean, _dvar = cts  # stats are detached: their cotangents drop
    x, gamma, beta, mean, inv, shift = res
    axes, shape = _axes_shape(x)
    dyf = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean.reshape(shape)) * inv.reshape(shape)
    dbeta = jnp.sum(dyf, axes)
    dgamma = jnp.sum(dyf * xhat, axes)
    # straight-through: dx independent of the reductions — one fused
    # (dy, x) read produces dx AND both param grads
    dx = (
        dyf * (gamma.astype(jnp.float32) * inv).reshape(shape)
    ).astype(x.dtype)
    dshift = None if shift is None else jnp.zeros_like(shift)
    return dx, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype), dshift


batch_norm_train_sampled.defvjp(_bns_fwd, _bns_bwd)


def batch_norm_infer(x, gamma, beta, mean, var, eps=1e-5):
    """Normalize by running stats (eval path); plain autodiff is fine
    here — stats are constants, so it's one fused elementwise pass."""
    _, shape = _axes_shape(x)
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - scale * mean.astype(jnp.float32)
    return (
        x * scale.astype(x.dtype).reshape(shape)
        + shift.astype(x.dtype).reshape(shape)
    )
