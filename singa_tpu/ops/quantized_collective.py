"""True int8-on-the-wire gradient collectives: a quantized ring reduce.

PR 8's ``grad_comm`` block models quantized gradient reduction — each
bucket is cast to a scaled int8/bf16 wire value *around* the data-axis
reduction — but the documented carry-over stands: the cast sits on the
logical (already-summed) gradient, so XLA's implicit GSPMD ``psum`` /
reduce-scatter still moves full-precision bytes. The wire is not
actually 4x narrower. EQuARX (PAPERS.md, arxiv 2506.17615) shows the
win comes from keeping the *reduction itself* in the quantized domain.

This module is that reduction: a ring reduce-scatter + allgather over
the data axis whose wire value is genuinely int8. It runs per shard
under ``shard_map`` (``parallel/ring.py``'s ppermute ring is the
structural precedent), so each shard holds its own LOCAL partial
gradient — the thing GSPMD never exposes — and every hop
``lax.ppermute``s a *quantized* chunk, with the bucket's f32 scale
riding alongside as a tiny scalar operand:

  reduce-scatter   each param's gradient is chunked over the data axis
                   (``chunk_dims``); at hop t every shard quantizes its
                   accumulated chunk (one symmetric max-abs scale per
                   BUCKET — the grad_comm scale granularity), ppermutes
                   the int8 bytes + the scale one hop, dequantizes what
                   arrives, and accumulates its own local partial of
                   that chunk in f32 — the EQuARX two-level
                   construction: narrow on the wire, full precision in
                   the accumulator.
  allgather        after N-1 hops each shard owns its chunk's full sum;
                   the owner quantizes it ONCE (banking the
                   quantization error as the error-feedback residual)
                   and the (q, scale) pair rides N-1 more hops around
                   the ring — every shard dequantizes the identical
                   bytes, so the gathered gradient is bitwise identical
                   on every shard. Under ``zero_update`` this phase is
                   skipped: the ring's natural scatter output IS the
                   update layout (each shard keeps exactly its
                   shard-local chunk).

Error feedback (the one-shot-EF caveat): PR 8's reference path banks
the ENTIRE compression error — quantization there is one shot on the
summed gradient. The ring re-quantizes per hop, and a hop's rounding
error is only known to the shard that rounded, for a chunk it does not
own — so the residual banks the final (owner-side) quantization error
exactly, in full f32, while per-hop wire errors go un-fed-back. They
are bounded by the same 1/127 relative scale and convergence stays
within the CI parity bar (tools/convergence.py ``--grad_comm q8wire``);
the trade is documented in README "Kernels".

NaN-poisoned-scale semantics are preserved: a NaN/Inf partial drives
its bucket's max-abs scale to NaN, dequantization multiplies by the
scale, and the poison propagates through every downstream accumulation
— the divergence guard's verdict over the reduced grads fires on the
same step as fp32.

The pure-ppermute form here is plain XLA ops — the interpret/CPU-CI
path that every test run exercises. ``fused_hop`` swaps the per-hop
dequantize+accumulate onto a small Pallas kernel for real hardware
(``quant_acc``), gated by the same ``fusable``-style geometry predicate
pattern as the paged-attention kernel (``ring_fusable``).

Hierarchical two-level form (``kernels { grad_allreduce: q8_hier }``):
EQuARX's deployment topology is not one flat ring — it is fast
intra-slice ICI feeding ONE scarce inter-slice DCN hop, and the int8
saving matters exactly on the scarce hop. ``hier_ring_geometry``
factors the n-wide data reduction as K (intra) x M (inter): rank
r = g*K + p runs

  intra reduce-scatter   K-1 hops over the fast axis in FULL f32 (ICI
                         bandwidth is cheap; no quantization error is
                         introduced where it buys nothing), piece-major
                         — after K-1 hops rank (g, p) holds the
                         group-local sum of every chunk at position p,
                         an (M, chunk) plane.
  inter quantized ring   M-1 hops over the scarce axis with the SAME
                         int8 + per-bucket-scale + dequant/accumulate/
                         requant discipline as the flat ring — rank
                         (g, p) finishes owning the global sum of chunk
                         g*K + p, the identical post-scatter state as
                         the flat ring, so error feedback and the
                         owner-side final quantize are literally shared
                         code.
  two-level allgather    the (int8 bytes, scale) pairs ride M-1 inter
                         hops then K-1 intra hops (whole plane at a
                         time), every rank dequantizes identical bytes
                         — the gathered gradient stays bitwise
                         ring-invariant. zero_update still skips it.

Chunk granularity stays n = K*M, so residual layouts, zero_update
shards, and sharded checkpoints are indistinguishable from a flat ring
of the same total width. Per-level wire accounting lives in
``modeled_wire_bytes_levels`` (analytic) and
``ppermute_wire_bytes_levels`` (jaxpr-counted), parity-held in tests;
the inter-slice bytes shrink by ~the intra degree vs the flat ring
(exactly: K*(M-1) <= n-1 chunks cross the scarce axis instead of n-1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # jax >= 0.6 exports shard_map at the top level; this image's
    # 0.4.x ships it under experimental — parallel/{ring,moe,pipeline}
    # import this shim too, so every shard_map call site resolves the
    # rename in one place
    from jax.experimental.shard_map import shard_map
except Exception:  # pragma: no cover - newer jax
    shard_map = jax.shard_map

#: int8 symmetric range: q in [-127, 127], scale = max|e| / 127 (shared
#: with parallel/collectives.py's reference quantized path — ONE
#: quantize/dequantize pair, so the ring and the oracle cannot drift)
INT8_MAX = 127.0

#: scale floor: an all-zero bucket must not divide by zero
_SCALE_FLOOR = 1e-30

#: hardware tile floor for the compiled (fused_hop) inner kernel: the
#: per-hop chunk is processed as (rows, 128) f32 tiles — sublanes of 8,
#: lanes of 128, like ops/paged_attention's floor
_SUBLANE, _LANE = 8, 128


# ---------------------------------------------------------------------------
# shared quantize/dequantize helpers (the one pair both the reference
# grad_comm path and the ring consult)
# ---------------------------------------------------------------------------


def symmetric_scale(arrays) -> jnp.ndarray:
    """One symmetric int8 scale for a bucket: max-abs over every array
    in it, floored away from zero so an all-zero bucket cannot divide
    by zero. Max is exactly associative, so the scale is
    bitwise-independent of layout — and a NaN/Inf element poisons it
    (``jnp.max`` propagates NaN), which is the guard contract: the
    poison survives dequantization."""
    amax = functools.reduce(
        jnp.maximum,
        (jnp.max(jnp.abs(a.astype(jnp.float32))) for a in arrays),
    )
    return jnp.maximum(amax, jnp.float32(_SCALE_FLOOR)) / INT8_MAX


def quantize_int8(e: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Symmetric int8 cast: round(e / scale), clipped to [-127, 127].
    A NaN scale produces implementation-defined int8 bytes — harmless,
    because ``dequantize_int8`` multiplies by the same NaN scale."""
    return jnp.clip(
        jnp.round(e.astype(jnp.float32) / scale), -INT8_MAX, INT8_MAX
    ).astype(jnp.int8)


def dequantize_int8(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """int8 wire value back to f32: q * scale (NaN scale -> NaN out)."""
    return q.astype(jnp.float32) * scale


def wire_cast(e: jnp.ndarray, scale, dtype: str):
    """Cast ``e`` to the wire dtype: (wire array, scale or None)."""
    if dtype == "int8":
        return quantize_int8(e, scale), scale
    return e.astype(jnp.bfloat16), None


def wire_uncast(w: jnp.ndarray, scale, dtype: str) -> jnp.ndarray:
    if dtype == "int8":
        return dequantize_int8(w, scale)
    return w.astype(jnp.float32)


# ---------------------------------------------------------------------------
# geometry predicates (consulted by the trainer's runtime rejection AND
# netlint's KRN002 — a static mirror must never drift from its runtime)
# ---------------------------------------------------------------------------


def ring_reducible(
    shapes: dict, ndata: int, chunk_dims: dict | None = None
) -> str | None:
    """None if the ring can chunk every gradient over an ``ndata``-wide
    data axis, else the reason it cannot. ``shapes`` maps param name ->
    stored shape; ``chunk_dims`` maps name -> the dim the ring chunks
    (default 0 — the update-layout dim under ``zero_update``). The ring
    sends fixed equal chunks, so the chunk dim must divide evenly: a
    padded phantom chunk would ppermute garbage into real sums."""
    if ndata <= 1:
        return None
    for name in sorted(shapes):
        shape = tuple(shapes[name])
        if not shape:
            return (
                f"param {name!r} is a scalar: the ring cannot chunk a "
                "0-d gradient over the data axis"
            )
        d = (chunk_dims or {}).get(name, 0)
        if shape[d] % ndata:
            return (
                f"param {name!r} dim {d} ({shape[d]}) not divisible by "
                f"the data-axis width {ndata}: the ring's bucket "
                "chunking cannot split it into equal wire chunks"
            )
    return None


def ring_fusable(
    shapes: dict, ndata: int, chunk_dims: dict | None = None,
    interpret: bool = True,
) -> str | None:
    """None if the fused (Pallas) per-hop quantize+accumulate kernel can
    serve this geometry, else the reason. The interpret form tiles
    anything (plain XLA ops); the compiled form processes each chunk as
    (rows, 128) f32 register tiles, so the per-shard chunk element
    count must align to the (8, 128) tile."""
    reason = ring_reducible(shapes, ndata, chunk_dims)
    if reason is not None:
        return reason
    if interpret or ndata <= 0:
        return None
    tile = _SUBLANE * _LANE
    for name in sorted(shapes):
        shape = tuple(shapes[name])
        d = (chunk_dims or {}).get(name, 0)
        elems = shape[d] // max(1, ndata)
        for i, s in enumerate(shape):
            if i != d:
                elems *= s
        if elems % tile:
            return (
                f"param {name!r} ring chunk has {elems} elements, not a "
                f"multiple of the ({_SUBLANE}, {_LANE}) f32 tile: the "
                "compiled quantize+accumulate kernel cannot tile it"
            )
    return None


def hier_ring_geometry(widths, ring, *, data_axis: str = "data"):
    """Resolve the two-level ring geometry for ``q8_hier``: returns
    ``(intra_axis, inter_axis, K, M)`` when the mesh admits the
    factorization, else the reason string. The trainer raises the
    reason at construction and netlint's KRN002 reports it statically
    — one predicate, so the static mirror cannot drift. This is the
    generalization seam for ``ring_reducible``/``ring_fusable``: the
    flat ring's loud composed-mesh rejection becomes the FALLBACK
    (``quantized_ring`` keeps it), while ``q8_hier`` accepts any mesh
    this factorization covers, then runs the chunkability predicates
    with the TOTAL width n = K*M.

    ``widths`` maps mesh axis -> width; ``ring`` is the model conf's
    ``ring {}`` block (or None). Factored form: ``intra_degree: K``
    splits the ``data`` axis into M = n/K groups of K adjacent ranks
    (K must divide the data width; every other axis must be 1-wide —
    nothing else covers them). Named form: ``intra_axis`` /
    ``inter_axis`` name two distinct mesh axes whose product IS the
    data reduction (the batch shards over both); the ``data`` axis
    must be one of them when >1-wide, and no third axis may be >1-wide.
    A 1-wide reduction degenerates to K = M = 1 (the ring is a no-op,
    same as ``ring_reducible``'s ``ndata <= 1`` convention)."""
    widths = {k: int(v) for k, v in (widths or {}).items()}
    intra = getattr(ring, "intra_axis", "") if ring is not None else ""
    inter = getattr(ring, "inter_axis", "") if ring is not None else ""
    degree = int(getattr(ring, "intra_degree", 0) or 0)
    if not degree and not intra and not inter:
        return (
            "kernels { grad_allreduce: q8_hier } needs a ring {} block "
            "declaring the two-level geometry: intra_degree to factor "
            "the data axis, or intra_axis/inter_axis naming mesh axes"
        )
    if degree and (intra or inter):
        return (
            "ring { intra_degree } and ring { intra_axis/inter_axis } "
            "are mutually exclusive: the factored form splits the data "
            "axis itself, the named form rides two real mesh axes"
        )
    if degree:
        n = widths.get(data_axis, 1)
        others = sorted(
            a for a, wd in widths.items() if a != data_axis and wd > 1
        )
        if others:
            return (
                f"ring {{ intra_degree: {degree} }} factors the "
                f"{data_axis!r} axis only, but the mesh also shards "
                + ", ".join(f"{a!r} (width {widths[a]})" for a in others)
                + " — name the extra axis with ring { intra_axis/"
                "inter_axis } if the reduction should ride it"
            )
        if n <= 1:
            return (data_axis, data_axis, 1, 1)
        if degree > n or n % degree:
            return (
                f"ring {{ intra_degree: {degree} }} does not divide the "
                f"{data_axis!r} axis width {n}: the two-level "
                "factorization needs n = intra_degree * inter groups"
            )
        return (data_axis, data_axis, degree, n // degree)
    if not intra or not inter:
        return (
            "ring { intra_axis/inter_axis } must name BOTH axes (got "
            f"intra_axis={intra!r}, inter_axis={inter!r}) — or use "
            "intra_degree to factor the data axis"
        )
    if intra == inter:
        return (
            f"ring {{ intra_axis: {intra!r} }} and inter_axis name the "
            "same mesh axis — use intra_degree to factor one axis"
        )
    for role, ax in (("intra_axis", intra), ("inter_axis", inter)):
        if ax not in widths:
            return (
                f"ring {{ {role}: {ax!r} }} names no mesh axis "
                f"(mesh axes: {', '.join(sorted(widths)) or 'none'})"
            )
    if widths.get(data_axis, 1) > 1 and data_axis not in (intra, inter):
        return (
            f"the {data_axis!r} axis (width {widths[data_axis]}) is "
            "not covered by ring { intra_axis/inter_axis } — the "
            "gradient reduction must include every data shard"
        )
    leftovers = sorted(
        a for a, wd in widths.items()
        if wd > 1 and a not in (intra, inter)
    )
    if leftovers:
        return (
            "mesh axes "
            + ", ".join(f"{a!r} (width {widths[a]})" for a in leftovers)
            + " are >1-wide but outside the ring { intra_axis/"
            "inter_axis } factorization — the two-level ring covers "
            "exactly two axes"
        )
    return (intra, inter, widths[intra], widths[inter])


# ---------------------------------------------------------------------------
# optional Pallas inner kernel: dequantize + accumulate fused per hop
# ---------------------------------------------------------------------------


def _quant_acc_kernel(q_ref, s_ref, x_ref, o_ref):
    o_ref[...] = q_ref[...].astype(jnp.float32) * s_ref[0, 0] + x_ref[...]


def quant_acc(
    q: jnp.ndarray, scale: jnp.ndarray, local: jnp.ndarray,
    *, interpret: bool = True,
) -> jnp.ndarray:
    """``dequantize_int8(q, scale) + local`` as ONE fused Pallas kernel
    — the per-hop accumulation's memory traffic is one read of the int8
    chunk, one read of the local f32 partial, one write, with no f32
    dequantized intermediate ever hitting HBM. ``interpret=True`` runs
    it through the Pallas interpreter (plain XLA ops — the unit test
    pins it to the jnp form within 1 ulp; the interpreter may contract
    the multiply-add into an fma); ``interpret=False`` compiles
    through Mosaic and needs ``ring_fusable`` geometry."""
    from jax.experimental import pallas as pl

    n = local.size
    cols = _LANE if n % _LANE == 0 else n
    qf = q.reshape(n // cols, cols)
    xf = local.astype(jnp.float32).reshape(n // cols, cols)
    out = pl.pallas_call(
        _quant_acc_kernel,
        out_shape=jax.ShapeDtypeStruct(xf.shape, jnp.float32),
        interpret=bool(interpret),
    )(qf, scale.reshape(1, 1), xf)
    return out.reshape(local.shape)


# ---------------------------------------------------------------------------
# the ring itself (runs per shard, inside shard_map)
# ---------------------------------------------------------------------------


def _chunked(x: jnp.ndarray, d: int, n: int) -> jnp.ndarray:
    """(..., S[d], ...) -> (n, S[d]//n, ...rest) with the chunk dim
    moved to the front."""
    y = jnp.moveaxis(x, d, 0)
    return y.reshape((n, y.shape[0] // n) + y.shape[1:])


def _unchunk(y: jnp.ndarray, d: int, shape) -> jnp.ndarray:
    """Inverse of ``_chunked``: (n, c, ...rest) -> the original shape."""
    z = y.reshape((y.shape[0] * y.shape[1],) + y.shape[2:])
    return jnp.moveaxis(z, 0, d).reshape(shape)


def _shard_shape(shape, d: int, n: int):
    return tuple(
        s // n if i == d else s for i, s in enumerate(shape)
    )


def _hier_reduce_scatter(
    chunks: dict, p, g, K: int, M: int, pperm_intra, pperm_inter,
    dtype: str, fused_hop: bool, fused_interpret: bool,
) -> dict:
    """Two-level reduce-scatter over already-chunked grads: -> each
    rank's fully-summed own chunk (index g*K + p), shape (c, ...) —
    the same post-scatter state as the flat ring's scan.

    Level 1 (intra, f32 wire): view the n = K*M chunks piece-major as
    (K, M, c, ...) — piece j holds every chunk at intra position j —
    and ring-reduce-scatter the K pieces over the fast axis in full
    f32: after K-1 hops rank (g, p) holds the group-g-local sum of
    piece p, an (M, c, ...) plane. Quantizing here would buy nothing
    (ICI is the cheap hop) and would cost rounding error per hop.

    Level 2 (inter, quantized wire): ring-reduce-scatter the M plane
    entries over the scarce axis with the flat ring's exact per-hop
    discipline — one symmetric scale per bucket, int8 bytes + scale
    ppermute'd, dequant + f32 accumulate (+ requant next hop)."""
    # piece-major view: pieces[nm][j, gg] = chunk gg*K + j, f32 so the
    # intra accumulation (and its wire) is full precision by contract
    pieces = {
        nm: jnp.swapaxes(
            c.reshape((M, K) + c.shape[1:]), 0, 1
        ).astype(jnp.float32)
        for nm, c in chunks.items()
    }

    def pick_piece(idx):
        return {
            nm: jax.lax.dynamic_index_in_dim(
                pc, idx % K, axis=0, keepdims=False
            )
            for nm, pc in pieces.items()
        }

    acc = pick_piece(p - 1)  # (M, c, ...) per param

    def ihop(carry, t):
        moved = {nm: pperm_intra(a) for nm, a in carry.items()}
        local = pick_piece(p - t - 2)
        return {nm: moved[nm] + local[nm] for nm in carry}, None

    if K > 1:
        acc, _ = jax.lax.scan(ihop, acc, jnp.arange(K - 1))

    def pick_group(idx):
        return {
            nm: jax.lax.dynamic_index_in_dim(
                a, idx % M, axis=0, keepdims=False
            )
            for nm, a in acc.items()
        }

    out = pick_group(g - 1)  # (c, ...) per param

    def xhop(carry, t):
        scale = (
            symmetric_scale(carry.values()) if dtype == "int8" else None
        )
        wires = {
            nm: wire_cast(a, scale, dtype)[0] for nm, a in carry.items()
        }
        wires = {nm: pperm_inter(w) for nm, w in wires.items()}
        if scale is not None:
            scale = pperm_inter(scale)
        local = pick_group(g - t - 2)
        nxt = {}
        for nm, w in wires.items():
            if fused_hop and dtype == "int8":
                nxt[nm] = quant_acc(
                    w, scale, local[nm], interpret=fused_interpret
                )
            else:
                nxt[nm] = wire_uncast(w, scale, dtype) + local[nm]
        return nxt, None

    if M > 1:
        out, _ = jax.lax.scan(xhop, out, jnp.arange(M - 1))
    return out


def _hier_allgather(
    fq: dict, fscale, p, g, K: int, M: int, pperm_intra, pperm_inter,
    dtype: str,
) -> dict:
    """Two-level allgather of the owner-quantized (wire bytes, scale)
    pairs: the inter ring collects the M chunk planes at this rank's
    intra position, then the intra ring carries the collected
    (M, c, ...) plane + (M,) scales around the group whole. Every rank
    dequantizes IDENTICAL bytes with identical scales, so the gathered
    gradient stays bitwise ring-invariant — same contract as the flat
    allgather, int8 on the scarce hops only by construction (the intra
    hops move the already-int8 planes too: bytes, not f32).
    Returns {nm: (n, c, ...) f32} in chunk-index order."""
    wnames = list(fq)
    planes = {
        nm: jax.lax.dynamic_update_index_in_dim(
            jnp.zeros((M,) + fq[nm].shape, fq[nm].dtype),
            fq[nm], g, axis=0,
        )
        for nm in wnames
    }
    scales = (
        jax.lax.dynamic_update_index_in_dim(
            jnp.zeros((M,), jnp.float32), fscale, g, axis=0
        )
        if fscale is not None
        else None
    )

    def gxhop(carry, t):
        planes, scales, w, s = carry
        w = {nm: pperm_inter(v) for nm, v in w.items()}
        if s is not None:
            s = pperm_inter(s)
        idx = (g - t - 1) % M
        planes = {
            nm: jax.lax.dynamic_update_index_in_dim(
                planes[nm], w[nm], idx, axis=0
            )
            for nm in wnames
        }
        if s is not None:
            scales = jax.lax.dynamic_update_index_in_dim(
                scales, s, idx, axis=0
            )
        return (planes, scales, w, s), None

    if M > 1:
        (planes, scales, _, _), _ = jax.lax.scan(
            gxhop,
            (planes, scales, dict(fq), fscale),
            jnp.arange(M - 1),
        )
    big = {
        nm: jax.lax.dynamic_update_index_in_dim(
            jnp.zeros((K,) + planes[nm].shape, planes[nm].dtype),
            planes[nm], p, axis=0,
        )
        for nm in wnames
    }
    bigs = (
        jax.lax.dynamic_update_index_in_dim(
            jnp.zeros((K, M), jnp.float32), scales, p, axis=0
        )
        if scales is not None
        else None
    )

    def gihop(carry, t):
        big, bigs, w, s = carry
        w = {nm: pperm_intra(v) for nm, v in w.items()}
        if s is not None:
            s = pperm_intra(s)
        idx = (p - t - 1) % K
        big = {
            nm: jax.lax.dynamic_update_index_in_dim(
                big[nm], w[nm], idx, axis=0
            )
            for nm in wnames
        }
        if s is not None:
            bigs = jax.lax.dynamic_update_index_in_dim(
                bigs, s, idx, axis=0
            )
        return (big, bigs, w, s), None

    if K > 1:
        (big, bigs, _, _), _ = jax.lax.scan(
            gihop, (big, bigs, planes, scales), jnp.arange(K - 1)
        )
    out = {}
    for nm in wnames:
        arr = big[nm]  # (K, M, c, ...) wire dtype
        if bigs is not None:
            f = arr.astype(jnp.float32) * bigs.reshape(
                (K, M) + (1,) * (arr.ndim - 2)
            )
        else:
            f = arr.astype(jnp.float32)
        # [j, gg] holds chunk gg*K + j -> chunk-index-major (n, c, ...)
        f = jnp.swapaxes(f, 0, 1)
        out[nm] = f.reshape((M * K,) + arr.shape[2:])
    return out


def ring_reduce_gradients(
    grads: dict,
    residuals: dict,
    buckets: tuple,
    *,
    axis_name: str,
    nshards: int,
    chunk_dims: dict,
    gather: dict,
    dtype: str = "int8",
    error_feedback: bool = True,
    overlapped: bool = False,
    residual_key=None,
    fused_hop: bool = False,
    fused_interpret: bool = True,
    hier: tuple | None = None,
) -> tuple[dict, dict]:
    """The quantized ring all-reduce, per shard: -> (reduced grads,
    new error-feedback residual chunks).

    Runs INSIDE ``shard_map`` over the data axis. ``grads`` are this
    shard's local partials, pre-scaled so the cross-shard sum is the
    desired reduction (the trainer divides its local-batch mean grads
    by ``nshards``). ``residuals`` hold this shard's OWN chunk of each
    param's error-feedback residual (sliced by the shard_map in_specs).
    ``buckets`` are the reverse-topo groups from
    ``parallel.collectives.reverse_topo_buckets`` — one wire scale per
    bucket per hop, and with ``overlapped`` the buckets chain through
    ``optimization_barrier`` in gradient-readiness order exactly like
    the reference path. ``gather[name]`` False keeps the scatter layout
    (zero_update: the shard's chunk IS its update shard; the allgather
    phase never runs for that param).

    Output identity: gathered params are reconstructed from the SAME
    (int8 bytes, f32 scale) pairs on every shard, so the reduced
    gradient is bitwise identical ring-wide — tested, and what lets the
    step's out_specs declare them replicated.

    ``hier = (intra_axis, inter_axis, K, M)`` (from
    ``hier_ring_geometry``, with ``nshards == K*M``) swaps both phases
    onto the hierarchical two-level form: f32 intra reduce-scatter,
    quantized inter ring, two-level byte-carrying allgather. The
    factored single-axis form has ``intra_axis == inter_axis`` and
    builds structured perms on that one axis (rank r = g*K + p);
    chunk granularity, the error-feedback/owner-quantize step between
    the phases, and every output layout are SHARED with the flat ring.
    """
    n = nshards
    perm = [(j, (j + 1) % n) for j in range(n)]
    if hier is not None:
        intra_ax, inter_ax, K, M = hier
        if K * M != n:
            raise ValueError(
                f"hier geometry {K}x{M} does not match nshards {n}"
            )
        if intra_ax == inter_ax:  # factored data axis: rank = g*K + p
            me = jax.lax.axis_index(intra_ax)
            p, g = me % K, me // K
            iperm = [
                (gg * K + j, gg * K + (j + 1) % K)
                for gg in range(M)
                for j in range(K)
            ]
            xperm = [
                (gg * K + j, ((gg + 1) % M) * K + j)
                for gg in range(M)
                for j in range(K)
            ]
        else:  # named mesh axes: chunk index = g*K + p by in_specs order
            p = jax.lax.axis_index(intra_ax)
            g = jax.lax.axis_index(inter_ax)
            me = g * K + p
            iperm = [(j, (j + 1) % K) for j in range(K)]
            xperm = [(j, (j + 1) % M) for j in range(M)]

        def pperm_intra(x):
            return jax.lax.ppermute(x, intra_ax, iperm)

        def pperm_inter(x):
            return jax.lax.ppermute(x, inter_ax, xperm)

    else:
        me = jax.lax.axis_index(axis_name)
    out: dict = {}
    new_res: dict = {}
    token = None

    for bucket in buckets:
        gs = {nm: grads[nm] for nm in bucket}
        if token is not None:
            # pin this bucket's ring after the previous bucket's first
            # reduced array: the same reverse-topo issue-order chain as
            # the reference path (optimization_barrier is a value
            # identity that adds a scheduling edge)
            names = list(gs)
            fused = jax.lax.optimization_barrier(
                tuple(gs[nm] for nm in names) + (token,)
            )
            gs = dict(zip(names, fused[:-1]))
        chunks = {
            nm: _chunked(g, chunk_dims[nm], n) for nm, g in gs.items()
        }

        def pick(idx):
            return {
                nm: jax.lax.dynamic_index_in_dim(
                    c, idx % n, axis=0, keepdims=False
                )
                for nm, c in chunks.items()
            }

        # --- reduce-scatter: after n-1 hops shard ``me`` holds the
        # full sum of its own chunk ``me`` (start chunk me-1; the chunk
        # arriving at hop t is me-t-2, accumulated in f32). The
        # hierarchical form reaches the identical state through the
        # two-level schedule (f32 intra, quantized inter) ---
        if hier is not None:
            acc = _hier_reduce_scatter(
                chunks, p, g, K, M, pperm_intra, pperm_inter,
                dtype, fused_hop, fused_interpret,
            )
        else:
            acc = pick(me - 1)

        def hop(carry, t):
            acc = carry
            scale = (
                symmetric_scale(acc.values()) if dtype == "int8" else None
            )
            wires = {
                nm: wire_cast(a, scale, dtype)[0] for nm, a in acc.items()
            }
            wires = {
                nm: jax.lax.ppermute(w, axis_name, perm)
                for nm, w in wires.items()
            }
            if scale is not None:
                scale = jax.lax.ppermute(scale, axis_name, perm)
            local = pick(me - t - 2)
            nxt = {}
            for nm, w in wires.items():
                if fused_hop and dtype == "int8":
                    nxt[nm] = quant_acc(
                        w, scale, local[nm], interpret=fused_interpret
                    )
                else:
                    nxt[nm] = wire_uncast(w, scale, dtype) + local[nm]
            return nxt, None

        if n > 1 and hier is None:
            acc, _ = jax.lax.scan(hop, acc, jnp.arange(n - 1))

        # --- error-feedback injection + the one owner-side quantize:
        # the owner adds its residual chunk in full f32, quantizes the
        # finished sum once for the broadcast, and banks the exact
        # quantization error as the next step's residual (per-hop wire
        # errors above are the documented un-fed-back caveat) ---
        if error_feedback and residual_key is not None:
            # the residual arrives as the shard's slice in ORIGINAL dim
            # order (the shard_map in_specs slice dim chunk_dims[nm]);
            # acc is in chunk-front layout, so move the chunk dim up
            # before adding (identity when the chunk dim is 0)
            acc = {
                nm: a + jnp.moveaxis(
                    residuals[residual_key(nm)].astype(jnp.float32),
                    chunk_dims[nm], 0,
                )
                for nm, a in acc.items()
            }
        fscale = symmetric_scale(acc.values()) if dtype == "int8" else None
        fq = {nm: wire_cast(a, fscale, dtype)[0] for nm, a in acc.items()}
        deq = {nm: wire_uncast(w, fscale, dtype) for nm, w in fq.items()}
        if error_feedback and residual_key is not None:
            for nm in bucket:
                # bank the owner-side quantization error back in the
                # residual's original dim order (the out_specs layout)
                new_res[residual_key(nm)] = jnp.moveaxis(
                    acc[nm] - deq[nm], 0, chunk_dims[nm]
                )

        # --- allgather: the (int8 bytes, scale) pair rides n-1 more
        # hops; chunk c lands dequantized from identical bytes on every
        # shard, so the gathered value is bitwise ring-invariant.
        # zero_update params skip this: their scatter chunk IS the
        # update-layout shard ---
        gathered = [nm for nm in bucket if gather[nm]]
        if gathered and n > 1 and hier is not None:
            full = _hier_allgather(
                {nm: fq[nm] for nm in gathered}, fscale,
                p, g, K, M, pperm_intra, pperm_inter, dtype,
            )
            for nm in gathered:
                out[nm] = _unchunk(
                    full[nm], chunk_dims[nm], gs[nm].shape
                ).astype(gs[nm].dtype)
        elif gathered and n > 1:
            buf = {
                nm: jax.lax.dynamic_update_index_in_dim(
                    jnp.zeros_like(chunks[nm], dtype=jnp.float32),
                    deq[nm], me, axis=0,
                )
                for nm in gathered
            }

            def ghop(carry, t):
                buf, fq, fscale = carry
                fq = {
                    nm: jax.lax.ppermute(w, axis_name, perm)
                    for nm, w in fq.items()
                }
                if fscale is not None:
                    fscale = jax.lax.ppermute(fscale, axis_name, perm)
                idx = (me - t - 1) % n
                buf = {
                    nm: jax.lax.dynamic_update_index_in_dim(
                        b, wire_uncast(fq[nm], fscale, dtype), idx, axis=0
                    )
                    for nm, b in buf.items()
                }
                return (buf, fq, fscale), None

            (buf, _, _), _ = jax.lax.scan(
                ghop,
                (buf, {nm: fq[nm] for nm in gathered}, fscale),
                jnp.arange(n - 1),
            )
            for nm in gathered:
                out[nm] = _unchunk(
                    buf[nm], chunk_dims[nm], gs[nm].shape
                ).astype(gs[nm].dtype)
        else:
            for nm in gathered:  # n == 1: the chunk is the whole array
                out[nm] = _unchunk(
                    deq[nm][None], chunk_dims[nm], gs[nm].shape
                ).astype(gs[nm].dtype)
        for nm in bucket:
            if not gather[nm]:
                d = chunk_dims[nm]
                out[nm] = jnp.moveaxis(
                    deq[nm], 0, d
                ).reshape(
                    _shard_shape(gs[nm].shape, d, n)
                ).astype(gs[nm].dtype)
        if overlapped:
            token = out[bucket[0]]
    return out, new_res


# ---------------------------------------------------------------------------
# wire-bytes accounting (the deterministic arm of the stall gate)
# ---------------------------------------------------------------------------


def _wire_itemsize(dtype: str) -> int:
    return 1 if dtype == "int8" else 2


def modeled_wire_bytes(
    sizes: dict, buckets: tuple, ndata: int, *,
    dtype: str = "int8", gather: dict | None = None,
) -> int:
    """Per-device bytes the quantized ring moves across the data axis
    in one step — what each hop's ppermute operands add up to: the
    reduce phase sends n-1 (chunk + scale) payloads per bucket, the
    allgather n-1 more for gathered params (skipped under zero_update's
    scatter layout). ``sizes`` maps param name -> element count;
    ``tests`` pin this model against the step jaxpr's actual ppermute
    operand bytes (``ppermute_wire_bytes``), so the gated number cannot
    drift from what the program sends."""
    if ndata <= 1:
        return 0
    w = _wire_itemsize(dtype)
    scale_bytes = 4 if dtype == "int8" else 0
    total = 0
    for bucket in buckets:
        chunk = sum(sizes[nm] // ndata for nm in bucket)
        total += (ndata - 1) * (chunk * w + scale_bytes)  # reduce phase
        gchunk = sum(
            sizes[nm] // ndata
            for nm in bucket
            if gather is None or gather[nm]
        )
        if gchunk:
            total += (ndata - 1) * (gchunk * w + scale_bytes)  # allgather
    return total


def modeled_wire_bytes_levels(
    sizes: dict, buckets: tuple, ndata: int, *,
    intra_degree: int, dtype: str = "int8", gather: dict | None = None,
) -> dict:
    """Per-device, per-LEVEL bytes the hierarchical ring moves in one
    step: ``{"intra": ..., "inter": ..., "total": ...}``. Per bucket
    with chunk = sum(sizes)/n, K = intra_degree, M = n/K:

      intra reduce   (K-1) hops x an (M, chunk) f32 plane (no scale —
                     the fast hop is unquantized by design)
      inter reduce   (M-1) hops x (chunk wire bytes + one f32 scale)
      inter gather   (M-1) hops x (chunk wire bytes + scale), gathered
                     params only (zero_update skips them)
      intra gather   (K-1) hops x (M x chunk wire bytes + M scales) —
                     the collected byte plane rides whole

    ``total`` equals what ``ppermute_wire_bytes`` counts from the
    traced step; the split is what ``ppermute_wire_bytes_levels``
    attributes per level — both parities are CI-held. The scarce-hop
    win vs the flat ring is exact integer math: K*(M-1) <= K*M - 1 =
    n - 1 chunks cross the inter axis, so
    inter_bytes * intra_degree <= flat modeled_wire_bytes always."""
    if ndata <= 1:
        return {"intra": 0, "inter": 0, "total": 0}
    K = max(1, int(intra_degree))
    if ndata % K:
        raise ValueError(
            f"intra_degree {K} does not divide ndata {ndata}"
        )
    M = ndata // K
    w = _wire_itemsize(dtype)
    scale_bytes = 4 if dtype == "int8" else 0
    intra = inter = 0
    for bucket in buckets:
        chunk = sum(sizes[nm] // ndata for nm in bucket)
        intra += (K - 1) * M * chunk * 4
        inter += (M - 1) * (chunk * w + scale_bytes)
        gchunk = sum(
            sizes[nm] // ndata
            for nm in bucket
            if gather is None or gather[nm]
        )
        if gchunk:
            inter += (M - 1) * (gchunk * w + scale_bytes)
            intra += (K - 1) * (M * gchunk * w + M * scale_bytes)
    return {
        "intra": int(intra),
        "inter": int(inter),
        "total": int(intra + inter),
    }


def ppermute_wire_bytes_levels(
    jaxpr, *, intra_axis: str = "data", inter_axis: str = "data",
    intra_degree: int = 1,
) -> dict:
    """Per-level ppermute byte attribution for the hierarchical ring,
    counted from the traced program: ``{"intra": ..., "inter": ...}``.
    Distinct mesh axes classify each ppermute by its ``axis_name``;
    the factored single-axis form classifies by perm STRUCTURE — a
    within-group hop keeps ``src//K == dst//K``, the cross-group hop
    keeps ``src%K == dst%K`` (disjoint for K, M > 1; a perm matching
    neither — e.g. a flat ring's — raises, misuse is loud)."""
    import jax.core as jcore

    inner = getattr(jaxpr, "jaxpr", jaxpr)
    K = max(1, int(intra_degree))
    out = {"intra": 0, "inter": 0}

    def level(eqn) -> str:
        ax = eqn.params.get("axis_name")
        if isinstance(ax, (tuple, list)) and len(ax) == 1:
            ax = ax[0]
        if intra_axis != inter_axis:
            if ax == intra_axis:
                return "intra"
            if ax == inter_axis:
                return "inter"
            raise ValueError(
                f"ppermute over unexpected axis {ax!r} (expected "
                f"{intra_axis!r} or {inter_axis!r})"
            )
        pairs = [(int(s), int(d)) for s, d in eqn.params["perm"]]
        if all(s // K == d // K for s, d in pairs):
            return "intra"
        if all(s % K == d % K for s, d in pairs):
            return "inter"
        raise ValueError(
            f"ppermute perm {pairs!r} matches neither ring level "
            f"(intra_degree={K})"
        )

    def walk(jx, mult: int) -> None:
        for eqn in jx.eqns:
            if eqn.primitive.name == "ppermute":
                lv = level(eqn)
                for v in eqn.invars:
                    aval = v.aval
                    out[lv] += (
                        mult * int(aval.size) * jnp.dtype(aval.dtype).itemsize
                    )
            submult = mult
            if eqn.primitive.name == "scan":
                submult = mult * int(eqn.params.get("length", 1))
            for val in eqn.params.values():
                vals = val if isinstance(val, (list, tuple)) else (val,)
                for v in vals:
                    if isinstance(v, jcore.ClosedJaxpr):
                        walk(v.jaxpr, submult)
                    elif isinstance(v, jcore.Jaxpr):
                        walk(v, submult)

    walk(inner, 1)
    return out


def reference_wire_bytes(
    sizes: dict, ndata: int, *, scatter_only: bool = False
) -> int:
    """Per-device bytes the REFERENCE path's fp32 data-axis collective
    moves per step: a bandwidth-optimal ring all-reduce of E elements
    costs each device 2(n-1)/n * 4E bytes (reduce-scatter + allgather);
    under zero_update the allgather half moves to the param constraint
    and the grad collective is the reduce-scatter alone. This is the
    wire PR 8's quantize-around-the-psum could not shrink — the
    comparison baseline for ``wire_bytes_ratio``."""
    if ndata <= 1:
        return 0
    total_elems = sum(sizes.values())
    phases = 1 if scatter_only else 2
    return int(phases * (ndata - 1) * total_elems * 4 / ndata)


def ppermute_wire_bytes(jaxpr) -> int:
    """Sum the per-device bytes every ``ppermute`` in ``jaxpr`` moves,
    recursing into scans (multiplied by trip count), conds, and other
    sub-jaxprs — the measured half of the wire-bytes gate: counted from
    the program the step actually traces, not from the model. Accepts a
    ClosedJaxpr (``jax.make_jaxpr(...)(...)``) or a raw Jaxpr."""
    import jax.core as jcore

    inner = getattr(jaxpr, "jaxpr", jaxpr)

    def walk(jx, mult: int) -> int:
        total = 0
        for eqn in jx.eqns:
            if eqn.primitive.name == "ppermute":
                for v in eqn.invars:
                    aval = v.aval
                    total += (
                        mult * int(aval.size) * jnp.dtype(aval.dtype).itemsize
                    )
            submult = mult
            if eqn.primitive.name == "scan":
                submult = mult * int(eqn.params.get("length", 1))
            for val in eqn.params.values():
                vals = val if isinstance(val, (list, tuple)) else (val,)
                for v in vals:
                    if isinstance(v, jcore.ClosedJaxpr):
                        total += walk(v.jaxpr, submult)
                    elif isinstance(v, jcore.Jaxpr):
                        total += walk(v, submult)
        return total

    return walk(inner, 1)
