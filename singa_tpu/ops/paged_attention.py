"""Fused paged attention: a Pallas kernel that reads K/V blocks IN
PLACE through the block table.

The serving engine's reference attention path is gather -> attend ->
scatter: every decode tick, prefill chunk, and verify pass first
materializes a dense ``(slots, heads, cache_len, head_dim)`` view of
the paged pool PER LAYER (``Engine._gather``) before ``cache_attend``
runs — for a pool that is mostly shared prefix blocks and trash
padding, that materialization is the serving tier's main memory
traffic. This kernel removes it: the per-sequence block table rides in
as a scalar-prefetch operand, the grid's block dimension maps each
step straight at the sequence's next pool block (``BlockSpec`` index
map = a table lookup), and masked online-softmax statistics accumulate
across grid steps in VMEM scratch — flash-attention tiling over
block-granular K/V, the PagedAttention idea from vLLM-style serving.
No dense ``(S, H, C, D)`` intermediate ever exists.

Two entry points cover the engine's three call shapes:

``paged_attention``
    write-then-read — the decode tick ``(slots, 1)`` and chunked
    prefill ``(1, chunk)`` pattern: the fresh K/V were already
    scattered into the pool (padding/dead lanes to the trash block),
    so every attended entry lives behind the table and the mask is
    ``cache_attend``'s exactly: pool position <= query position.

``paged_attention_overlay``
    the speculative verify ``(slots, k+1)`` pattern: the pool must NOT
    be written before acceptance is known (KV rewind is "rejected
    positions were never written"), so the chunk's fresh K/V ride as a
    separate operand attended after the pool blocks — pool entries
    strictly BEFORE the chunk, chunk columns causally within it, the
    same split the reference path's gathered-view ``.at[].set``
    overlay encodes.

Masking discipline is inherited from ``cache_attend``: out-of-range
entries score ``NEG_INF`` (-1e30, finite — ``exp(m - m)`` stays 1 on
fully-masked rows) and their probabilities are zeroed explicitly, so
trash-block garbage and stale pool bytes never move an output bit. A
fully-masked query row emits zeros (the ``l == 0`` guard), where the
reference emits a uniform average of masked garbage — both are
garbage no caller reads (dead slots / padding queries), documented
rather than matched.

Parity with the reference is TOLERANCE-LEVEL, not bitwise: online
softmax reorders the reduction (blockwise running max/sum vs one
global softmax), the same cross-shape caveat PR 9 documents for XLA's
own re-tiled GEMM accumulation. Greedy token STREAMS are pinned
identical in tests — argmax decisions survive reduction-order ulps on
every workload the suite drives.

Bytes skipped, not just bytes reorganized: the causal bound clamps the
fetch index map so blocks past a sequence's live range re-fetch the
previous block id — Pallas skips the DMA when consecutive grid steps
map to the same block — and ``pl.when`` skips their compute.

``interpret=True`` (the default, and what CPU CI runs) executes the
kernel through the Pallas interpreter — plain XLA ops, so the
masking/online-softmax logic is tested on every run and the kernel
composes with GSPMD sharding (``serving_kv_shardings`` lays the pool's
heads over the model axis; the grid's ``S*H`` dimension partitions
with it). ``interpret=False`` compiles through Mosaic for a real TPU
and constrains the geometry (``fusable``): the K/V block tile must
align to the (8, 128) float32 register tile, i.e. ``kv_block_len`` a
multiple of 8 and ``head_dim`` a multiple of 128. netlint's KRN001 is
the static mirror of that rejection.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from .attention import NEG_INF

try:  # soft import, like ops/attention: CPU wheels ship pallas too,
    # but a missing extra must degrade to a loud config error, not an
    # import-time crash of the whole serve package
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False


#: hardware tile floor for the compiled (interpret=False) kernel: the
#: K/V block tile is (block_len, head_dim) float32 — sublanes of 8,
#: lanes of 128 (pallas_guide "Tiling Constraints")
_SUBLANE, _LANE = 8, 128


def fusable(block_len: int, head_dim: int, interpret: bool = True):
    """None if the kernel can serve this geometry, else the reason it
    cannot — the ONE tiling predicate the engine's runtime rejection
    and netlint's KRN001 both consult (a static mirror must never
    drift from the thing it mirrors)."""
    if not HAS_PALLAS:
        return "jax.experimental.pallas is unavailable in this environment"
    if block_len < 1:
        return f"kv_block_len {block_len} < 1"
    if interpret:
        return None  # the interpreter tiles anything
    if block_len % _SUBLANE:
        return (
            f"kv_block_len {block_len} not a multiple of {_SUBLANE} "
            f"(the fp32 sublane tile): the compiled kernel cannot tile "
            "the pool's block dimension"
        )
    if head_dim % _LANE:
        return (
            f"head_dim {head_dim} not a multiple of {_LANE} (the lane "
            "tile): the compiled kernel cannot tile the head dimension"
        )
    return None


def _kernel(
    tab_ref, nlive_ref,
    q_ref, k_ref, v_ref, pos_ref, *rest,
    block_len, n_heads, mb, per_query_pool_mask, has_chunk,
):
    """One (sequence*head, pool-block) program.

    Grid iterates the block dimension innermost and sequentially, so
    the flash (acc, m, l) statistics live in VMEM scratch across steps
    of the same (s, h) row: initialized at b == 0, folded per live
    block, normalized at b == mb - 1 (where the overlay chunk, if any,
    is folded last — online softmax is order-free).

    ``per_query_pool_mask``: True = write-then-read (pool position <=
    query position, cache_attend's mask); False = overlay (pool
    position strictly < the chunk's first position — every query sees
    every pool entry, the chunk columns carry [pos0, pos0+Q)).
    """
    if has_chunk:
        ck_ref, cv_ref, valid_ref, o_ref, acc, m, l = rest
    else:
        o_ref, acc, m, l = rest
    b = pl.program_id(1)
    s = pl.program_id(0) // n_heads
    q = q_ref[0, 0].astype(jnp.float32)            # (Q, D)
    pos = pos_ref[0]                               # (Q,) int32
    scale = 1.0 / math.sqrt(q.shape[-1])

    def fold(scores, mask, values):
        """One online-softmax update of the running (acc, m, l)."""
        scores = jnp.where(mask, scores, NEG_INF)
        m_prev = m[0]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(mask, jnp.exp(scores - m_new[:, None]), 0.0)
        acc[...] = acc[...] * alpha[:, None] + p @ values
        l[0] = l[0] * alpha + jnp.sum(p, axis=-1)
        m[0] = m_new

    @pl.when(b == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m[...] = jnp.full_like(m, NEG_INF)
        l[...] = jnp.zeros_like(l)

    @pl.when(b < nlive_ref[s])
    def _pool_block():
        k = k_ref[0, 0].astype(jnp.float32)        # (BL, D)
        v = v_ref[0, 0].astype(jnp.float32)
        kpos = b * block_len + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_len), 1
        )[0]
        if per_query_pool_mask:
            mask = kpos[None, :] <= pos[:, None]   # (Q, BL)
        else:
            mask = jnp.broadcast_to(
                kpos[None, :] < pos[0], (q.shape[0], block_len)
            )
        fold((q @ k.T) * scale, mask, v)

    @pl.when(b == mb - 1)
    def _finish():
        if has_chunk:
            ck = ck_ref[0, 0].astype(jnp.float32)  # (Q, D)
            cv = cv_ref[0, 0].astype(jnp.float32)
            vld = valid_ref[0] != 0
            # column jj holds the entry AT position pos[jj]: causal
            # within the chunk, padding/rejected columns masked out
            mask = (pos[None, :] <= pos[:, None]) & vld[None, :]
            fold((q @ ck.T) * scale, mask, cv)
        safe = jnp.where(l[0] == 0.0, 1.0, l[0])
        o_ref[0, 0] = (acc[...] / safe[:, None]).astype(o_ref.dtype)


def live_blocks(last_position, block_len, max_blocks):
    """Blocks the kernel's clamped grid actually fetches for one
    sequence whose last attended POOL position is ``last_position``
    (= ceil((last_position + 1) / block_len), clipped to the table
    width; -1 = no pool blocks). The ONE formula shared by the kernel
    (``_call``'s nlive) and the bytes model tools/attend_stall.py
    gates on — keeping the gated model in lockstep with what the
    kernel fetches. Works on scalars and arrays."""
    return jnp.clip((last_position + block_len) // block_len, 0, max_blocks)


def _call(q, k_pool, v_pool, tables, positions, chunk, interpret):
    s, h, nq, d = q.shape
    _, _, bl, _ = k_pool.shape
    mb = tables.shape[1]
    reason = fusable(bl, d, interpret=bool(interpret))
    if reason is not None:
        raise ValueError(f"paged_attention cannot run: {reason}")
    if chunk is None:
        # write-then-read: blocks must cover every query position
        live_to = jnp.max(positions, axis=1)
    else:
        # overlay: blocks cover strictly-before-the-chunk positions
        live_to = positions[:, 0] - 1
    nlive = live_blocks(live_to, bl, mb).astype(jnp.int32)
    tflat = tables.reshape(-1).astype(jnp.int32)

    def kmap(i, b, tref, nref):
        # clamp dead iterations at the last live block: the repeated
        # index lets the grid pipeline skip the re-fetch, pl.when
        # skips the compute — bytes saved, not just masked
        row = i // h
        bb = jnp.minimum(b, jnp.maximum(nref[row] - 1, 0))
        return (tref[row * mb + bb], i % h, 0, 0)

    qspec = pl.BlockSpec(
        (1, 1, nq, d), lambda i, b, t, n: (i // h, i % h, 0, 0)
    )
    rowspec = pl.BlockSpec((1, nq), lambda i, b, t, n: (i // h, 0))
    kvspec = pl.BlockSpec((1, 1, bl, d), kmap)
    in_specs = [qspec, kvspec, kvspec, rowspec]
    args = [q, k_pool, v_pool, positions.astype(jnp.int32)]
    if chunk is not None:
        ck, cv, valid = chunk
        in_specs += [qspec, qspec, rowspec]
        args += [ck, cv, valid.astype(jnp.int32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s * h, mb),
        in_specs=in_specs,
        out_specs=qspec,
        scratch_shapes=[
            pltpu.VMEM((nq, d), jnp.float32),      # acc
            pltpu.VMEM((1, nq), jnp.float32),      # m (running rowmax)
            pltpu.VMEM((1, nq), jnp.float32),      # l (running rowsum)
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _kernel,
            block_len=bl, n_heads=h, mb=mb,
            per_query_pool_mask=chunk is None,
            has_chunk=chunk is not None,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=bool(interpret),
    )(tflat, nlive, *args)


def modeled_bytes(
    n_seqs: int, n_heads: int, n_queries: int, head_dim: int,
    block_len: int, live_blocks_total: int, *, overlay: bool = False,
    itemsize: int = 4,
) -> int:
    """The kernel's modeled bytes accessed for one invocation — what a
    ``pl.CostEstimate`` declares on hardware: Q in, the LIVE K/V block
    tiles the clamped grid actually fetches (dead iterations re-fetch
    the previous block and Pallas skips the DMA), the overlay chunk if
    any, and O out. ``live_blocks_total`` is the sum over sequences of
    each one's live-block count (what ``_call`` computes as ``nlive``).

    This is the deterministic arm of tools/attend_stall.py's or-gate:
    the XLA cost analysis of the INTERPRETED kernel models the
    emulation's bookkeeping (whole-buffer loop carries), not the
    kernel's memory traffic, so the comparison against the reference
    path's dense gather uses this model instead — block-tile reads vs
    the ``(slots, H, cache_len, D)`` materialization."""
    qo = 2 * n_seqs * n_heads * n_queries * head_dim * itemsize
    kv = 2 * live_blocks_total * n_heads * block_len * head_dim * itemsize
    chunk = (
        2 * n_seqs * n_heads * n_queries * head_dim * itemsize
        if overlay else 0
    )
    return qo + kv + chunk


def paged_attention(
    q, k_pool, v_pool, tables, positions, *, interpret=True
):
    """Masked paged attention, write-then-read form.

    ``q`` (S, H, Q, D) queries at absolute ``positions`` (S, Q);
    ``k_pool``/``v_pool`` (n_blocks, H, block_len, D) pools already
    holding every attended entry (the fresh chunk was scattered in,
    padding to the trash block); ``tables`` (S, max_blocks) block ids.
    -> (S, H, Q, D), allclose to
    ``cache_attend(q, gather(k_pool), gather(v_pool), positions)``
    without the gather's dense intermediate.
    """
    return _call(q, k_pool, v_pool, tables, positions, None, interpret)


def paged_attention_overlay(
    q, k_pool, v_pool, tables, positions, chunk_k, chunk_v, chunk_valid,
    *, interpret=True,
):
    """Masked paged attention with the fresh chunk OVERLAID — the
    verify tick's no-pool-write form (KV rewind by construction).

    ``chunk_k``/``chunk_v`` (S, H, Q, D) hold the K/V of the chunk's
    own positions (column jj lives at ``positions[s, jj]``);
    ``chunk_valid`` (S, Q) marks real columns (draft-width/liveness
    padding rides masked). Pool entries are attended strictly BEFORE
    ``positions[:, 0]``; the pool is never written here.
    """
    return _call(
        q, k_pool, v_pool, tables, positions,
        (chunk_k, chunk_v, chunk_valid), interpret,
    )
