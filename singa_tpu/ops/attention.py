"""Attention ops: reference softmax attention, a Pallas TPU
flash-attention kernel, and the online-softmax block primitives that
ring attention (singa_tpu/parallel/ring.py) stitches across chips.

The reference system predates transformers — no attention op exists
anywhere in it (layer registry, src/worker/neuralnet.cc:13-33) — so this
is a singa-tpu extension making long-context models first-class. The
kernel follows the standard flash recipe: stream K/V blocks through VMEM,
keep running (max, sum, output) statistics per query block so the S x S
score matrix never materializes in HBM; the MXU sees (Bq, D) x (D, Bk)
and (Bq, Bk) x (Bk, D) matmuls.

All shapes are (batch, heads, seq, head_dim).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
) -> jnp.ndarray:
    """Reference dense attention: softmax(QK^T / sqrt(d)) V."""
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(mask, scores, NEG_INF)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), v)


# ---------------------------------------------------------------------
# online-softmax block math (shared by the Pallas kernel and ring
# attention): process one K/V block, fold into running (out, m, l)
# ---------------------------------------------------------------------


def block_attn_update(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    out: jnp.ndarray,
    m: jnp.ndarray,
    l: jnp.ndarray,
    *,
    q_offset=0,
    k_offset=0,
    causal: bool = False,
):
    """Fold one K/V block into running flash statistics.

    q (..., Sq, D); k/v (..., Sk, D); out (..., Sq, D) unnormalized;
    m/l (..., Sq) running rowmax / normalizer. Offsets give the global
    positions of the local blocks so causal masking works when the
    sequence is sharded (ring attention) or blocked (the kernel).
    Returns the updated (out, m, l).
    """
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k) / math.sqrt(d)
    if causal:
        qpos = q_offset + jnp.arange(q.shape[-2])
        kpos = k_offset + jnp.arange(k.shape[-2])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask, scores, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    if causal:
        p = jnp.where(mask, p, 0.0)
    out = out * alpha[..., None] + jnp.einsum("...qk,...kd->...qd", p, v)
    l = l * alpha + jnp.sum(p, axis=-1)
    return out, m_new, l


def block_attn_init(q_like: jnp.ndarray):
    """Zero-state (out, m, l) for block_attn_update accumulation.

    Derived arithmetically from ``q_like`` (not via zeros()) so that
    under shard_map the state inherits q's varying-axis type and can
    serve as a fori_loop carry (JAX's vma tracking)."""
    out = q_like * 0.0
    m = q_like[..., 0] * 0.0 + NEG_INF
    l = q_like[..., 0] * 0.0
    return out, m, l


def block_attn_finish(out, m, l):
    """Normalize accumulated output (fully-masked rows emit zeros)."""
    safe = jnp.where(l == 0.0, 1.0, l)
    return out / safe[..., None]


# ---------------------------------------------------------------------
# Pallas flash-attention kernel
# ---------------------------------------------------------------------

try:  # pallas import kept soft: CPU-only environments use interpret mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, block_k, seq_k):
    """One (batch*head, q-block) program: stream K/V blocks via VMEM.

    Refs are (1, Bq, D) for q/o and (1, Sk, D) for k/v; accumulation in
    fp32 registers/VMEM values (flash statistics never touch HBM).
    """
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    bq, d = q.shape
    out = jnp.zeros((bq, d), dtype=jnp.float32)
    m = jnp.full((bq,), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((bq,), dtype=jnp.float32)
    nblocks = seq_k // block_k
    q_offset = qi * bq

    def body(i, carry):
        out, m, l = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        return block_attn_update(
            q, k, v, out, m, l,
            q_offset=q_offset, k_offset=i * block_k, causal=causal,
        )

    if causal:
        # only K blocks at or below this q block's diagonal contribute
        nblocks_live = jax.lax.div(q_offset + bq - 1, block_k) + 1
        out, m, l = jax.lax.fori_loop(0, nblocks_live, body, (out, m, l))
    else:
        out, m, l = jax.lax.fori_loop(0, nblocks, body, (out, m, l))
    o_ref[0] = block_attn_finish(out, m, l).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q, k, v, causal=False, block_q=128, block_k=128, interpret=None
):
    """Flash attention: Pallas forward, reference-math backward.

    Falls back to the dense reference when Pallas is unavailable, the
    sequence does not tile evenly, or Sq != Sk. ``interpret=True`` runs
    the kernel in the Pallas interpreter (CPU testing); default
    auto-detects TPU.

    NOTE: the backward pass recomputes through the dense reference, so
    it materializes the S x S score matrix — training peak memory is the
    dense peak. For long-context *training*, shard the sequence with
    ring attention (singa_tpu/parallel/ring.py) instead; this kernel's
    win is forward/inference memory and fusion.
    """
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)


def _use_kernel(q, k, block_q, block_k, interpret):
    if not HAS_PALLAS:
        return False
    s = q.shape[2]
    if s != k.shape[2]:  # kernel assumes Sq == Sk; dense handles the rest
        return False
    if s % block_q or s % block_k:
        return False
    if interpret is None:
        return jax.default_backend() == "tpu"
    return True


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    if not _use_kernel(q, k, block_q, block_k, interpret):
        return attention(q, k, v, causal=causal)
    b, h, s, d = q.shape
    bh = b * h
    qf = q.reshape(bh, s, d)
    kf = k.reshape(bh, s, d)
    vf = v.reshape(bh, s, d)
    kernel = functools.partial(
        _flash_kernel, causal=causal, block_k=block_k, seq_k=s
    )
    out = pl.pallas_call(
        kernel,
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=bool(interpret),
    )(qf, kf, vf)
    return out.reshape(b, h, s, d)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    """Backward through the dense reference math (recompute): exact
    gradients, O(S^2) flops like any attention backward, no extra
    forward residuals kept in HBM."""
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: attention(q, k, v, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
