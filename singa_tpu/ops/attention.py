"""Attention ops: reference softmax attention, a Pallas TPU
flash-attention kernel, and the online-softmax block primitives that
ring attention (singa_tpu/parallel/ring.py) stitches across chips.

The reference system predates transformers — no attention op exists
anywhere in it (layer registry, src/worker/neuralnet.cc:13-33) — so this
is a singa-tpu extension making long-context models first-class. The
kernel follows the standard flash recipe: stream K/V blocks through VMEM,
keep running (max, sum, output) statistics per query block so the S x S
score matrix never materializes in HBM; the MXU sees (Bq, D) x (D, Bk)
and (Bq, Bk) x (Bk, D) matmuls.

All shapes are (batch, heads, seq, head_dim).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
) -> jnp.ndarray:
    """Reference dense attention: softmax(QK^T / sqrt(d)) V."""
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(mask, scores, NEG_INF)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), v)


# ---------------------------------------------------------------------
# online-softmax block math (shared by the Pallas kernel and ring
# attention): process one K/V block, fold into running (out, m, l)
# ---------------------------------------------------------------------


def block_attn_update(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    out: jnp.ndarray,
    m: jnp.ndarray,
    l: jnp.ndarray,
    *,
    q_offset=0,
    k_offset=0,
    causal: bool = False,
):
    """Fold one K/V block into running flash statistics.

    q (..., Sq, D); k/v (..., Sk, D); out (..., Sq, D) unnormalized;
    m/l (..., Sq) running rowmax / normalizer. Offsets give the global
    positions of the local blocks so causal masking works when the
    sequence is sharded (ring attention) or blocked (the kernel).
    Returns the updated (out, m, l).
    """
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k) / math.sqrt(d)
    if causal:
        qpos = q_offset + jnp.arange(q.shape[-2])
        kpos = k_offset + jnp.arange(k.shape[-2])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask, scores, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    if causal:
        p = jnp.where(mask, p, 0.0)
    out = out * alpha[..., None] + jnp.einsum("...qk,...kd->...qd", p, v)
    l = l * alpha + jnp.sum(p, axis=-1)
    return out, m_new, l


def block_attn_init(q_like: jnp.ndarray):
    """Zero-state (out, m, l) for block_attn_update accumulation.

    Derived arithmetically from ``q_like`` (not via zeros()) so that
    under shard_map the state inherits q's varying-axis type and can
    serve as a fori_loop carry (JAX's vma tracking)."""
    out = q_like * 0.0
    m = q_like[..., 0] * 0.0 + NEG_INF
    l = q_like[..., 0] * 0.0
    return out, m, l


def block_attn_finish(out, m, l):
    """Normalize accumulated output (fully-masked rows emit zeros)."""
    safe = jnp.where(l == 0.0, 1.0, l)
    return out / safe[..., None]


# ---------------------------------------------------------------------
# Pallas flash-attention kernel
# ---------------------------------------------------------------------

try:  # pallas import kept soft: CPU-only environments use interpret mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, block_k, seq_k
):
    """One (batch*head, q-block) program: stream K/V blocks via VMEM.

    Refs are (1, Bq, D) for q/o, (1, Sk, D) for k/v, (1, 1, Bq) for the
    log-sum-exp rows (the backward kernels' softmax residual; the lse
    array is laid out (BH, 1, S) so every block index is static and
    lane-aligned — Mosaic rejects dynamic sublane loads); accumulation
    in fp32 registers/VMEM values (flash statistics never touch HBM).
    """
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    bq, d = q.shape
    out = jnp.zeros((bq, d), dtype=jnp.float32)
    m = jnp.full((bq,), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((bq,), dtype=jnp.float32)
    nblocks = seq_k // block_k
    q_offset = qi * bq

    def body(i, carry):
        out, m, l = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        return block_attn_update(
            q, k, v, out, m, l,
            q_offset=q_offset, k_offset=i * block_k, causal=causal,
        )

    if causal:
        # only K blocks at or below this q block's diagonal contribute
        nblocks_live = jax.lax.div(q_offset + bq - 1, block_k) + 1
        out, m, l = jax.lax.fori_loop(0, nblocks_live, body, (out, m, l))
    else:
        out, m, l = jax.lax.fori_loop(0, nblocks, body, (out, m, l))
    o_ref[0] = block_attn_finish(out, m, l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(jnp.where(l == 0.0, 1.0, l))


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, causal, block_k, seq_k, scale,
):
    """dQ for one (batch*head, q-block): stream K/V blocks.

    FlashAttention backward recurrences: P = exp(S - lse),
    dS = P * (dO V^T - D) with D = rowsum(dO * O), dQ = dS K * scale.
    D arrives precomputed per row (like lse) so neither backward kernel
    redoes the rowsum.
    """
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]  # D, (Bq,)
    bq, d = q.shape
    q_offset = qi * bq
    dq = jnp.zeros((bq, d), dtype=jnp.float32)

    def body(i, dq):
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = (q @ k.T) * scale
        if causal:
            qpos = q_offset + jnp.arange(bq)
            kpos = i * block_k + jnp.arange(block_k)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        ds = p * (do @ v.T - delta[:, None])
        return dq + (ds @ k) * scale

    nblocks = seq_k // block_k
    if causal:
        nlive = jax.lax.div(q_offset + bq - 1, block_k) + 1
        dq = jax.lax.fori_loop(0, nlive, body, dq)
    else:
        dq = jax.lax.fori_loop(0, nblocks, body, dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, causal, block_q, seq_q, scale,
):
    """dK/dV for one (batch*head, k-block): stream Q/dO/O blocks.

    dV = P^T dO; dK = (P * (dO V^T - D))^T Q * scale.
    """
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    k_offset = ki * bk
    dk = jnp.zeros((bk, d), dtype=jnp.float32)
    dv = jnp.zeros((bk, d), dtype=jnp.float32)

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(j * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(j * block_q, block_q)]
        s = (q @ k.T) * scale
        if causal:
            qpos = j * block_q + jnp.arange(block_q)
            kpos = k_offset + jnp.arange(bk)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])  # (Bq, Bk)
        ds = p * (do @ v.T - delta[:, None])
        return dk + (ds.T @ q) * scale, dv + p.T @ do

    nblocks = seq_q // block_q
    if causal:
        # q blocks strictly above this k block's diagonal see only masked
        # scores; start at the first contributing block
        first = jax.lax.div(k_offset, block_q)
        dk, dv = jax.lax.fori_loop(first, nblocks, body, (dk, dv))
    else:
        dk, dv = jax.lax.fori_loop(0, nblocks, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _auto_block(s: int) -> int:
    """Largest supported block size dividing S. Measured on TPU v5e
    (S=8192, fwd+bwd): 512-blocks run 4.4x faster than 128-blocks —
    fewer grid programs, longer MXU-resident loops; VMEM per program
    stays small (a 512 x 64 fp32 tile is 128 KB)."""
    for b in (512, 256, 128):
        if s % b == 0:
            return b
    return 128  # _use_kernel rejects non-128-divisible S anyway


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q, k, v, causal=False, block_q=None, block_k=None, interpret=None
):
    """Flash attention: Pallas forward AND backward.

    Falls back to the dense reference when Pallas is unavailable, the
    sequence does not tile evenly, or Sq != Sk. ``interpret=True`` runs
    the kernels in the Pallas interpreter (CPU testing); default
    auto-detects TPU. Block sizes default to _auto_block(S); pass
    explicit values to override.

    Training memory is O(S) per head row (out + lse residuals) instead
    of the dense O(S^2): the backward recomputes P blockwise from
    (q, k, v, lse) inside its own kernels.
    """
    out, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out


def _use_kernel(q, k, block_q, block_k, interpret):
    if not HAS_PALLAS:
        return False
    s = q.shape[2]
    if s != k.shape[2]:  # kernel assumes Sq == Sk; dense handles the rest
        return False
    if s % block_q or s % block_k:
        return False
    if not interpret and block_q % 128:
        # on real hardware the lse lane dimension is blocked by block_q,
        # and Mosaic requires lane blocks in multiples of 128 (the
        # interpreter is laxer — tests exercise smaller geometries there)
        return False
    if interpret is None:
        return jax.default_backend() == "tpu"
    return True


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    """-> (out, lse | None); lse None means the dense fallback ran."""
    block_q = block_q or _auto_block(q.shape[2])
    block_k = block_k or _auto_block(k.shape[2])
    if not _use_kernel(q, k, block_q, block_k, interpret):
        return attention(q, k, v, causal=causal), None
    b, h, s, d = q.shape
    bh = b * h
    qf = q.reshape(bh, s, d)
    kf = k.reshape(bh, s, d)
    vf = v.reshape(bh, s, d)
    kernel = functools.partial(
        _flash_kernel, causal=causal, block_k=block_k, seq_k=s
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        interpret=bool(interpret),
    )(qf, kf, vf)
    return out.reshape(b, h, s, d), lse


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    # resolve auto blocks exactly as the forward did (same S)
    block_q = block_q or _auto_block(q.shape[2])
    block_k = block_k or _auto_block(k.shape[2])
    if lse is None:
        # dense fallback path: recompute through the reference math
        _, vjp = jax.vjp(
            lambda q, k, v: attention(q, k, v, causal=causal), q, k, v
        )
        return vjp(g)
    b, h, s, d = q.shape
    bh = b * h
    scale = 1.0 / math.sqrt(d)
    flat = lambda x: x.reshape(bh, s, d)  # noqa: E731
    # D = rowsum(dO * O), computed ONCE per row and fed to both kernels
    # laid out (BH, 1, S) like lse
    delta = jnp.sum(
        flat(g).astype(jnp.float32) * flat(out).astype(jnp.float32),
        axis=-1,
    )[:, None, :]
    args = (flat(q), flat(k), flat(v), flat(g), lse, delta)
    qspec = pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0))
    full = pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0))
    lse_blk = pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j))
    lse_full = pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0))
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            causal=causal, block_k=block_k, seq_k=s, scale=scale,
        ),
        grid=(bh, s // block_q),
        in_specs=[qspec, full, full, qspec, lse_blk, lse_blk],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=bool(interpret),
    )(*args)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel,
            causal=causal, block_q=block_q, seq_q=s, scale=scale,
        ),
        grid=(bh, s // block_k),
        in_specs=[full, kspec, kspec, full, lse_full, lse_full],
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=bool(interpret),
    )(*args)
    unflat = lambda x: x.reshape(b, h, s, d)  # noqa: E731
    return unflat(dq), unflat(dk), unflat(dv)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def auto_attention(q, k, v, *, causal=False, n_devices=1):
    """Pick dense vs the Pallas kernel by score-tensor footprint.

    Measured on TPU v5e (BASELINE.md r3): XLA's fused dense attention
    beats the kernel at every size where the S x S score tensor
    comfortably fits HBM, so the kernel's job is the long-context
    regime where dense would blow memory. The footprint estimate is
    per device (fwd+bwd fp32 scores / ``n_devices`` — pass the mesh
    size when batch/seq dims are sharded); the threshold is
    SINGA_TPU_DENSE_ATTN_MB (default 512).
    """
    import os

    b, h, s, _ = q.shape
    scores_mb = b * h * s * s * 4 * 2 / 1e6 / max(1, n_devices)
    if scores_mb <= float(os.environ.get("SINGA_TPU_DENSE_ATTN_MB", "512")):
        return attention(q, k, v, causal=causal)
    return flash_attention(q, k, v, causal)
